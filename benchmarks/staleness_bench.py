"""Staleness → convergence tradeoff for AsySG-InCon (VERDICT r4 next #4).

The algorithm's literature claim (Lian et al. 2015, cited by the
reference ``README.md:56-59``) is a CONVERGENCE statement: bounded
staleness costs convergence quality, bought back by asynchrony's
throughput. This bench makes the tradeoff an artifact:

1. **In-XLA curve** — ``AsyncPS`` sweeps staleness bounds {0,1,2,4,8}
   at MATCHED update counts (same rounds x workers, same lr, same data
   stream, uniform lag sampling up to the bound), recording the eval-
   loss trajectory against applied-update count. Sampling noise is
   averaged over ``--repeats`` seeds.
2. **Shm-fleet ground truth** — real multi-process runs (jitted
   workers, native shm PS) at two bounds, recording the measured
   arrival histogram, applied/dropped counts, and final loss: the
   validation points behind the in-XLA curve (the histogram replay
   test ties the two stacks together).
3. **The verdict** — per bound, the update-count inflation
   ``I(S) = updates_to_target(S) / updates_to_target(0)``. Asynchrony
   nets out ahead iff ``I(S) < measured async/sync throughput gain``
   (2.7x under the forced-straggler bench, ``async_bench.py``): the doc
   section states where that crossover lands.

Run: ``python benchmarks/staleness_bench.py [--rounds 80] [--repeats 3]
[--skip-fleet]`` (CPU-friendly; convergence semantics are backend-
independent).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

BOUNDS = [0, 1, 2, 4, 8]
WORKERS = 4
EVAL_EVERY = 5


def emit(**rec):
    rec.setdefault("backend", jax.default_backend())
    print(json.dumps(rec), flush=True)


def _problem():
    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem

    cfg = {
        "model": "mlp",
        "model_kw": {"features": (32, 4)},
        "in_shape": (8,),
        "batch": 64,
        "seed": 11,
        "optim": "sgd",
        "hyper": {"lr": 0.05},
    }
    _, params0, batch_fn, loss_fn = make_problem(cfg)
    return cfg, params0, batch_fn, loss_fn


def inxla_curve(rounds: int, repeats: int):
    """Mean eval-loss trajectory per staleness bound, matched updates."""
    from pytorch_ps_mpi_tpu.parallel.async_ps import AsyncPS

    cfg, params0, batch_fn, loss_fn = _problem()
    eval_batch = batch_fn(10**6, 10**6)
    eval_loss = jax.jit(loss_fn)

    curves = {}
    for bound in BOUNDS:
        trajs = []
        for rep in range(repeats):
            ps = AsyncPS(
                params0, loss_fn, num_workers=WORKERS, optim="sgd",
                lr=cfg["hyper"]["lr"], max_staleness=bound, seed=100 + rep,
            )
            traj = [(0, float(eval_loss(ps.params, eval_batch)))]
            for step in range(rounds):
                batches = jax.tree.map(
                    lambda *xs: jnp.stack(xs),
                    *[batch_fn(step, w) for w in range(WORKERS)],
                )
                ps.step(batches)
                if (step + 1) % EVAL_EVERY == 0:
                    traj.append(((step + 1) * WORKERS,
                                 float(eval_loss(ps.params, eval_batch))))
            trajs.append(traj)
        updates = [u for u, _ in trajs[0]]
        mean_losses = [
            float(np.mean([t[i][1] for t in trajs]))
            for i in range(len(trajs[0]))
        ]
        curves[bound] = (updates, mean_losses)
        emit(
            metric="staleness_convergence_inxla",
            staleness_bound=bound,
            workers=WORKERS,
            rounds=rounds,
            updates=rounds * WORKERS,
            repeats=repeats,
            lr=cfg["hyper"]["lr"],
            loss_initial=mean_losses[0],
            loss_final=mean_losses[-1],
            trajectory={str(u): round(l, 5)
                        for u, l in zip(updates, mean_losses)},
        )
    return curves


def updates_to_target(curves, target_frac=0.35):
    """Applied updates to reach target_frac * initial loss, per bound
    (linear interpolation on the mean trajectory; None if never)."""
    out = {}
    for bound, (updates, losses) in curves.items():
        target = target_frac * losses[0]
        hit = None
        for i in range(1, len(losses)):
            if losses[i] <= target:
                u0, u1 = updates[i - 1], updates[i]
                l0, l1 = losses[i - 1], losses[i]
                frac = (l0 - target) / max(l0 - l1, 1e-12)
                hit = u0 + frac * (u1 - u0)
                break
        out[bound] = hit
    return out


def fleet_points(bounds=(1, 4)):
    """Real shm-fleet runs: measured arrival staleness + final loss."""
    from pytorch_ps_mpi_tpu.parallel import dcn
    from pytorch_ps_mpi_tpu.parallel.async_train import serve, spawn_worker

    if dcn.get_lib() is None:
        emit(metric="staleness_convergence_fleet",
             skipped="native psqueue unavailable")
        return

    base_cfg, params0, _, _ = _problem()
    steps_per_worker = 40
    for bound in bounds:
        cfg = dict(base_cfg)
        cfg["worker_steps"] = {str(i): steps_per_worker
                               for i in range(WORKERS)}
        # one paced straggler induces real staleness spread
        cfg["slow_ms"] = {str(WORKERS - 1): 40.0}
        name = f"/psq_stale_{bound}_{os.getpid()}"
        server = dcn.ShmPSServer(
            name, num_workers=WORKERS, template=params0, max_staleness=bound,
        )
        try:
            procs = [spawn_worker(name, i, cfg) for i in range(WORKERS)]
            _, m = serve(
                server, cfg, total_grads=0,
                total_received=WORKERS * steps_per_worker, timeout=300.0,
            )
            for p in procs:
                assert p.wait(timeout=120) == 0
        finally:
            server.close()
        emit(
            metric="staleness_convergence_fleet",
            staleness_bound=bound,
            workers=WORKERS,
            pushed=WORKERS * steps_per_worker,
            applied=m["applied"],
            stale_drops=m.get("stale_drops"),
            loss_initial=m["loss_initial"],
            loss_final=m["loss_final"],
            staleness_hist=m["staleness_hist"],
        )


def main():
    # pin the platform HERE, not at import: tests import this module for
    # its pure helpers, and a collection-time config update would pin
    # the whole pytest process to CPU
    jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=80)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--target-fracs", type=str, default="0.35,0.1,0.03",
                    help="comma list: loss targets as fractions of the "
                         "initial loss (tighter target -> later in the "
                         "curve, where the staleness tax compounds)")
    ap.add_argument("--skip-fleet", action="store_true")
    args = ap.parse_args()

    curves = inxla_curve(args.rounds, args.repeats)
    # the throughput gain asynchrony buys (measured under a forced
    # straggler, benchmarks/async_bench.py + committed artifact)
    measured_gain = 2.7
    for frac in [float(f) for f in args.target_fracs.split(",")]:
        utt = updates_to_target(curves, frac)
        base = utt.get(0)
        inflation = {
            str(b): ((u / base) if (u and base) else None)
            for b, u in utt.items()
        }
        emit(
            metric="staleness_convergence_verdict",
            target_frac=frac,
            updates_to_target={str(b): (round(u, 1) if u else None)
                               for b, u in utt.items()},
            update_inflation_vs_sync={
                b: (round(i, 3) if i is not None else None)
                for b, i in inflation.items()
            },
            async_throughput_gain_measured=measured_gain,
            nets_out_ahead={
                b: (i is not None and i < measured_gain)
                for b, i in inflation.items()
            },
            note=(
                "asynchrony wins end-to-end at bound S iff its update-"
                "count inflation I(S) stays under the measured "
                "throughput gain (2.7x, forced-straggler A/B); I(S) "
                "from the mean in-XLA curve at matched update counts"
            ),
        )
    if not args.skip_fleet:
        fleet_points()


if __name__ == "__main__":
    main()
