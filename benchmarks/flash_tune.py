"""Flash-attention block-size sweep vs the XLA dense path.

Measures attention-only fwd+bwd device time (RTT-corrected scan, see
``utils/devtime.py``) for BERT-base head geometry (h=12, d=64) across
sequence lengths and (block_q, block_k) choices, against the fused-dense
einsum oracle XLA compiles for the same shapes. This is the measurement
behind the ``full``-attention dispatch policy in ``models/bert.py``: the
dense path owns short sequences (its matmuls batch perfectly on the MXU
and the O(L^2) scores still fit HBM traffic comfortably); the flash
kernel must EARN the dispatch at the crossover where score
materialization starts to dominate.

Run on a live TPU: ``python benchmarks/flash_tune.py [--quick]``.
One JSON line per (seq, config), then a summary line per seq.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.utils.backend_guard import (
    enable_compilation_cache,
    ensure_live_backend,
)

enable_compilation_cache()

from pytorch_ps_mpi_tpu.ops.attention_pallas import (
    _attention_jnp,
    flash_attention,
)
from pytorch_ps_mpi_tpu.utils.devtime import timed


def emit(**rec):
    rec.setdefault("backend", jax.default_backend())
    print(json.dumps(rec), flush=True)


def bench_one(fn, q, k, v, scan_k: int = 8, reps: int = 5) -> float:
    """Device seconds per fwd+bwd of ``fn(q, k, v) -> [b, l, h, d]``."""

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    grad = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))

    @jax.jit
    def scanned(q, k, v):
        def body(c, _):
            qq, kk, vv = c
            l, (dq, dk, dv) = grad(qq, kk, vv)
            # carry-dependence so XLA cannot hoist any round
            s = jnp.asarray(1e-30, qq.dtype) * l.astype(qq.dtype)
            return (qq + s * dq, kk + s * dk, vv + s * dv), None

        c, _ = jax.lax.scan(body, (q, k, v), None, length=scan_k)
        return c

    _, dev_s = timed(
        lambda: grad(q, k, v),
        lambda: scanned(q, k, v),
        scan_k, reps=reps,
    )
    return dev_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fewest configs: one block choice per seq")
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--head-dim", type=int, default=64)
    args = ap.parse_args()
    ensure_live_backend()

    h, d = args.heads, args.head_dim
    # token budget ~constant: b*l = 16k; s1024 sits ON the default-tier
    # boundary (_default_block_targets switches at 1024), so its row
    # decides the boundary rather than bracketing it
    cases = [(128, 128), (32, 512), (16, 1024), (8, 2048), (2, 8192)]
    blocks = [(128, 128)] if args.quick else [
        (128, 128), (128, 256), (256, 256), (128, 512), (256, 512),
        (512, 512), (256, 1024), (512, 1024),
    ]

    for b, l in cases:
        key = jax.random.key(l)
        mk = lambda i: jax.random.normal(
            jax.random.fold_in(key, i), (b, l, h, d), jnp.bfloat16
        )
        q, k, v = mk(0), mk(1), mk(2)

        # the dense path can legitimately die at the long end (f32 scores
        # b*h*l*l ~ 6.4 GB at s8192 + backward): that failure IS a data
        # point and must not cost the flash half of the sweep
        try:
            dense_s = bench_one(
                lambda q, k, v: _attention_jnp(
                    q, k, v, 0, 0, True, d ** -0.5)[0],
                q, k, v,
            )
            emit(metric="attn_fwd_bwd_ms", seq=l, batch=b,
                 config="dense-einsum", value=round(dense_s * 1e3, 3))
        except Exception as e:
            dense_s = None
            emit(metric="attn_fwd_bwd_ms", seq=l, batch=b,
                 config="dense-einsum",
                 error=f"{type(e).__name__}: {str(e)[:160]}")

        best = None
        for bq, bk in blocks:
            if bq > l or bk > l:
                continue
            fa = functools.partial(
                flash_attention, causal=True, block_q=bq, block_k=bk
            )
            try:
                dev_s = bench_one(fa, q, k, v)
            except Exception as e:
                emit(metric="attn_fwd_bwd_ms", seq=l, batch=b,
                     config=f"flash-{bq}x{bk}",
                     error=f"{type(e).__name__}: {str(e)[:160]}")
                continue
            emit(metric="attn_fwd_bwd_ms", seq=l, batch=b,
                 config=f"flash-{bq}x{bk}", value=round(dev_s * 1e3, 3))
            if best is None or dev_s < best[1]:
                best = ((bq, bk), dev_s)

        if best:
            # dense_s == 0.0 is a devtime zero-clamp (RTT jitter
            # swallowed the k-step signal): distinct from "errored"
            # (None), but comparing a finite flash time against 0.0 is
            # meaningless — report it indeterminate, never as a verdict
            if dense_s is None:
                verdict = "dense errored"
            elif dense_s == 0.0:
                verdict = "dense zero-clamped"
            else:
                verdict = bool(best[1] < dense_s)
            emit(metric="attn_crossover_summary", seq=l, batch=b,
                 dense_ms=(round(dense_s * 1e3, 3)
                           if dense_s is not None else None),
                 best_flash_ms=round(best[1] * 1e3, 3),
                 best_block=f"{best[0][0]}x{best[0][1]}",
                 flash_wins=verdict)


if __name__ == "__main__":
    main()
