"""Async PS behavior at WAN-like RTT (VERDICT r4 next #7).

Every multi-host artifact so far ran its sockets over bare loopback
(~0.05 ms RTT) — nothing like the reference's cluster deployment
(`/root/reference/README.md:19-23`). This kernel has no netem qdisc, so
the TCP transport carries its own WAN emulation (``native/tcpps.cpp``:
``TPS_WAN_RTT_MS`` / ``TPS_WAN_JITTER_MS``, worker-side propagation
delays). This bench sweeps RTT in {0, 5, 20, 50} ms (+ jitter at the
top point) over the REAL multi-process TCP fleet and records, per RTT:

- the async-vs-sync-barrier update-rate ratio under a forced straggler
  (does asynchrony's win survive when every message pays the WAN tax?);
- the measured arrival-staleness histogram (bounded staleness under
  latency: lags grow with RTT, the bound still caps them);
- the live wire compression ratio with the sign codec (server-counted
  bytes — DCN doctrine at WAN RTT).

Run: ``python benchmarks/wan_bench.py [--workers 4]`` (CPU protocol
bench; absolute rates are single-core-host numbers, the RATIOS and
histograms are the evidence).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from benchmarks.async_bench import run
from pytorch_ps_mpi_tpu.codecs import get_codec
from pytorch_ps_mpi_tpu.utils.backend_guard import enable_compilation_cache
from pytorch_ps_mpi_tpu.utils.devtime import safe_ratio

enable_compilation_cache()

RTTS_MS = [0.0, 5.0, 20.0, 50.0]


def emit(**rec):
    rec.setdefault(
        "backend",
        "cpu (protocol bench; ratios/histograms are the evidence)",
    )
    print(json.dumps(rec), flush=True)


def set_wan(rtt_ms: float, jitter_ms: float = 0.0) -> None:
    """Spawned workers inherit the parent env; the server side of the
    shim never sleeps, so setting it here affects exactly the worker-
    side propagation paths."""
    os.environ["TPS_WAN_RTT_MS"] = str(rtt_ms)
    os.environ["TPS_WAN_JITTER_MS"] = str(jitter_ms)


def sweep_point(rtt_ms: float, jitter_ms: float, w: int,
                fast_steps: int, slow_steps: int, slow_ms: float):
    set_wan(rtt_ms, jitter_ms)
    base = {
        "transport": "tcp",
        "model": "mlp",
        "model_kw": {"features": (64, 8)},
        "in_shape": (16,),
        "batch": 32,
        "seed": 5,
        "optim": "sgd",
        "hyper": {"lr": 0.02},
        "slow_ms": {str(w - 1): slow_ms},
        "open_timeout": 600.0,
        "push_timeout": 600.0,
    }

    sync_cfg = dict(base)
    sync_cfg["worker_steps"] = {str(i): slow_steps for i in range(w)}
    m_sync = run(sync_cfg, w, sync_barrier=True, total=w * slow_steps)

    async_cfg = dict(base)
    async_cfg["worker_steps"] = {
        **{str(i): fast_steps for i in range(w - 1)},
        str(w - 1): slow_steps,
    }
    m_async = run(
        async_cfg, w, sync_barrier=False,
        total=(w - 1) * fast_steps + slow_steps, max_staleness=8,
    )

    # sign-codec wire at this RTT (server-counted bytes). Workers read
    # the codec from cfg ("codec"/"codec_kw"); the server gets the
    # matching instance via run(code=...)
    codec_cfg = dict(async_cfg)
    codec_cfg["codec"] = "sign"
    codec_cfg["codec_kw"] = {"use_pallas": False}
    m_codec = run(
        codec_cfg, w, sync_barrier=False,
        total=(w - 1) * fast_steps + slow_steps, max_staleness=8,
        code=get_codec("sign", use_pallas=False),
    )

    ratio = round(
        safe_ratio(m_async["updates_per_sec"], m_sync["updates_per_sec"]), 2
    )
    emit(
        metric="wan_async_vs_sync_updates_per_sec_ratio",
        value=ratio,
        unit="x",
        rtt_ms=rtt_ms,
        jitter_ms=jitter_ms,
        workers=w,
        straggler_ms=slow_ms,
        async_updates_per_sec=round(m_async["updates_per_sec"], 3),
        sync_updates_per_sec=round(m_sync["updates_per_sec"], 3),
        async_loss_final=round(m_async["loss_final"], 4),
        sync_loss_final=round(m_sync["loss_final"], 4),
        async_staleness_hist=m_async["staleness_hist"],
        async_stale_drops=m_async.get("stale_drops"),
        sign_codec_compression_ratio=round(
            m_codec.get("compression_ratio", 0.0), 2),
        sign_codec_loss_final=round(m_codec["loss_final"], 4),
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--fast-steps", type=int, default=12)
    ap.add_argument("--slow-steps", type=int, default=3)
    ap.add_argument("--slow-ms", type=float, default=500.0)
    args = ap.parse_args()

    try:
        for rtt in RTTS_MS:
            sweep_point(rtt, 0.0, args.workers, args.fast_steps,
                        args.slow_steps, args.slow_ms)
        # jittered top point: WAN tails, not just mean latency
        sweep_point(RTTS_MS[-1], 20.0, args.workers, args.fast_steps,
                    args.slow_steps, args.slow_ms)
    finally:
        set_wan(0.0, 0.0)


if __name__ == "__main__":
    main()
