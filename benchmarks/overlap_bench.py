"""Compute/communication overlap proof (VERDICT r3 item 3).

The reference's signature design is overlapping encode/serialize/comm
with backprop via autograd hooks feeding a 200-thread pool
(``/root/reference/ps.py:65-66,85``). This framework's claim is that the
fused ``MPI_PS.step`` program lets XLA's scheduler do the same job —
this bench stops taking that on faith: it traces the fused ResNet-18
data-parallel train step and measures, from event timelines, how much of
the collective's execution interval actually rides under backward
compute (``utils.tracing.profiled_overlap``), A/B'ing XLA's
latency-hiding/concurrency scheduler flag.

Topology note: overlap needs collectives, and collectives need >1
device. The committed artifact therefore comes from the 8-device virtual
CPU mesh (real XLA collectives, the same fused program structure that
runs on a pod) — honestly labeled ``backend: cpu``. On a multi-chip TPU
mesh the same script measures the real ICI overlap; the single tunneled
v5e chip has no collective to trace (a 1-device psum is a no-op), which
the output records as ``skipped`` rather than faking a number.

Each flag config runs in a subprocess because XLA_FLAGS bind at backend
initialization.

Output: one JSON line per config + a final summary line; append to
``benchmarks/results/`` for the round artifact.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCH = 256


def child(scheduler_flag: str | None) -> None:
    """Trace one fused DP train step on this process's backend."""
    import jax

    if os.environ.get("OVERLAP_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from pytorch_ps_mpi_tpu import SGD
    from pytorch_ps_mpi_tpu.mesh import make_mesh
    from pytorch_ps_mpi_tpu.models import ResNet18
    from pytorch_ps_mpi_tpu.utils.tracing import profiled_overlap

    n_dev = len(jax.devices())
    rec = {
        "metric": "resnet18_dp_step_comm_compute_overlap",
        "backend": jax.default_backend(),
        "devices": n_dev,
        "batch": BATCH,
        "scheduler_flag": scheduler_flag or "default",
    }
    if n_dev < 2:
        rec["skipped"] = "single-device backend: no collective to trace"
        print(json.dumps(rec), flush=True)
        return

    model = ResNet18(num_classes=10, small_inputs=True)
    x = jax.random.normal(jax.random.key(1), (BATCH, 32, 32, 3))
    y = jax.random.randint(jax.random.key(2), (BATCH,), 0, 10)
    params = jax.jit(model.init)(jax.random.key(0), x[:1])

    def loss_fn(p, batch):
        xb, yb = batch
        logits = model.apply(p, xb)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))

    opt = SGD(params, mesh=make_mesh(), lr=0.01, momentum=0.9)
    opt.step(loss_fn=loss_fn, batch=(x, y))  # compile + warm
    _, split = profiled_overlap(
        lambda: opt.step(loss_fn=loss_fn, batch=(x, y))
    )
    rec.update({k: round(v, 6) if isinstance(v, float) else v
                for k, v in split.items()})
    print(json.dumps(rec), flush=True)


def main() -> None:
    force_cpu = os.environ.get("OVERLAP_FORCE_CPU")
    if "--live" in sys.argv:
        force_cpu = "0"  # watcher mode: measure the live accelerator mesh
    if force_cpu is None:
        # default: prove on the virtual 8-device CPU mesh (see module
        # docstring); pass --live to trace the accelerator backend instead
        force_cpu = "1"

    # A/B: XLA's latency-hiding scheduler. TPU and CPU spell it
    # differently; each config is (label, extra XLA_FLAGS).
    if force_cpu == "1":
        configs = [
            ("concurrency_sched_off",
             "--xla_cpu_enable_concurrency_optimized_scheduler=false"),
            ("concurrency_sched_on",
             "--xla_cpu_enable_concurrency_optimized_scheduler=true"),
        ]
        base_flags = "--xla_force_host_platform_device_count=8"
    else:
        configs = [
            ("latency_hiding_sched_off",
             "--xla_tpu_enable_latency_hiding_scheduler=false"),
            ("latency_hiding_sched_on",
             "--xla_tpu_enable_latency_hiding_scheduler=true"),
        ]
        base_flags = ""

    if force_cpu != "1":
        # Probe the live backend FLAGLESS first: (a) a single tunneled
        # chip has no collective to trace — skip honestly without ever
        # spawning the flag configs; (b) the axon plugin's flag parser
        # FATALS on unknown XLA_FLAGS (observed with the TPU scheduler
        # flag on the 2026-07-31 window), so flags must only reach
        # backends that survive a probe with them.
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                env=dict(os.environ), capture_output=True, text=True,
                timeout=180, cwd=REPO,
            )
            n_live = int(probe.stdout.strip().splitlines()[-1])
        except Exception:
            n_live = 0
        if n_live < 2:
            summary = {
                "metric": "comm_compute_overlap_summary",
                "value": None,
                "unit": "fraction of collective time under compute",
                "skipped": f"live backend has {n_live} device(s): no "
                           "collective to trace; the committed 8-device "
                           "CPU-mesh artifact carries the measurement",
            }
            print(json.dumps(summary), flush=True)
            return

    rows = []
    flag_known_unsupported = False
    for label, flag in configs:
        for with_flag in (True, False):
            if with_flag and flag_known_unsupported:
                if rows:
                    # one flagless (default-schedule) measurement already
                    # exists; a second identical run adds nothing
                    line = dict(rows[-1])
                    line["scheduler_flag"] = (
                        label + "_flag_unsupported_same_default_run")
                    break
                continue
            env = dict(os.environ)
            extra = (base_flags + " " + flag) if with_flag else base_flags
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " +
                                extra).strip()
            env["OVERLAP_FORCE_CPU"] = force_cpu
            try:
                out = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--child", label],
                    env=env, capture_output=True, text=True, timeout=1800,
                    cwd=REPO,
                )
            except subprocess.TimeoutExpired as e:
                line = {"metric": "resnet18_dp_step_comm_compute_overlap",
                        "scheduler_flag": label,
                        "error": f"timeout after 1800s: "
                                 f"{str(e.stdout or '')[-200:]}"}
                break
            if with_flag and "Unknown flag in XLA_FLAGS" in (out.stderr or ""):
                # this backend's parser rejects the scheduler flag —
                # rerun flagless so the config still yields a (default-
                # schedule) measurement, labeled as such
                label = label + "_flag_unsupported_ran_default"
                flag_known_unsupported = True
                continue
            line = None
            for ln in out.stdout.splitlines():
                try:
                    parsed = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict):  # stray parseable lines lose
                    line = parsed
            if line is None:
                line = {"metric": "resnet18_dp_step_comm_compute_overlap",
                        "scheduler_flag": label, "error": out.stderr[-500:]}
            else:
                line["scheduler_flag"] = label
            break
        print(json.dumps(line), flush=True)
        rows.append(line)

    ok = [r for r in rows if "overlap_frac" in r]
    summary = {
        "metric": "comm_compute_overlap_summary",
        "value": max((r["overlap_frac"] for r in ok), default=0.0),
        "unit": "fraction of collective time under compute",
        "configs": {r["scheduler_flag"]: r.get("overlap_frac") for r in rows},
        "note": (
            "fused MPI_PS.step traced with utils.tracing.profiled_overlap; "
            "overlap_frac = (comm intervals ∩ compute intervals) / comm, "
            "per-device mean. Proves/refutes the XLA-subsumes-the-"
            "reference's-hook-pool claim with timeline evidence."
        ),
    }
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    if "--child" in sys.argv:
        child(sys.argv[sys.argv.index("--child") + 1])
    else:
        main()
