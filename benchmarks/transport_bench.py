"""Transport overhead: the SAME async training job over shm vs TCP.

Identical worker fleets (real jitted compute, no stragglers), identical
server loop; the only variable is the wire — native shared memory
(``parallel/dcn.py``) vs native TCP over localhost (``parallel/tcp.py``).
The updates/sec ratio is the transport tax a single-host deployment pays
for choosing the cross-host-capable wire; across real hosts TCP is the
only option and the number to compare is the reference's MPI-over-
Ethernet throughput (which shipped pickled full-f32 buffers — here the
codec keeps payloads small either way).

Honest labeling: single-core host, absolute rates meaningless, the
RATIO between the two runs (same machine, same contention) is the
evidence.

Run: ``python benchmarks/transport_bench.py [--model mlp] [--workers 3]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # protocol bench: host only

from async_bench import run as run_job  # the one server-lifecycle harness
from pytorch_ps_mpi_tpu.utils.backend_guard import enable_compilation_cache
from pytorch_ps_mpi_tpu.utils.devtime import safe_ratio

enable_compilation_cache()


def run(transport: str, cfg, n_workers: int, total: int, code):
    cfg = dict(cfg)
    if transport == "tcp":
        cfg["transport"] = "tcp"
    else:
        cfg.pop("transport", None)
    return run_job(cfg, n_workers, sync_barrier=False, total=total, code=code)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="mlp")
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--codec", default="sign")
    ap.add_argument("--rounds", type=int, default=3,
                    help="alternating shm/tcp rounds; medians reported")
    args = ap.parse_args()

    cfg = {
        "model": args.model,
        "model_kw": ({"features": (64, 8)} if args.model == "mlp"
                     else {"num_classes": 10}),
        "in_shape": [8] if args.model == "mlp" else [32, 32, 3],
        "batch": args.batch,
        "seed": 0,
        "optim": "sgd",
        "hyper": {"lr": 0.02},
        "steps": args.steps,
        "open_timeout": 600.0,
        "push_timeout": 600.0,
    }
    if args.codec and args.codec != "identity":
        cfg["codec"] = args.codec
        cfg["codec_kw"] = ({"use_pallas": False} if args.codec == "sign"
                           else {})

    from statistics import median

    from pytorch_ps_mpi_tpu.codecs import get_codec

    code = (get_codec(args.codec, **cfg.get("codec_kw", {}))
            if "codec" in cfg else None)
    total = args.workers * args.steps

    # alternate A/B rounds so slow load drift hits both transports
    # equally; report medians (single runs swung 0.77x-1.06x on this
    # loaded 1-core host)
    shm_rates, tcp_rates = [], []
    m_shm = m_tcp = None
    for _ in range(args.rounds):
        m_shm = run("shm", cfg, args.workers, total, code)
        shm_rates.append(m_shm["updates_per_sec"])
        m_tcp = run("tcp", cfg, args.workers, total, code)
        tcp_rates.append(m_tcp["updates_per_sec"])

    ratio = round(safe_ratio(median(tcp_rates), median(shm_rates)), 3)
    print(json.dumps({
        "metric": f"{args.model}_async_tcp_vs_shm_updates_per_sec_ratio",
        "value": ratio,
        "unit": "x (1.0 = no transport tax)",
        "vs_baseline": ratio,
        "shm_updates_per_sec_median": round(median(shm_rates), 3),
        "tcp_updates_per_sec_median": round(median(tcp_rates), 3),
        "shm_rates": [round(r, 3) for r in shm_rates],
        "tcp_rates": [round(r, 3) for r in tcp_rates],
        "shm_loss_final": round(m_shm["loss_final"], 4),
        "tcp_loss_final": round(m_tcp["loss_final"], 4),
        "rounds": args.rounds,
        "workers": args.workers,
        "codec": args.codec,
        "wire_bytes_per_grad": m_tcp["wire_bytes_per_grad"],
        "backend": "cpu (protocol bench; single-core localhost, the "
                   "shm-vs-tcp RATIO is the evidence)",
    }, ensure_ascii=False), flush=True)


if __name__ == "__main__":
    main()
