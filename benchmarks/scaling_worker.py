"""SPMD worker for ``scaling_bench.py``'s cross-process (DCN) points —
NOT a pytest file. Launched N times via ``pytorch_ps_mpi_tpu.launch``
(N=2 with 4 local CPU devices each, N=4 with 2 each): the global
8-device mesh spans real process boundaries, so the gradient psum
crosses the distributed runtime the way a multi-host pod's DCN hop
would (loopback here; same code path).

Rank 0 prints one JSON row compatible with the in-process sweep's rows.
"""

import json
import sys
import time


def main():
    import jax
    import jax.numpy as jnp

    from pytorch_ps_mpi_tpu import SGD
    from pytorch_ps_mpi_tpu.mesh import make_mesh
    from pytorch_ps_mpi_tpu.models import ResNet18

    per_worker_batch = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 6

    world = len(jax.devices())
    mesh = make_mesh()
    model = ResNet18(num_classes=10, small_inputs=True)
    batch = per_worker_batch * world
    x = jax.random.normal(jax.random.key(1), (batch, 32, 32, 3))
    y = jax.random.randint(jax.random.key(2), (batch,), 0, 10)
    params = jax.jit(model.init)(jax.random.key(0), x[:1])

    from pytorch_ps_mpi_tpu.data import cross_entropy_loss

    def loss_fn(p, b):
        xb, yb = b
        return cross_entropy_loss(model.apply(p, xb), yb)

    opt = SGD(params, mesh=mesh, lr=0.05, average=True)
    opt.step(loss_fn=loss_fn, batch=(x, y))  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        opt.step(loss_fn=loss_fn, batch=(x, y))
    wall = time.perf_counter() - t0
    if jax.process_index() == 0:
        print("SCALING_ROW " + json.dumps({
            "workers": world,
            "processes": jax.process_count(),
            "per_worker_batch": per_worker_batch,
            "steps_per_sec": round(steps / wall, 4),
            "step_ms": round(1e3 * wall / steps, 2),
        }), flush=True)
    print(f"SCALING_WORKER_OK rank={jax.process_index()}", flush=True)


if __name__ == "__main__":
    main()
