"""BERT-base MLM — BASELINE config #5, the large-flat-gradient stress test.

Three sections, each honestly labeled with the backend that ran it:

1. Single-device BERT-base (~110M params) MLM train step (Adam), timed
   per-call and scan-amortized, with measured-FLOPs MFU — the headline
   model-compute number on whatever accelerator is live.
2. Distributed ``MPI_PS.step`` (fused grad → encode → psum → update) for
   the full 110M-param gradient on an 8-device mesh. On this machine the
   mesh is the virtual CPU one (the tunneled TPU is a single chip), so
   the number is *relative* evidence — it becomes a TPU number on
   multi-chip hardware with no code change.
3. The codec wire-bytes table for the ~110M-param flat gradient
   (the compression-curve evidence the reference's codings hook existed
   for, SURVEY §2.2), analytic from ``payload_bits`` plus measured
   encode+decode time on the live backend.

Run: ``python benchmarks/bert_bench.py [--seq 128] [--batch 16]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the virtual CPU mesh for section 2 must be configured before JAX inits
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.utils.backend_guard import (
    enable_compilation_cache,
    ensure_live_backend,
)

enable_compilation_cache()

from pytorch_ps_mpi_tpu.mesh import make_mesh
from pytorch_ps_mpi_tpu.models.bert import BertConfig, BertMLM, mlm_loss
from pytorch_ps_mpi_tpu.optim import AdamHyper, adam_update, init_adam_state
from pytorch_ps_mpi_tpu.utils.devtime import codec_roundtrip_seconds


def emit(**rec):
    rec.setdefault("backend", jax.default_backend())
    print(json.dumps(rec), flush=True)


def make_batch(key, batch, seq, vocab):
    tokens = jax.random.randint(key, (batch, seq), 0, vocab)
    targets = jax.random.randint(jax.random.fold_in(key, 1), (batch, seq), 0, vocab)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 2), 0.15, (batch, seq))
    return tokens, targets, mask


def single_device_bench(batch: int, seq: int, scan_k: int = 8, reps: int = 10,
                        attention: str = "full", f32_logits: bool = True):
    cfg = BertConfig(dtype=jnp.bfloat16, max_position=max(512, seq),
                     attention=attention, f32_logits=f32_logits)
    model = BertMLM(cfg)
    h = AdamHyper(lr=1e-4)

    def loss_fn(params, b):
        tokens, targets, mask = b
        return mlm_loss(model.apply(params, tokens), targets, mask)

    def train_step(params, state, b):
        loss, grads = jax.value_and_grad(loss_fn)(params, b)
        p2, s2 = adam_update(params, grads, state, h)
        return p2, s2, loss

    b = make_batch(jax.random.key(1), batch, seq, cfg.vocab_size)
    params = jax.jit(model.init)(jax.random.key(0), b[0][:1])
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    state = init_adam_state(params)

    # shared honest step-timing recipe (benchmarks/_stepbench.py)
    from benchmarks._stepbench import step_timing_fields

    fields = step_timing_fields(train_step, params, state, b,
                                scan_k=scan_k, reps=reps)
    suffix = "" if attention == "full" else f"_attn-{attention}"
    suffix += "" if f32_logits else "_bf16logits"
    emit(
        metric=(f"bert_base_{n_params//10**6}M_mlm_train_step"
                f"_b{batch}_s{seq}{suffix}"),
        attention=attention,
        **fields,
    )
    return n_params


def distributed_bench(seq: int, reps: int = 3):
    """Full 110M-param fused grad+aggregate+update on the 8-device CPU
    mesh (relative evidence; the same program IS the multi-chip path)."""
    from pytorch_ps_mpi_tpu import Adam

    cpu_devices = jax.devices("cpu")
    if len(cpu_devices) < 8:
        emit(metric="bert_base_mpi_ps_step_8dev", error="no 8-device cpu mesh")
        return
    mesh = make_mesh(devices=cpu_devices[:8])
    cfg = BertConfig(max_position=max(512, seq))
    model = BertMLM(cfg)
    cpu0 = cpu_devices[0]
    with jax.default_device(cpu0):
        b = make_batch(jax.random.key(1), 8, seq, cfg.vocab_size)
        params = jax.jit(model.init)(jax.random.key(0), b[0][:1])
    # rehost: single-device-committed arrays conflict with the 8-device
    # shard_map placement; numpy leaves let the jitted step shard freely
    params = jax.tree.map(np.asarray, params)
    b = jax.tree.map(np.asarray, b)
    opt = Adam(params, lr=1e-4, mesh=mesh)

    def loss_fn(p, batch):
        tokens, targets, mask = batch
        return mlm_loss(model.apply(p, tokens), targets, mask)

    opt.step(loss_fn=loss_fn, batch=b)  # compile
    times = []
    for _ in range(reps):
        loss, data = opt.step(loss_fn=loss_fn, batch=b)
        times.append(data["step_time"])
    emit(
        metric="bert_base_mpi_ps_fused_step_8dev_cpu_mesh",
        value=round(min(times) * 1e3, 1), unit="ms",
        note="relative evidence: virtual 8-device CPU mesh on one host; "
        "same XLA program runs unchanged on a real 8-chip mesh",
        per_device_batch=1, seq=seq,
    )


def codec_table(n_params: int, measure: bool):
    """Wire bytes for the flat ~110M-param gradient, per codec; on a live
    accelerator also the measured encode+decode device time."""
    from pytorch_ps_mpi_tpu.codecs import get_codec

    rows = []
    n = (n_params // 1024) * 1024
    shape = (n // 1024, 1024)
    for label, name, kw in [
        ("identity", "identity", {}),
        ("int8", "int8", {}),
        ("sign", "sign", {}),
        ("qsgd16", "qsgd", {"levels": 16}),
        ("terngrad", "terngrad", {}),
        ("topk-approx-1%", "topk", {"fraction": 0.01, "approx": True}),
        ("blocktopk-1%", "blocktopk", {"fraction": 0.01}),
        ("blocktopk-1%-4k", "blocktopk", {"fraction": 0.01,
                                          "block_size": 4096}),
        ("blocktopk8-1%", "blocktopk8", {"fraction": 0.01}),
        ("randomk-1%", "randomk", {"fraction": 0.01}),
        ("threshold", "threshold", {"tau": 2.0, "max_fraction": 0.05}),
        ("powersgd-r4", "powersgd", {"rank": 4}),
    ]:
        code = get_codec(name, **kw)
        wire = code.payload_bits(shape, jnp.float32) / 8
        row = {"codec": label, "wire_mb": round(wire / 1e6, 2),
               "ratio": round(n * 4 / wire, 1)}
        if measure:
            try:
                row["enc_dec_ms_device"] = round(
                    codec_roundtrip_seconds(code, shape, jnp.float32)
                    * 1e3, 2,
                )
            except Exception as e:  # one codec OOMing must not kill the table
                row["enc_dec_ms_device"] = f"error: {type(e).__name__}"
            if name in ("topk", "blocktopk", "blocktopk8", "randomk",
                        "threshold"):
                # encode/decode split for the sparse family: the
                # doctrine's claim that REASSEMBLY (gather/scatter),
                # not selection, is what loses on ICI must be a
                # measurement, not an inference (CODEC_ECONOMICS.md).
                # Own try: an encode-phase failure must not clobber a
                # roundtrip number that already succeeded.
                try:
                    row["enc_ms_device"] = round(
                        codec_roundtrip_seconds(
                            code, shape, jnp.float32, phase="encode")
                        * 1e3, 2,
                    )
                except Exception as e:
                    row["enc_ms_device"] = f"error: {type(e).__name__}"
        rows.append(row)
    emit(metric="bert_base_flat_grad_codec_wire_table", n_elems=n, rows=rows)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--skip-distributed", action="store_true")
    ap.add_argument("--codec-table-only", action="store_true",
                    help="run ONLY the 13-codec table (its own watcher "
                         "stage, so a timeout costs nothing else)")
    ap.add_argument("--skip-codec-table", action="store_true",
                    help="train lines only: the 13-codec 132M-element "
                         "table costs most of the stage's wall, and a "
                         "flaky window should spend itself on the A/B "
                         "train lines first")
    args = ap.parse_args()

    live = ensure_live_backend()
    on_tpu = live and jax.default_backend() == "tpu"
    # param count analytically (eval_shape — no HBM), so the codec table
    # can run first against an EMPTY device memory (a 132M-element qsgd
    # encode plus resident BERT+Adam state OOMed the 16 GB chip)
    cfg = BertConfig()
    n_params = sum(
        int(np.prod(s.shape))
        for s in jax.tree.leaves(
            jax.eval_shape(
                BertMLM(cfg).init, jax.random.key(0),
                jnp.ones((1, args.seq), jnp.int32),
            )
        )
    )
    # measuring 110M-elem encodes on the host CPU takes minutes; analytic
    # table only when the accelerator is down
    if not args.skip_codec_table:
        codec_table(n_params, measure=on_tpu)
    if args.codec_table_only:
        return
    if on_tpu:
        # flash-vs-einsum A/B at the headline shape, plus the long-seq
        # line the dense path collapses on (VERDICT r3 item 5). Each line
        # fails independently: a kernel lowering error must not cost the
        # einsum baseline (or vice versa) in a rare TPU window.
        # headline = 'full' (auto -> flash on TPU, bare metric name so the
        # series stays continuous across rounds and provenance recall
        # never keys the einsum baseline over it); einsum row suffixed.
        # s512/s2048 pairs chart where the O(L^2) dense path falls off
        # the flash curve; token budget is held ~constant per line
        for b, s, attn in [
            (args.batch, args.seq, "full"),
            (args.batch, args.seq, "einsum"),
            (max(args.batch // 4, 1), 512, "full"),
            (max(args.batch // 4, 1), 512, "einsum"),
            (1, 2048, "full"),
            (1, 2048, "einsum"),
            # MFU-push configs (VERDICT r4 next #5): bigger batches
            # amortize fixed per-step work — chart MFU vs batch at the
            # two headline sequence lengths
            (2 * args.batch, args.seq, "full"),
            (max(args.batch // 2, 1), 512, "full"),
        ]:
            try:
                single_device_bench(b, s, attention=attn)
            except Exception as e:
                emit(metric=f"bert_train_step_b{b}_s{s}", attention=attn,
                     error=f"{type(e).__name__}: {str(e)[:300]}")
        # bf16-logits lever on the biggest-logits config (b32 s128:
        # 500 MB of f32 [B,S,V] skipped) — the bert twin of the
        # gpt_bench A/B row
        try:
            single_device_bench(2 * args.batch, args.seq, f32_logits=False)
        except Exception as e:
            emit(metric=f"bert_train_step_b{2*args.batch}_s{args.seq}"
                        "_bf16logits",
                 error=f"{type(e).__name__}: {str(e)[:300]}")
    else:
        single_device_bench(4, 64)
    if not args.skip_distributed:
        distributed_bench(args.seq)


if __name__ == "__main__":
    main()
