"""Flat-bucket aggregation sweep: collective launch count + step ms over
``bucket_mb ∈ {0, 4, 16, 32}`` on resnet18- and bert-base-shaped trees.

Two measurements per (model, bucket_mb) point:

- **launch count** — collective ops in the LOWERED grads-only step
  (``bucketing.lowered_collective_counts``; abstract args, nothing is
  executed, so the 110M-param bert tree costs only a trace). This is the
  per-message-overhead quantity bucketing exists to shrink, and the
  number the acceptance gate checks (≥ 5× fewer launches at 16 MB on
  bert-base).
- **step ms** — wall time of the executed aggregation+update step, for
  the resnet18-size tree by default (the bert tree is ~3.5 GB of stacked
  per-worker gradients on a CPU host; pass ``--run-bert`` to time it on
  real hardware).

Emits one JSON line per point (benchmarks/results/ schema: metric /
value / unit / backend + sweep fields), table to stderr-free stdout so
the TPU watcher (``tools/tpu_watch.py``) can append records verbatim.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import subprocess

_ndev = 0
try:
    _out = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(len(jax.devices()))"],
        timeout=75, capture_output=True, text=True,
        env={k: v for k, v in os.environ.items() if k != "XLA_FLAGS"},
    )
    _ndev = int(_out.stdout.strip() or 0) if _out.returncode == 0 else 0
except (subprocess.TimeoutExpired, ValueError):
    _ndev = 0

import jax

if _ndev < 2:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.bucketing import lowered_collective_counts
from pytorch_ps_mpi_tpu.ps import SGD

SWEEP_MB = (0, 4, 16, 32)
REPS = 5


def resnet18_tree():
    """~60 tensors, ~11M f32 elements (the leader_bench shape census)."""
    n = 11_000_000
    sizes = [n // 60] * 59 + [n - 59 * (n // 60)]
    return {f"p{i}": jnp.zeros((s,), jnp.float32) for i, s in enumerate(sizes)}


def bert_base_tree():
    """BERT-base shape census: ~199 leaves, ~110M params, f32."""
    H, FF, L = 768, 3072, 12
    t = {
        "embed/word": (30522, H),
        "embed/pos": (512, H),
        "embed/type": (2, H),
        "embed/ln_g": (H,),
        "embed/ln_b": (H,),
    }
    for i in range(L):
        p = f"layer{i}"
        t.update({
            f"{p}/q_w": (H, H), f"{p}/q_b": (H,),
            f"{p}/k_w": (H, H), f"{p}/k_b": (H,),
            f"{p}/v_w": (H, H), f"{p}/v_b": (H,),
            f"{p}/attn_out_w": (H, H), f"{p}/attn_out_b": (H,),
            f"{p}/ln1_g": (H,), f"{p}/ln1_b": (H,),
            f"{p}/ffn_in_w": (H, FF), f"{p}/ffn_in_b": (FF,),
            f"{p}/ffn_out_w": (FF, H), f"{p}/ffn_out_b": (H,),
            f"{p}/ln2_g": (H,), f"{p}/ln2_b": (H,),
        })
    t.update({"pooler/w": (H, H), "pooler/b": (H,)})
    return {k: jnp.zeros(s, jnp.float32) for k, s in t.items()}


def grad_structs(params, world):
    return jax.tree.map(
        lambda p: jax.ShapeDtypeStruct((world,) + p.shape, p.dtype), params
    )


def launch_counts(params, world, bucket_mb, mode):
    opt = SGD(params, lr=0.1, mode=mode, bucket_mb=bucket_mb)
    fn = opt._build_grads_only_step()
    return lowered_collective_counts(
        fn, opt.params, opt.opt_state, opt.codec_state,
        grad_structs(params, world), jax.random.key(0),
    ), opt


def timed_step_ms(opt, grads):
    opt.step(grads=grads)  # compile + warm
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        opt.step(grads=grads)
        times.append(time.perf_counter() - t0)
    return float(np.median(times) * 1e3)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-bert", action="store_true",
                    help="also EXECUTE the bert-base step (3.5 GB of "
                         "stacked grads; launch counts are always taken)")
    ap.add_argument("--modes", default="allgather,leader")
    args = ap.parse_args()
    world = len(jax.devices())
    backend = jax.default_backend()
    modes = args.modes.split(",")

    for model, make, execute in (
        ("resnet18", resnet18_tree, True),
        ("bert-base", bert_base_tree, args.run_bert),
    ):
        params = make()
        n_leaves = len(jax.tree.leaves(params))
        grads = None
        if execute:
            grads = jax.tree.map(
                lambda p: jnp.zeros((world,) + p.shape, p.dtype), params
            )
        for mode in modes:
            base_total = None
            for mb in SWEEP_MB:
                counts, opt = launch_counts(params, world, mb, mode)
                if mb == 0:
                    base_total = counts["total"]
                row = {
                    "metric": f"{model}_bucket_agg_{mode}",
                    "unit": "collective launches",
                    "value": counts["total"],
                    "bucket_mb": mb,
                    "buckets": (opt._bucket_plan.num_buckets
                                if opt._bucket_plan else 0),
                    "leaves": n_leaves,
                    "all_reduce": counts["all_reduce"],
                    "all_gather": counts["all_gather"],
                    "reduce_scatter": counts["reduce_scatter"],
                    "launch_reduction_x": round(
                        base_total / counts["total"], 2
                    ) if base_total else 1.0,
                    "workers": world,
                    "backend": backend,
                }
                if execute:
                    row["step_ms"] = round(timed_step_ms(opt, grads), 3)
                print(json.dumps(row), flush=True)
                del opt


if __name__ == "__main__":
    main()
