"""GPT (decoder-only causal LM) train-step bench — the second model
family's TPU number.

BERT-base MLM stresses flat-gradient bandwidth (``bert_bench.py``); the
causal LM stresses the CAUSAL attention paths — on TPU, at s1024/s2048
the 'full' gate dispatches the flash kernel (seq >= FLASH_MIN_SEQ),
whose causal schedule skips fully-future tiles, so this line measures
that schedule inside a whole training step rather than a kernel
microbench. The einsum twin rides alongside at each shape as the A/B.

GPT-2-small geometry (12 layers, 12 heads, 768 hidden, 50257 vocab,
tied embeddings — ~124M params), Adam, bf16 compute. RTT-corrected
scan timing (``utils/devtime.py``).

Run on a live TPU: ``python benchmarks/gpt_bench.py``; off-TPU it runs
one tiny honest CPU line so the script always proves itself runnable.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.utils.backend_guard import (
    enable_compilation_cache,
    ensure_live_backend,
)

enable_compilation_cache()

from benchmarks._stepbench import step_timing_fields
from pytorch_ps_mpi_tpu.models.bert import BertConfig
from pytorch_ps_mpi_tpu.models.gpt import GPTLM, causal_lm_loss
from pytorch_ps_mpi_tpu.optim import AdamHyper, adam_update, init_adam_state


def emit(**rec):
    rec.setdefault("backend", jax.default_backend())
    print(json.dumps(rec), flush=True)


def _suffix(attention: str, remat: bool = False) -> str:
    s = "" if attention == "full" else f"_attn-{attention}"
    return s + ("_remat" if remat else "")


def metric_name(batch: int, seq: int, attention: str, cfg_kw: dict,
                remat: bool = False) -> str:
    """Metric name derived from the config alone (abstract eval, no
    device work), so error and success rows for one config share the
    same name and provenance's newest-per-metric recall sees one series.
    """
    cfg = BertConfig(causal=True, attention=attention, remat=remat,
                     max_position=max(1024, seq), **cfg_kw)
    model = GPTLM(cfg)
    shapes = jax.eval_shape(
        model.init, jax.random.key(0),
        jax.ShapeDtypeStruct((1, seq), jnp.int32))
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes))
    return (f"gpt2s_{n_params//10**6}M_lm_train_step_b{batch}_s{seq}"
            f"{_suffix(attention, remat)}")


def bench_line(batch: int, seq: int, attention: str, cfg_kw: dict,
               metric: str, remat: bool = False,
               scan_k: int = 8, reps: int = 5) -> None:
    cfg = BertConfig(causal=True, attention=attention, remat=remat,
                     max_position=max(1024, seq), **cfg_kw)
    model = GPTLM(cfg)
    h = AdamHyper(lr=1e-4)

    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0,
                                cfg.vocab_size)

    def loss_fn(params, toks):
        return causal_lm_loss(model.apply(params, toks), toks)

    def train_step(params, state, toks):
        loss, grads = jax.value_and_grad(loss_fn)(params, toks)
        p2, s2 = adam_update(params, grads, state, h)
        return p2, s2, loss

    params = jax.jit(model.init)(jax.random.key(0), tokens[:1])
    state = init_adam_state(params)
    fields = step_timing_fields(train_step, params, state, tokens,
                                scan_k=scan_k, reps=reps)
    emit(metric=metric, attention=attention, remat=remat, **fields)


def main() -> None:
    ensure_live_backend()
    if jax.default_backend() != "tpu":
        # honest CPU smoke: tiny geometry, one line, runnable anywhere
        tiny = dict(dtype=jnp.float32, num_layers=2, num_heads=2,
                    hidden_size=64, intermediate_size=128, vocab_size=512)
        bench_line(2, 64, "full", tiny,
                   metric=metric_name(2, 64, "full", tiny),
                   scan_k=4, reps=2)
        return
    gpt2s = dict(dtype=jnp.bfloat16, num_layers=12, num_heads=12,
                 hidden_size=768, intermediate_size=3072, vocab_size=50257)
    names = {}
    sweep = [
        (8, 1024, "full", False),   # flash via the gate (seq >= FLASH_MIN_SEQ)
        (8, 1024, "einsum", False),
        (1, 2048, "full", False),   # A/B pair at a batch dense can hold
        (1, 2048, "einsum", False),  # (b4 einsum keeps ~4.8 GB of residuals)
        (4, 2048, "full", False),   # flash-only capacity line
        # remat completes the b4 s2048 A/B dense can't otherwise hold:
        # per-layer rematerialization trades recompute for the O(L^2)
        # score residuals — the HBM lever measured inside a real step
        (4, 2048, "einsum", True),
        (4, 2048, "full", True),    # remat tax on the flash path, same shape
    ]
    for batch, seq, attn, remat in sweep:
        # name computed BEFORE the try: it re-runs the constructor/trace
        # steps, so calling it inside the handler would just re-raise
        # and kill the rest of the sweep with no error row
        name = metric_name(batch, seq, attn, gpt2s, remat)
        names[(batch, seq, attn, remat)] = name
        try:
            bench_line(batch, seq, attn, gpt2s, metric=name, remat=remat)
        except Exception as e:
            # same config-derived name as the success path, so one
            # config is one metric series whether the run lives or dies
            emit(metric=name, attention=attn, remat=remat,
                 error=f"{type(e).__name__}: {str(e)[:300]}")

    # scan_layers A/B at the headline shape: same math (loop-vs-scan
    # equality tested in tests/test_models.py), different compile
    # economics — compile_s is the column this pair exists for, and
    # step_ms_device answers whether lax.scan costs any runtime by
    # inhibiting inter-layer fusion. The persistent compilation cache
    # would turn compile_s into a cache-load time on warm reruns, so
    # the PAIR runs with the cache disabled — the loop twin recompiles
    # cold too (one extra compile is the price of an honest column).
    base = names[(8, 1024, "full", False)]
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        for kw, suffix in [
            (gpt2s, "_coldcompile"),
            (dict(gpt2s, scan_layers=True), "_scanlayers"),
        ]:
            try:
                bench_line(8, 1024, "full", kw, metric=base + suffix)
            except Exception as e:
                emit(metric=base + suffix, attention="full", remat=False,
                     error=f"{type(e).__name__}: {str(e)[:300]}")
    finally:
        jax.config.update("jax_enable_compilation_cache", True)

    # bf16-logits lever: f32_logits=False skips the 1.65 GB f32
    # materialization of the [b, s, V] logits at b8 s1024 (the loss
    # reduces in f32 through a fused upcast instead); A/B against the
    # einsum twin above under the same metric-series convention
    # (compilation cache back ON — this pair compares step time, not
    # compile time)
    name_bf = names[(8, 1024, "einsum", False)] + "_bf16logits"
    try:
        bench_line(8, 1024, "einsum", dict(gpt2s, f32_logits=False),
                   metric=name_bf)
    except Exception as e:
        emit(metric=name_bf, attention="einsum", remat=False,
             error=f"{type(e).__name__}: {str(e)[:300]}")


if __name__ == "__main__":
    main()
