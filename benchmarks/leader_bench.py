"""leader (ZeRO-1 sharded PS) vs allgather (replicated step) — Adam.

The measured case for the leader topology (VERDICT r1 item 3): both modes
move the same gradient bytes over the interconnect (psum and
reduce_scatter+all_gather are the same 2(w-1)/w·n volume), but leader
divides the *update* FLOPs and the optimizer-state memory by world size:

  allgather: every device steps the full model -> w·n update work total,
             3n floats of Adam state per device
  leader:    each device steps its 1/w flat shard -> n update work total,
             3n/w floats of Adam state per device

Run: ``python benchmarks/leader_bench.py [n_elems]`` (defaults ~11M on an
8-device virtual CPU mesh; on real hardware use the ambient devices).
Prints a table + one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# leader mode needs a multi-device mesh. Only pin to the 8-device virtual
# CPU mesh when the ambient backend can't form one (the single tunneled
# TPU chip today); a future multi-chip machine benches its real mesh
# (VERDICT r2 weak #6). The probe runs in a subprocess so a wedged tunnel
# can't hang us and the parent's backend choice stays open.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import subprocess

_ndev = 0
try:
    _out = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(len(jax.devices()))"],
        timeout=75, capture_output=True, text=True,
        env={k: v for k, v in os.environ.items() if k != "XLA_FLAGS"},
    )
    _ndev = int(_out.stdout.strip() or 0) if _out.returncode == 0 else 0
except (subprocess.TimeoutExpired, ValueError):
    _ndev = 0

import jax

if _ndev < 2:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu import Adam

REPS = 10


def bench_mode(mode: str, params, grads, code=None):
    """Returns (min step seconds, per-device state bytes, lowering)."""
    opt = Adam(params, lr=1e-3, mode=mode, code=code)
    opt.step(grads=grads)  # compile
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        _, data = opt.step(grads=grads)
        times.append(time.perf_counter() - t0)
    if code is not None:
        print(f"  [{mode}+{type(code).__name__}] lowering="
              f"{data['wire_lowering']} "
              f"wire_bytes/worker={data['wire_bytes_per_worker']/1e6:.1f}MB",
              flush=True)
    # per-device optimizer-state bytes: leader's moments are sharded over
    # the mesh, allgather's replicated on every device
    state_elems = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(tuple(opt.opt_state)[1:])
    )
    world = opt.size
    per_device_state = state_elems * 4 // (world if mode == "leader" else 1)
    return min(times), per_device_state, data["wire_lowering"]


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 11_000_000
    # ~60 tensors like ResNet-18's parameter list
    k = jax.random.key(0)
    sizes = [n // 60] * 59 + [n - 59 * (n // 60)]
    params = {f"p{i}": jnp.zeros((s,), jnp.float32) for i, s in enumerate(sizes)}
    world = len(jax.devices())
    grads = {
        name: jax.random.normal(jax.random.fold_in(k, i), (world,) + p.shape)
        for i, (name, p) in enumerate(params.items())
    }

    t_all, mem_all, _ = bench_mode("allgather", params, grads)
    t_lead, mem_lead, _ = bench_mode("leader", params, grads)

    # the round-4 lowering choice, measured: leader + a weakly-compressing
    # codec (int8, ratio 4 < world 8) takes dense_scatter — decode own
    # payload locally + reduce_scatter — instead of payload all-gather
    from pytorch_ps_mpi_tpu.codecs import get_codec

    # the lowering is world-size dependent (dense_scatter needs ratio <
    # world): key the JSON field by what actually compiled
    t_ds, _, ds_lowering = bench_mode("leader", params, grads,
                                      code=get_codec("int8"))
    t_ag_codec, _, _ = bench_mode("allgather", params, grads,
                                  code=get_codec("int8"))

    print(f"backend={jax.default_backend()} world={world} n={n}")
    print("| mode | step ms | adam state bytes/device |")
    print("|---|---|---|")
    print(f"| allgather | {t_all*1e3:.2f} | {mem_all/1e6:.1f} MB |")
    print(f"| leader    | {t_lead*1e3:.2f} | {mem_lead/1e6:.1f} MB |")
    print(
        json.dumps(
            {
                "metric": "adam_11M_leader_vs_allgather_step_speedup",
                "value": round(t_all / t_lead, 3),
                "unit": "x",
                "vs_baseline": round(t_all / t_lead, 3),
                "backend": jax.default_backend(),
                "leader_step_ms": round(t_lead * 1e3, 3),
                "allgather_step_ms": round(t_all * 1e3, 3),
                "state_bytes_per_device_ratio": mem_all / mem_lead,
                f"leader_int8_{ds_lowering}_step_ms": round(t_ds * 1e3, 3),
                "allgather_int8_step_ms": round(t_ag_codec * 1e3, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
