"""Convergence parity across gradient codecs — the compression-research
deliverable the reference's external ``codings`` hook existed to produce
(SURVEY §2.2): same model, same data stream, same step budget, one run
per codec through the full fused ``MPI_PS`` pipeline (encode →
collective → decode+sum → update), reporting each codec's final loss
next to its wire size. Identity is the no-compression control.

Runs on the 8-device virtual CPU mesh (convergence semantics are
backend-independent; the distributed program is the real one).

Run: ``python benchmarks/convergence_bench.py [--steps 150]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from pytorch_ps_mpi_tpu import SGD
from pytorch_ps_mpi_tpu.codecs import get_codec
from pytorch_ps_mpi_tpu.data import cross_entropy_loss, synthetic_images
from pytorch_ps_mpi_tpu.models import MLP

CODECS = [  # (label, name, kwargs, lr) — lr tuned per codec family:
    # sign-style steps are magnitude-free and need a cooler rate
    ("identity", "identity", {}, 0.1),
    ("bf16", "bf16", {}, 0.1),
    ("int8", "int8", {}, 0.1),
    ("qsgd16", "qsgd", {"levels": 16}, 0.1),
    ("terngrad", "terngrad", {}, 0.05),
    ("sign", "sign", {"use_pallas": False}, 0.02),
    ("topk-25%", "topk", {"fraction": 0.25}, 0.1),
    ("blocktopk-25%", "blocktopk", {"fraction": 0.25, "block_size": 128}, 0.1),
    ("blocktopk8-25%", "blocktopk8", {"fraction": 0.25, "block_size": 128}, 0.1),
    ("randomk-25%", "randomk", {"fraction": 0.25}, 0.1),
    ("powersgd-r4", "powersgd", {"rank": 4}, 0.1),
    ("threshold", "threshold", {"tau": 1.0, "max_fraction": 0.5}, 0.1),
    ("ef-topk-10%", "ef", {"inner_name": "topk", "fraction": 0.10}, 0.1),
]


def run_one(name, kw, lr, steps, batch=64):
    model = MLP(features=(128, 10))
    data = synthetic_images("mnist", batch)
    x0, _ = next(data)
    params = model.init(jax.random.key(0), x0)

    def loss_fn(p, b):
        x, y = b
        return cross_entropy_loss(model.apply(p, x), y)

    code = get_codec(name, **kw)
    opt = SGD(params, lr=lr, momentum=0.9, code=code, average=True)
    first = last = None
    for i, b in zip(range(steps), data):
        loss, _ = opt.step(loss_fn=loss_fn, batch=b)
        if i == 0:
            first = float(loss)
        last = float(loss)
    n = sum(int(jnp.size(x)) for x in jax.tree.leaves(params))
    wire = sum(
        code.payload_bits(p.shape, p.dtype) // 8
        for p in jax.tree.leaves(params)
    )
    # payload_bits is the STATIC wire size; for the ragged threshold
    # codec that is the max_fraction high-water cap, not the (varying)
    # true occupancy — label it so its row can't be read as "no
    # compression" next to codecs whose static size IS their real size
    ragged = name == "threshold"
    return first, last, n * 4 / wire, ragged


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    args = ap.parse_args()
    if args.steps < 1:
        ap.error("--steps must be >= 1")

    rows = []
    print("| codec | wire ratio (static) | first loss | final loss |")
    print("|---|---|---|---|")
    for label, name, kw, lr in CODECS:
        first, last, ratio, ragged = run_one(name, kw, lr, args.steps)
        note = " (cap; ragged true size varies)" if ragged else ""
        rows.append({"codec": label, "wire_ratio_static": round(ratio, 1),
                     "ragged": ragged,
                     "first_loss": round(first, 4),
                     "final_loss": round(last, 4)})
        print(f"| {label} | {ratio:.1f}x{note} | {first:.3f} | {last:.3f} |",
              flush=True)

    ident = next(r for r in rows if r["codec"] == "identity")
    print(json.dumps({
        "metric": f"codec_convergence_mlp_{args.steps}steps",
        "value": ident["final_loss"], "unit": "loss",
        "vs_baseline": 1.0,
        "backend": jax.default_backend(),
        "rows": rows,
        "note": "same job per codec through the full fused MPI_PS "
                "pipeline on the 8-device virtual CPU mesh",
    }))


if __name__ == "__main__":
    main()
