"""Serialization microbenchmark — the rebuild of the reference's
``Serialization-timing.ipynb`` (pickle vs msgpack dump/load + zlib levels
over array sizes, 100 repeats): here pickle vs this framework's typed
pytree pack (``utils/serialization.py``) vs the native wire codec
(``utils/native.py``), over the same n ∈ logspace sweep.

Prints a markdown table; run: ``python benchmarks/serialization_bench.py``.
"""

from __future__ import annotations

import os
import pickle
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from pytorch_ps_mpi_tpu.utils import native
from pytorch_ps_mpi_tpu.utils.serialization import pack_pytree, unpack_pytree

REPEATS = 100


def timeit(fn, repeats=REPEATS):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(repeats):
        fn()
    return (time.perf_counter() - t0) / repeats


def main():
    print("| n | pickle dump | pack_pytree | wirecodec compress | pickle B | packed B | compressed B |")
    print("|---|---|---|---|---|---|---|")
    for n in [10, 100, 1000, 10_000, 100_000]:
        rng = np.random.RandomState(0)
        arr = (rng.randn(n) * 1e-3).astype(np.float32)
        tree = {"grad": arr}

        t_pickle = timeit(lambda: pickle.dumps(arr))
        t_pack = timeit(lambda: pack_pytree(tree))
        buf, spec = pack_pytree(tree)
        t_comp = timeit(lambda: native.compress(buf, elem_size=4))

        pickled = pickle.dumps(arr)
        blob = native.compress(buf, elem_size=4)
        # round-trip checks
        assert np.array_equal(
            unpack_pytree(buf, spec, template=tree)["grad"], arr
        )
        assert native.decompress(blob) == buf
        print(
            f"| {n} | {t_pickle*1e6:.1f} µs | {t_pack*1e6:.1f} µs "
            f"| {t_comp*1e6:.1f} µs | {len(pickled)} | {len(buf)} | {len(blob)} |"
        )


if __name__ == "__main__":
    main()
