"""agg_bench: per-push server cost of homomorphic aggregation.

Measures what the serve loop actually pays per arriving push, on real
``CodecWire`` payload bytes, for both server-side disciplines:

- **decode-sum** (the pre-aggregation path): jitted decode of every
  push into a full f32 tree + tree-add — cost scales with the DECODED
  model size whatever the codec compressed the wire to;
- **aggregate** (``Codec.aggregate`` via ``WireAggregator``): each push
  folds into a compressed accumulator (host numpy, no jit dispatch, no
  tree rebuild) and ONE decode runs per round — cost scales with the
  PAYLOAD.

The bench runs each codec over a 1× and an 8× model (element count) and
asserts the headline claims:

- sparse codecs at fixed k (top-k / random-k): per-push aggregate cost
  is FLAT in model size (≤1.2× between 1× and 8×) — the payload does
  not grow, so neither does the fold;
- integer codecs (int8 / qsgd): the payload grows with the model, so
  absolute flatness is unavailable; the gate is RELATIVE — the per-push
  accumulate (the fold alone, what the serve loop pays per arrival;
  the finalize is the round's one decode, paid per publish) must beat
  a per-push decode. The full-round speedup (finalize included) is
  reported but not gated: at world=4 it amortizes a quarter of an O(n)
  decode into every push and sits at noise-level parity on CPU.

With the native fast path (``utils/native.fold_lib``, this PR) a third
discipline joins: the fold runs as ONE C++ SIMD dequant-multiply-add /
scatter pass (``wc_fold_*``) instead of numpy/jit. The bench A/Bs it
against the PR 8 fallback by flipping ``PS_NO_NATIVE`` between timed
runs and gates two claims at the 8×-model size:

- integer codecs: the native per-push fold is ≥ 2× faster than the
  fallback fold (measured steady-state, accumulator pages warm, async
  jit results blocked — the earlier unblocked timing under-reported
  the jit path by ~100×);
- every codec: the native PUBLISH path (finalize — the round's one
  decode, the serve loop's critical path at round completion) is ≥ 2×
  faster. For sparse codecs the per-push fold is µs-parity by design
  (payload-bound: a 2048-entry memcpy vs a 2048-entry scatter) — the
  native win is moving the whole concat + scatter-add + device fetch
  off the publish path (measured ~100× here).

Run: ``python benchmarks/agg_bench.py [--quick]``. Appends one row per
(codec, size, path) to ``benchmarks/results/agg_bench.jsonl`` plus a
summary row ``bench="agg_bench"`` for ``bench_gate --trajectory``
(wired as ``make agg-bench``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

RESULTS_DIR = os.path.join("benchmarks", "results")
TRAJECTORY = os.path.join(RESULTS_DIR, "agg_bench.jsonl")

WORLD = 4  # pushes per aggregation round


def _no_native() -> bool:
    from pytorch_ps_mpi_tpu.utils import native

    return native.fold_lib() is None


def make_template(n_elems: int) -> dict:
    """A few-leaf tree totalling ``n_elems`` (mixed leaf sizes, like a
    small model tower rather than one flat blob)."""
    big = int(n_elems * 0.75)
    mid = int(n_elems * 0.2)
    small = n_elems - big - mid
    return {
        "dense": np.zeros((big // 128, 128), np.float32),
        "proj": np.zeros((mid,), np.float32),
        "bias": np.zeros((small,), np.float32),
    }


def timed(fn, rounds: int, repeats: int = 5, best: bool = False) -> float:
    """Wall seconds per execution of fn: median-of-repeats by default,
    min-of-repeats (``best=True``) for the µs-scale fold timings where
    scheduler noise dominates the median."""
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(rounds):
            fn()
        samples.append((time.perf_counter() - t0) / rounds)
    return float(np.min(samples) if best else np.median(samples))


def _block(agg) -> None:
    """Force async (jitted-fallback) fold results to materialize so the
    timer sees compute, not dispatch."""
    import jax

    for acc in agg._accs:
        a = acc.get("acc") if isinstance(acc, dict) else None
        if a is not None and not isinstance(a, np.ndarray):
            jax.block_until_ready(a)


def bench_codec(name: str, kw: dict, n_elems: int, rounds: int) -> dict:
    import jax

    from pytorch_ps_mpi_tpu.codecs import get_codec
    from pytorch_ps_mpi_tpu.parallel.dcn import CodecWire

    template = make_template(n_elems)
    wire = CodecWire(get_codec(name, **kw), template, seed=0)
    assert wire.agg_supported, name
    rng = np.random.RandomState(0)
    grads = [
        jax.tree.map(
            lambda x: rng.randn(*x.shape).astype(np.float32), template)
        for _ in range(WORLD)
    ]
    bufs = [np.copy(wire.encode_to_bytes(g)) for g in grads]

    # warmup both paths (jit compiles, accumulator allocation)
    for b in bufs:
        wire.decode_from_bytes(b)
    agg = wire.agg_begin()
    for b in bufs:
        agg.fold(b)
    agg.finalize()

    def decode_round():
        ref = None
        for b in bufs:
            d = wire.decode_from_bytes(b)
            ref = d if ref is None else jax.tree.map(np.add, ref, d)
        return ref

    def agg_round():
        a = wire.agg_begin()
        for b in bufs:
            a.fold(b)
        return a.finalize()

    def fold_round():
        a = wire.agg_begin()
        for b in bufs:
            a.fold(b)
        _block(a)
        return a

    t_decode = timed(decode_round, rounds) / WORLD   # per push
    t_agg = timed(agg_round, rounds) / WORLD         # per push, finalize incl.
    # the per-push ACCUMULATE cost (what scales with arrival rate): the
    # fold alone — the finalize is the round's ONE decode, paid once per
    # published version however many pushes composed it (and necessarily
    # O(n): its output IS the dense gradient)
    t_fold = timed(fold_round, rounds * 4, repeats=7, best=True) / WORLD
    # steady-state per-push fold (accumulator allocated, pages warm, jit
    # compiled): M extra folds into one long-lived accumulator — the
    # serve loop's actual per-arrival cost once a round is underway
    warm = wire.agg_begin()
    for b in bufs:
        warm.fold(b)
    _block(warm)

    def fold_steady():
        for b in bufs:
            warm.fold(b)
        _block(warm)

    t_fold_steady = timed(fold_steady, max(rounds // 2, 3), repeats=7,
                          best=True) / WORLD
    # publish-path latency: the finalize alone, from last-fold to the
    # materialized dense gradient (the serve loop blocks on exactly this
    # at round completion)
    fin = []
    for _ in range(5):
        a = wire.agg_begin()
        for b in bufs:
            a.fold(b)
        _block(a)
        t0 = time.perf_counter()
        out = a.finalize()
        leaf = jax.tree.leaves(out)[0]
        if not isinstance(leaf, np.ndarray):
            jax.block_until_ready(leaf)
        fin.append(time.perf_counter() - t0)
    t_finalize = float(np.median(fin[1:]))
    payload_mb = wire.wire_bytes / (1 << 20)
    return {
        "codec": name, "codec_kw": kw, "n_elems": n_elems,
        "world": WORLD, "payload_bytes": wire.wire_bytes,
        "decode_per_push_ms": round(t_decode * 1e3, 4),
        "agg_per_push_ms": round(t_agg * 1e3, 4),
        "fold_per_push_ms": round(t_fold * 1e3, 4),
        "fold_steady_per_push_ms": round(t_fold_steady * 1e3, 4),
        "finalize_ms": round(t_finalize * 1e3, 4),
        "native": not _no_native(),
        "agg_per_payload_mb_ms": round(t_agg * 1e3 / max(payload_mb, 1e-9),
                                       4),
        "speedup_x": round(t_decode / max(t_agg, 1e-12), 2),
        "decodes_per_publish_agg": 1,
        "decodes_per_publish_decode_sum": WORLD,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smaller models / fewer rounds (CI smoke scale)")
    args = ap.parse_args(argv)

    base = 128_000 if args.quick else 1_000_000
    rounds = 10 if args.quick else 30
    sizes = {"1x": base, "8x": 8 * base}
    k = 2048
    codecs = [
        ("topk", {"k": k}, "sparse"),
        ("randomk", {"k": k}, "sparse"),
        ("int8", {}, "integer"),
        ("qsgd", {"levels": 16}, "integer"),
    ]

    os.makedirs(RESULTS_DIR, exist_ok=True)
    stamp = time.strftime("%Y-%m-%d")
    artifact = os.path.join(RESULTS_DIR, f"agg_bench_{stamp}.jsonl")
    native_ok = not _no_native()
    rows = {}
    rows_fb = {}
    with open(artifact, "a") as f:
        for name, kw, family in codecs:
            for label, n in sizes.items():
                row = bench_codec(name, kw, n, rounds)
                row.update({"bench": "agg_bench_row", "size": label,
                            "family": family, "quick": bool(args.quick),
                            "backend": "cpu", "t": time.time()})
                rows[(name, label)] = row
                print(json.dumps(row), flush=True)
                f.write(json.dumps(row) + "\n")
                if native_ok:
                    # A/B the PR 8 fallback fold: same bench, native
                    # force-disabled (fold_lib is read per agg_init, so
                    # the flip takes effect immediately)
                    os.environ["PS_NO_NATIVE"] = "1"
                    try:
                        fb = bench_codec(name, kw, n, max(rounds // 2, 3))
                    finally:
                        os.environ.pop("PS_NO_NATIVE", None)
                    fb.update({"bench": "agg_bench_row", "size": label,
                               "family": family, "quick": bool(args.quick),
                               "backend": "cpu", "t": time.time()})
                    rows_fb[(name, label)] = fb
                    print(json.dumps(fb), flush=True)
                    f.write(json.dumps(fb) + "\n")
                if (native_ok and name == "int8" and label == "8x"
                        and not args.quick):
                    # third leg, int8@8x only: the PR 8 PURE-NUMPY fold
                    # (fallback with the jit crossover pushed out of
                    # reach) — the discipline the ISSUE's ≥2× claim is
                    # against. The jitted leg above is the better PR 8
                    # path at this size and is gated separately as a
                    # no-regression floor.
                    from pytorch_ps_mpi_tpu.codecs import base as _cb

                    os.environ["PS_NO_NATIVE"] = "1"
                    jit_min = _cb.FOLD_JIT_MIN
                    _cb.FOLD_JIT_MIN = 1 << 62
                    try:
                        np_row = bench_codec(name, kw, n,
                                             max(rounds // 4, 2))
                    finally:
                        _cb.FOLD_JIT_MIN = jit_min
                        os.environ.pop("PS_NO_NATIVE", None)
                    np_row.update({"bench": "agg_bench_row", "size": label,
                                   "family": family, "fold_path": "numpy",
                                   "quick": bool(args.quick),
                                   "backend": "cpu", "t": time.time()})
                    rows_fb[(name, label, "numpy")] = np_row
                    print(json.dumps(np_row), flush=True)
                    f.write(json.dumps(np_row) + "\n")

    # -- gates -------------------------------------------------------------
    # flat-cost threshold, per path: the FALLBACK sparse fold is a pure
    # O(k) list append, so it gates tight (1.2x at measurement scale,
    # 1.5x under --quick where the fold sits at tens of µs and CI
    # scheduler noise alone moves the ratio ±30%). The NATIVE sparse
    # fold is an O(k) random-access scatter into the pooled dense
    # accumulator: its per-entry cost shifts with the cache tier the
    # accumulator lands in (512KB→L2, 4MB→L3, 32MB→DRAM — measured
    # 1.0–1.5x between sizes here), so it gates at 2.5x — loose enough
    # for cache-latency growth, tight enough to catch a reintroduced
    # O(n) term (the pre-pool zeros(n)-per-round bug showed 3–8x).
    flat_max_fb = 1.5 if args.quick else 1.2
    flat_max_native = 2.5
    failures = []
    sparse_ratios = []
    int_speedups = []
    int_fold_wins = []
    for name, kw, family in codecs:
        r1, r8 = rows[(name, "1x")], rows[(name, "8x")]
        if family == "sparse":
            # fixed-k payload: per-push ACCUMULATE (fold) cost flat in
            # model size — the payload doesn't grow, so neither may the
            # per-arrival work
            # gate BOTH paths when both were measured: the native rows
            # live in `rows`, the numpy-fallback A/B rows in `rows_fb`
            # — without the second check an O(n) term reintroduced
            # into the fallback fold would pass unnoticed (and inflate
            # the native speedup gates while doing so)
            pairs = [(r1, r8)]
            if (name, "1x") in rows_fb and (name, "8x") in rows_fb:
                pairs.append((rows_fb[(name, "1x")], rows_fb[(name, "8x")]))
            for p1, p8 in pairs:
                flat_max = (flat_max_native if p8.get("native")
                            else flat_max_fb)
                path = "native" if p8.get("native") else "fallback"
                ratio = p8["fold_per_push_ms"] / max(
                    p1["fold_per_push_ms"], 1e-9)
                sparse_ratios.append(ratio)
                print(f"{name} [{path}]: fold per-push "
                      f"1x={p1['fold_per_push_ms']}ms "
                      f"8x={p8['fold_per_push_ms']}ms ratio={ratio:.2f} "
                      f"(gate {flat_max}x)")
                if ratio > flat_max:
                    failures.append(
                        f"{name} [{path}]: per-push accumulate cost not "
                        f"flat ({ratio:.2f}x between 1x and 8x model, "
                        f"gate {flat_max}x)")
        else:
            # dense integer payload grows with the model: gate the
            # per-push ACCUMULATE (fold) against a per-push decode —
            # the serve loop pays the fold per arrival and the finalize
            # once per publish, so that is the cost that must win.
            # Under --quick the 1x model is 128k elements, where the
            # fold's jit dispatch (~0.1 ms) is the whole budget and the
            # ratio is noise — report it, gate only the 8x size there
            # (full scale gates both). The full-round speedup_x
            # (finalize included) is reported for the table, never
            # gated: it hovers at parity on CPU within timer noise.
            for r in (r1, r8):
                gated = not (args.quick and r is r1)
                fold_win = (r["decode_per_push_ms"]
                            / max(r["fold_per_push_ms"], 1e-9))
                if gated:
                    int_speedups.append(r["speedup_x"])
                    int_fold_wins.append(round(fold_win, 2))
                print(f"{name}@{r['size']}: decode "
                      f"{r['decode_per_push_ms']}ms vs fold "
                      f"{r['fold_per_push_ms']}ms ({fold_win:.2f}x), "
                      f"full-round agg {r['agg_per_push_ms']}ms "
                      f"({r['speedup_x']}x)"
                      + ("" if gated else " [reported, not gated]"))
                if gated and fold_win < 1.0:
                    failures.append(
                        f"{name}@{r['size']}: per-push accumulate "
                        f"slower than a per-push decode "
                        f"({fold_win:.2f}x)")
    # -- native fast-path gates (ISSUE 9) ---------------------------------
    # At the 8x model (8M elements full scale) the native C++ fold must
    # beat the PR 8 numpy/jit fallback >= 2x per push. int8 is gated on
    # the steady-state fold itself — both paths do O(n) dequant-MA work
    # per push, so the kernel either wins or it doesn't. top-k is gated
    # on the full-round per-push cost (fold + amortized finalize): the
    # sparse per-push fold is payload-bound µs on BOTH paths by design
    # (a 2048-entry memcpy vs a 2048-entry scatter), and the native win
    # is the publish path — finalize is a zero-copy view of the dense
    # accumulator instead of the fallback's O(n) concat + scatter-add.
    # Under --quick the gate relaxes to 1.5x: at 1M elements the fold is
    # sub-ms and scheduler noise alone moves the ratio ±30%.
    native_speedups = {}
    if native_ok:
        gate_min = 1.5 if args.quick else 2.0
        # int8: the ISSUE's ≥2× claim is against the PR 8 NUMPY fold
        # (multiply-into-temp + add — ~2× the memory traffic of the
        # fused C++ dequant-MA). The jitted crossover leg is ALSO the
        # PR 8 fallback at this size and is physics-parity with the
        # native kernel (both are one bandwidth-bound pass over q+acc
        # on the same cores), so it gates as a ≥0.9× no-regression
        # floor, not a speedup. --quick skips the numpy leg (only run
        # at 8M full scale) and gates the jit leg at 1.5× — at 1M the
        # jit path still pays dispatch + XLA temp overheads.
        nat = rows[("int8", "8x")]["fold_steady_per_push_ms"]
        fbj = rows_fb[("int8", "8x")]["fold_steady_per_push_ms"]
        sp_jit = fbj / max(nat, 1e-9)
        if args.quick:
            native_speedups["int8"] = round(sp_jit, 2)
            print(f"native int8@8x: fold_steady native={nat}ms "
                  f"jit-fallback={fbj}ms ({sp_jit:.2f}x, gate {gate_min}x)")
            if sp_jit < gate_min:
                failures.append(
                    f"int8@8x: native fold only {sp_jit:.2f}x over the "
                    f"fallback (gate {gate_min}x)")
        else:
            fbn = rows_fb[("int8", "8x", "numpy")]["fold_steady_per_push_ms"]
            sp_np = fbn / max(nat, 1e-9)
            native_speedups["int8"] = round(sp_np, 2)
            native_speedups["int8_vs_jit"] = round(sp_jit, 2)
            print(f"native int8@8x: fold_steady native={nat}ms "
                  f"numpy={fbn}ms ({sp_np:.2f}x, gate {gate_min}x) "
                  f"jit={fbj}ms ({sp_jit:.2f}x, floor 0.9x)")
            if sp_np < gate_min:
                failures.append(
                    f"int8@8x: native fold only {sp_np:.2f}x over the "
                    f"PR 8 numpy fold (gate {gate_min}x)")
            if sp_jit < 0.9:
                failures.append(
                    f"int8@8x: native fold regressed vs the jitted "
                    f"fallback ({sp_jit:.2f}x, floor 0.9x)")
        # top-k gates the full-round per-push cost at FULL scale only:
        # its native win is the O(n) work (fresh zeros + finalize
        # scatter) the fallback pays per round — at --quick's
        # 1M-element "8x" that is ~0.3 ms and the ctypes call overhead
        # of 12 sub-ms folds eats the margin.
        nat = rows[("topk", "8x")]["agg_per_push_ms"]
        fb = rows_fb[("topk", "8x")]["agg_per_push_ms"]
        sp = fb / max(nat, 1e-9)
        native_speedups["topk"] = round(sp, 2)
        gated = not args.quick
        print(f"native topk@8x: agg_per_push native={nat}ms "
              f"fallback={fb}ms ({sp:.2f}x"
              + (f", gate {gate_min}x)" if gated
                 else ") [reported, not gated under --quick]"))
        if gated and sp < gate_min:
            failures.append(
                f"topk@8x: native agg_per_push only {sp:.2f}x over "
                f"the numpy fallback (gate {gate_min}x)")
    else:
        print("native fast path unavailable (PS_NO_NATIVE or no "
              "toolchain) — A/B gates skipped, fallback rows only")

    if failures:
        for msg in failures:
            print(f"FAIL: {msg}")
        return 1

    summary = {
        "bench": "agg_bench", "t": time.time(),
        "sparse_flat_ratio": round(max(sparse_ratios), 3),
        "int_speedup_min_x": round(min(int_speedups), 2),
        "int_fold_win_min_x": round(min(int_fold_wins), 2),
        "topk_agg_per_push_ms": rows[("topk", "8x")]["agg_per_push_ms"],
        "int8_agg_per_push_ms": rows[("int8", "8x")]["agg_per_push_ms"],
        "quick": bool(args.quick),
    }
    if native_speedups:
        summary["native_fold_speedup_int8_x"] = native_speedups["int8"]
        summary["native_push_speedup_topk_x"] = native_speedups["topk"]
        if "int8_vs_jit" in native_speedups:
            summary["native_vs_jit_int8_x"] = native_speedups["int8_vs_jit"]
    with open(TRAJECTORY, "a") as f:
        f.write(json.dumps(summary) + "\n")
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
