"""agg_bench: per-push server cost of homomorphic aggregation.

Measures what the serve loop actually pays per arriving push, on real
``CodecWire`` payload bytes, for both server-side disciplines:

- **decode-sum** (the pre-aggregation path): jitted decode of every
  push into a full f32 tree + tree-add — cost scales with the DECODED
  model size whatever the codec compressed the wire to;
- **aggregate** (``Codec.aggregate`` via ``WireAggregator``): each push
  folds into a compressed accumulator (host numpy, no jit dispatch, no
  tree rebuild) and ONE decode runs per round — cost scales with the
  PAYLOAD.

The bench runs each codec over a 1× and an 8× model (element count) and
asserts the headline claims:

- sparse codecs at fixed k (top-k / random-k): per-push aggregate cost
  is FLAT in model size (≤1.2× between 1× and 8×) — the payload does
  not grow, so neither does the fold;
- integer codecs (int8 / qsgd): the payload grows with the model, so
  absolute flatness is unavailable; the gate is RELATIVE — the per-push
  accumulate (the fold alone, what the serve loop pays per arrival;
  the finalize is the round's one decode, paid per publish) must beat
  a per-push decode. The full-round speedup (finalize included) is
  reported but not gated: at world=4 it amortizes a quarter of an O(n)
  decode into every push and sits at noise-level parity on CPU.

Run: ``python benchmarks/agg_bench.py [--quick]``. Appends one row per
(codec, size, path) to ``benchmarks/results/agg_bench.jsonl`` plus a
summary row ``bench="agg_bench"`` for ``bench_gate --trajectory``
(wired as ``make agg-bench``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

RESULTS_DIR = os.path.join("benchmarks", "results")
TRAJECTORY = os.path.join(RESULTS_DIR, "agg_bench.jsonl")

WORLD = 4  # pushes per aggregation round


def make_template(n_elems: int) -> dict:
    """A few-leaf tree totalling ``n_elems`` (mixed leaf sizes, like a
    small model tower rather than one flat blob)."""
    big = int(n_elems * 0.75)
    mid = int(n_elems * 0.2)
    small = n_elems - big - mid
    return {
        "dense": np.zeros((big // 128, 128), np.float32),
        "proj": np.zeros((mid,), np.float32),
        "bias": np.zeros((small,), np.float32),
    }


def timed(fn, rounds: int, repeats: int = 5, best: bool = False) -> float:
    """Wall seconds per execution of fn: median-of-repeats by default,
    min-of-repeats (``best=True``) for the µs-scale fold timings where
    scheduler noise dominates the median."""
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(rounds):
            fn()
        samples.append((time.perf_counter() - t0) / rounds)
    return float(np.min(samples) if best else np.median(samples))


def bench_codec(name: str, kw: dict, n_elems: int, rounds: int) -> dict:
    import jax

    from pytorch_ps_mpi_tpu.codecs import get_codec
    from pytorch_ps_mpi_tpu.parallel.dcn import CodecWire

    template = make_template(n_elems)
    wire = CodecWire(get_codec(name, **kw), template, seed=0)
    assert wire.agg_supported, name
    rng = np.random.RandomState(0)
    grads = [
        jax.tree.map(
            lambda x: rng.randn(*x.shape).astype(np.float32), template)
        for _ in range(WORLD)
    ]
    bufs = [np.copy(wire.encode_to_bytes(g)) for g in grads]

    # warmup both paths (jit compiles, accumulator allocation)
    for b in bufs:
        wire.decode_from_bytes(b)
    agg = wire.agg_begin()
    for b in bufs:
        agg.fold(b)
    agg.finalize()

    def decode_round():
        ref = None
        for b in bufs:
            d = wire.decode_from_bytes(b)
            ref = d if ref is None else jax.tree.map(np.add, ref, d)
        return ref

    def agg_round():
        a = wire.agg_begin()
        for b in bufs:
            a.fold(b)
        return a.finalize()

    def fold_round():
        a = wire.agg_begin()
        for b in bufs:
            a.fold(b)
        return a

    t_decode = timed(decode_round, rounds) / WORLD   # per push
    t_agg = timed(agg_round, rounds) / WORLD         # per push, finalize incl.
    # the per-push ACCUMULATE cost (what scales with arrival rate): the
    # fold alone — the finalize is the round's ONE decode, paid once per
    # published version however many pushes composed it (and necessarily
    # O(n): its output IS the dense gradient)
    t_fold = timed(fold_round, rounds * 4, repeats=7, best=True) / WORLD
    payload_mb = wire.wire_bytes / (1 << 20)
    return {
        "codec": name, "codec_kw": kw, "n_elems": n_elems,
        "world": WORLD, "payload_bytes": wire.wire_bytes,
        "decode_per_push_ms": round(t_decode * 1e3, 4),
        "agg_per_push_ms": round(t_agg * 1e3, 4),
        "fold_per_push_ms": round(t_fold * 1e3, 4),
        "agg_per_payload_mb_ms": round(t_agg * 1e3 / max(payload_mb, 1e-9),
                                       4),
        "speedup_x": round(t_decode / max(t_agg, 1e-12), 2),
        "decodes_per_publish_agg": 1,
        "decodes_per_publish_decode_sum": WORLD,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smaller models / fewer rounds (CI smoke scale)")
    args = ap.parse_args(argv)

    base = 128_000 if args.quick else 1_000_000
    rounds = 10 if args.quick else 30
    sizes = {"1x": base, "8x": 8 * base}
    k = 2048
    codecs = [
        ("topk", {"k": k}, "sparse"),
        ("randomk", {"k": k}, "sparse"),
        ("int8", {}, "integer"),
        ("qsgd", {"levels": 16}, "integer"),
    ]

    os.makedirs(RESULTS_DIR, exist_ok=True)
    stamp = time.strftime("%Y-%m-%d")
    artifact = os.path.join(RESULTS_DIR, f"agg_bench_{stamp}.jsonl")
    rows = {}
    with open(artifact, "a") as f:
        for name, kw, family in codecs:
            for label, n in sizes.items():
                row = bench_codec(name, kw, n, rounds)
                row.update({"bench": "agg_bench_row", "size": label,
                            "family": family, "quick": bool(args.quick),
                            "backend": "cpu", "t": time.time()})
                rows[(name, label)] = row
                print(json.dumps(row), flush=True)
                f.write(json.dumps(row) + "\n")

    # -- gates -------------------------------------------------------------
    # flat-cost threshold: 1.2x at measurement scale; 1.5x under --quick,
    # where the fold sits at tens of µs and CI scheduler noise alone
    # moves the ratio ±30%
    flat_max = 1.5 if args.quick else 1.2
    failures = []
    sparse_ratios = []
    int_speedups = []
    int_fold_wins = []
    for name, kw, family in codecs:
        r1, r8 = rows[(name, "1x")], rows[(name, "8x")]
        if family == "sparse":
            # fixed-k payload: per-push ACCUMULATE (fold) cost flat in
            # model size — the payload doesn't grow, so neither may the
            # per-arrival work
            ratio = r8["fold_per_push_ms"] / max(r1["fold_per_push_ms"],
                                                 1e-9)
            sparse_ratios.append(ratio)
            print(f"{name}: fold per-push 1x={r1['fold_per_push_ms']}ms "
                  f"8x={r8['fold_per_push_ms']}ms ratio={ratio:.2f}")
            if ratio > flat_max:
                failures.append(
                    f"{name}: per-push accumulate cost not flat "
                    f"({ratio:.2f}x between 1x and 8x model, "
                    f"gate {flat_max}x)")
        else:
            # dense integer payload grows with the model: gate the
            # per-push ACCUMULATE (fold) against a per-push decode —
            # the serve loop pays the fold per arrival and the finalize
            # once per publish, so that is the cost that must win.
            # Under --quick the 1x model is 128k elements, where the
            # fold's jit dispatch (~0.1 ms) is the whole budget and the
            # ratio is noise — report it, gate only the 8x size there
            # (full scale gates both). The full-round speedup_x
            # (finalize included) is reported for the table, never
            # gated: it hovers at parity on CPU within timer noise.
            for r in (r1, r8):
                gated = not (args.quick and r is r1)
                fold_win = (r["decode_per_push_ms"]
                            / max(r["fold_per_push_ms"], 1e-9))
                if gated:
                    int_speedups.append(r["speedup_x"])
                    int_fold_wins.append(round(fold_win, 2))
                print(f"{name}@{r['size']}: decode "
                      f"{r['decode_per_push_ms']}ms vs fold "
                      f"{r['fold_per_push_ms']}ms ({fold_win:.2f}x), "
                      f"full-round agg {r['agg_per_push_ms']}ms "
                      f"({r['speedup_x']}x)"
                      + ("" if gated else " [reported, not gated]"))
                if gated and fold_win < 1.0:
                    failures.append(
                        f"{name}@{r['size']}: per-push accumulate "
                        f"slower than a per-push decode "
                        f"({fold_win:.2f}x)")
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}")
        return 1

    summary = {
        "bench": "agg_bench", "t": time.time(),
        "sparse_flat_ratio": round(max(sparse_ratios), 3),
        "int_speedup_min_x": round(min(int_speedups), 2),
        "int_fold_win_min_x": round(min(int_fold_wins), 2),
        "topk_agg_per_push_ms": rows[("topk", "8x")]["agg_per_push_ms"],
        "int8_agg_per_push_ms": rows[("int8", "8x")]["agg_per_push_ms"],
        "quick": bool(args.quick),
    }
    with open(TRAJECTORY, "a") as f:
        f.write(json.dumps(summary) + "\n")
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
