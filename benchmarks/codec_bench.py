"""Per-codec encode/decode latency + wire size on the current backend.

The compression-curve evidence the reference's codings research surface
existed to produce (SURVEY §2.2): for a ResNet-18-sized flat gradient,
each codec's on-device encode+decode time and bytes on the wire.

Run: ``python benchmarks/codec_bench.py [n_elems]``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.codecs import get_codec
from pytorch_ps_mpi_tpu.utils.backend_guard import ensure_live_backend

CODECS = [  # (label, registry name, kwargs)
    ("identity", "identity", {}),
    ("bf16", "bf16", {}),
    ("int8", "int8", {}),
    ("qsgd", "qsgd", {"levels": 16}),
    ("sign", "sign", {}),
    ("terngrad", "terngrad", {}),
    ("topk", "topk", {"fraction": 0.01}),
    ("topk-approx", "topk", {"fraction": 0.01, "approx": True}),
    # the VERDICT r3 item-2 answer: per-block selection, no global sort
    ("blocktopk", "blocktopk", {"fraction": 0.01}),
    ("blocktopk-4k", "blocktopk", {"fraction": 0.01, "block_size": 4096}),
    ("blocktopk8", "blocktopk8", {"fraction": 0.01}),
    ("randomk", "randomk", {"fraction": 0.01}),
    ("powersgd", "powersgd", {"rank": 4}),
    ("threshold", "threshold", {"tau": 2.0, "max_fraction": 0.05}),
]

# codecs with a Pallas kernel AND a jnp fallback: measure both and report
# the Mosaic-kernel speedup (VERDICT r1 item 2 — only meaningful on TPU,
# where use_pallas=True lowers through Mosaic instead of the interpreter)
PALLAS_PAIRS = ["int8", "sign"]


def bench_codec(name, kw, n, k=None):
    """Device ms for one encode+decode round-trip at ``n`` elements —
    the shared honest-timing recipe (``utils/devtime.py``: adaptive-k
    fused scan with a data dependence, scalar fetch, co-measured RTT
    floor subtracted; k sized so the signal clears the RTT jitter)."""
    from pytorch_ps_mpi_tpu.utils.devtime import codec_roundtrip_seconds

    code = get_codec(name, **kw)
    # powersgd wants a matrix view; give every codec the same 2-D shape
    shape = (n // 1024, 1024)
    t_rt = codec_roundtrip_seconds(code, shape, jnp.float32, k=k)
    bits = code.payload_bits(shape, jnp.float32)
    return t_rt, bits / 8


def main():
    live = ensure_live_backend()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 23  # ~8M ≈ ResNet18
    n = max(1024, (n // 1024) * 1024)  # benchmarked shape is (n//1024, 1024)
    raw_bytes = n * 4
    backend = jax.default_backend()
    # fallback is judged by the EXECUTING backend, not the probe (a
    # loaded host can time the probe out while the backend is live TPU)
    print(f"backend={backend} fallback={backend == 'cpu'} "
          f"probe_live={live} n={n} raw={raw_bytes/1e6:.1f} MB")
    print("| codec | enc+dec ms (device) | wire MB | ratio |")
    print("|---|---|---|---|")
    rows = []
    for label, name, kw in CODECS:
        t_rt, wire = bench_codec(name, kw, n)
        print(
            f"| {label} | {t_rt*1e3:.2f} "
            f"| {wire/1e6:.2f} | {raw_bytes/wire:.1f}x |"
        )
        rows.append({"codec": label, "enc_dec_ms_device": round(t_rt * 1e3, 2),
                     "wire_mb": round(wire / 1e6, 2),
                     "ratio": round(raw_bytes / wire, 1)})
    # same table as ONE machine-readable line: the watcher/extract_sweep
    # pipeline keeps JSON metric lines; markdown is for humans. Size tag
    # in binary units so distinct n never collide on one metric name
    # (provenance keeps only the newest record per name)
    size = f"{n//2**20}M" if n >= 2**20 else f"{n//2**10}K"
    print(json.dumps({"metric": f"codec_wire_table_{size}", "n_elems": n,
                      "rows": rows, "backend": backend}), flush=True)

    if backend == "tpu":
        print()
        print("| kernel | pallas enc+dec ms | jnp enc+dec ms | speedup |")
        print("|---|---|---|---|")
        from pytorch_ps_mpi_tpu.utils.devtime import safe_ratio

        for name in PALLAS_PAIRS:
            # the flaky tunnel can kill the TPU worker mid-row; partial
            # results already printed must survive (rc 0), matching the
            # watcher's write-incrementally design
            try:
                pt, _ = bench_codec(name, {"use_pallas": True}, n)
                jt, _ = bench_codec(name, {"use_pallas": False}, n)
            except Exception as e:
                msg = (str(e).splitlines() or [""])[0][:120]
                print(f"| {name} | (aborted: {type(e).__name__}: {msg}) "
                      f"| — | — |")
                break
            print(
                f"| {name} | {pt*1e3:.2f} | {jt*1e3:.2f} "
                f"| {safe_ratio(jt, pt):.2f}x |"
            )
    else:
        print("(pallas-vs-jnp column skipped: kernels run interpreted off-TPU)")


if __name__ == "__main__":
    main()
