"""Per-codec encode/decode latency + wire size on the current backend.

The compression-curve evidence the reference's codings research surface
existed to produce (SURVEY §2.2): for a ResNet-18-sized flat gradient,
each codec's on-device encode+decode time and bytes on the wire.

Run: ``python benchmarks/codec_bench.py [n_elems]``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.codecs import get_codec
from pytorch_ps_mpi_tpu.utils.backend_guard import ensure_live_backend

CODECS = [  # (label, registry name, kwargs)
    ("identity", "identity", {}),
    ("bf16", "bf16", {}),
    ("int8", "int8", {}),
    ("qsgd", "qsgd", {"levels": 16}),
    ("sign", "sign", {}),
    ("terngrad", "terngrad", {}),
    ("topk", "topk", {"fraction": 0.01}),
    ("topk-approx", "topk", {"fraction": 0.01, "approx": True}),
    # the VERDICT r3 item-2 answer: per-block selection, no global sort
    ("blocktopk", "blocktopk", {"fraction": 0.01}),
    ("blocktopk-4k", "blocktopk", {"fraction": 0.01, "block_size": 4096}),
    ("blocktopk8", "blocktopk8", {"fraction": 0.01}),
    ("randomk", "randomk", {"fraction": 0.01}),
    ("powersgd", "powersgd", {"rank": 4}),
    ("threshold", "threshold", {"tau": 2.0, "max_fraction": 0.05}),
]

# codecs with a Pallas kernel AND a jnp fallback: measure both and report
# the Mosaic-kernel speedup (VERDICT r1 item 2 — only meaningful on TPU,
# where use_pallas=True lowers through Mosaic instead of the interpreter).
# sign and terngrad use the PR 9 fused encode+pack kernels (one VMEM
# pass instead of reduce-then-pack).
PALLAS_PAIRS = ["int8", "sign", "terngrad"]


def bench_codec(name, kw, n, k=None):
    """Device ms for one encode+decode round-trip at ``n`` elements —
    the shared honest-timing recipe (``utils/devtime.py``: adaptive-k
    fused scan with a data dependence, scalar fetch, co-measured RTT
    floor subtracted; k sized so the signal clears the RTT jitter)."""
    from pytorch_ps_mpi_tpu.utils.devtime import codec_roundtrip_seconds

    code = get_codec(name, **kw)
    # powersgd wants a matrix view; give every codec the same 2-D shape
    shape = (n // 1024, 1024)
    t_rt = codec_roundtrip_seconds(code, shape, jnp.float32, k=k)
    bits = code.payload_bits(shape, jnp.float32)
    return t_rt, bits / 8


def main():
    live = ensure_live_backend()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 23  # ~8M ≈ ResNet18
    n = max(1024, (n // 1024) * 1024)  # benchmarked shape is (n//1024, 1024)
    raw_bytes = n * 4
    backend = jax.default_backend()
    # fallback is judged by the EXECUTING backend, not the probe (a
    # loaded host can time the probe out while the backend is live TPU)
    print(f"backend={backend} fallback={backend == 'cpu'} "
          f"probe_live={live} n={n} raw={raw_bytes/1e6:.1f} MB")
    print("| codec | enc+dec ms (device) | wire MB | ratio |")
    print("|---|---|---|---|")
    rows = []
    for label, name, kw in CODECS:
        t_rt, wire = bench_codec(name, kw, n)
        print(
            f"| {label} | {t_rt*1e3:.2f} "
            f"| {wire/1e6:.2f} | {raw_bytes/wire:.1f}x |"
        )
        rows.append({"codec": label, "enc_dec_ms_device": round(t_rt * 1e3, 2),
                     "wire_mb": round(wire / 1e6, 2),
                     "ratio": round(raw_bytes / wire, 1)})
    # same table as ONE machine-readable line: the watcher/extract_sweep
    # pipeline keeps JSON metric lines; markdown is for humans. Size tag
    # in binary units so distinct n never collide on one metric name
    # (provenance keeps only the newest record per name)
    size = f"{n//2**20}M" if n >= 2**20 else f"{n//2**10}K"
    print(json.dumps({"metric": f"codec_wire_table_{size}", "n_elems": n,
                      "rows": rows, "backend": backend}), flush=True)

    if backend == "tpu":
        print()
        print("| kernel | pallas enc+dec ms | jnp enc+dec ms | speedup |")
        print("|---|---|---|---|")
        from pytorch_ps_mpi_tpu.utils.devtime import safe_ratio

        for name in PALLAS_PAIRS:
            # the flaky tunnel can kill the TPU worker mid-row; partial
            # results already printed must survive (rc 0), matching the
            # watcher's write-incrementally design
            try:
                pt, _ = bench_codec(name, {"use_pallas": True}, n)
                jt, _ = bench_codec(name, {"use_pallas": False}, n)
            except Exception as e:
                msg = (str(e).splitlines() or [""])[0][:120]
                print(f"| {name} | (aborted: {type(e).__name__}: {msg}) "
                      f"| — | — |")
                break
            print(
                f"| {name} | {pt*1e3:.2f} | {jt*1e3:.2f} "
                f"| {safe_ratio(jt, pt):.2f}x |"
            )
        # ISSUE 9 acceptance: the exact top-k Pallas selection
        # (threshold refine + chunked compaction, no full sort) must
        # land within 2× of approx_max_k at this size — lax.top_k's
        # full bitonic sort measured 5.5× over approx at 8M on v5e.
        try:
            pe, _ = bench_codec("topk", {"fraction": 0.01, "pallas": True}, n)
            ax, _ = bench_codec("topk",
                                {"fraction": 0.01, "approx": True}, n)
            st, _ = bench_codec("topk", {"fraction": 0.01}, n)
            ratio = pe / max(ax, 1e-12)
            print(f"topk exact selection: pallas {pe*1e3:.2f} ms, "
                  f"lax.top_k sort {st*1e3:.2f} ms, approx "
                  f"{ax*1e3:.2f} ms — exact/approx {ratio:.2f}x (gate 2x)")
            if ratio > 2.0:
                print(f"FAIL: exact top-k Pallas encode {ratio:.1f}x over "
                      f"approx (gate 2x)")
                return 1
        except Exception as e:
            msg = (str(e).splitlines() or [""])[0][:120]
            print(f"topk exact-vs-approx aborted: {type(e).__name__}: {msg}")
    else:
        print("(pallas-vs-jnp column skipped: kernels run interpreted off-TPU)")

    # threshold-compaction regression guard (ISSUE 9): the unchunked
    # sort compaction ran a bitonic network of depth log²(n) over the
    # WHOLE tensor — 619–1613 ms on the BERT flat grad vs 17.8 ms for
    # exact top-k on the same bytes (tpu_v5e 2026-07-31 sweep), a 35×
    # gap that scaled superlinearly. The chunked compaction bounds the
    # sort width, so threshold enc+dec must now stay within one
    # moderate factor of top-k at any size: 10× — the TPU sort path
    # sits at ~2× post-fix and the CPU scatter path at ~5.5×, while
    # the pre-fix pathology measured 35× and grew with n.
    by = {r["codec"]: r["enc_dec_ms_device"] for r in rows}
    thr_ratio = by["threshold"] / max(by["topk"], 1e-9)
    print(f"threshold/topk enc+dec ratio: {thr_ratio:.2f}x (gate 10x)")
    if thr_ratio > 10.0:
        print(f"FAIL: threshold compaction regressed — enc+dec "
              f"{by['threshold']} ms is {thr_ratio:.1f}x top-k's "
              f"{by['topk']} ms (gate 10x; see ThresholdCodec chunk=)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
