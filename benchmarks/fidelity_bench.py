"""Codec-fidelity table: what each codec actually does to real gradients.

The offline half of the online `Codec.fidelity_probe` story (the online
half runs inside the async workers, ``telemetry/numerics.py``): one real
backprop of resnet18 / BERT, then every registered codec probed per leaf
and aggregated over the whole gradient tree — decode-after-encode
relative L2 error, cosine similarity, and achieved bits-per-parameter.
This is the measured form of the compression-utility trade the
reference's ``codings`` hook existed to explore: the sanity anchor
(identity ≈ 0 error), the cheap-cast tier (bf16/f16), and how much of
the gradient direction each aggressive codec actually keeps.

Run: ``python benchmarks/fidelity_bench.py [--models resnet18,bert]
[--bert-config base|tiny]``. Emits one JSON row per (model, codec) and
appends to ``benchmarks/results/fidelity_<model>.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs import get_codec

#: the probed configurations — the registry's full compression curve
CODECS = [
    ("identity", {}),
    ("bf16", {}),
    ("f16", {}),
    ("int8", {}),
    ("qsgd", {}),
    ("sign", {"use_pallas": False}),
    ("terngrad", {}),
    ("topk", {"fraction": 0.01}),
    ("randomk", {"fraction": 0.01}),
    ("threshold", {}),
    ("powersgd", {"rank": 2}),
    ("ef", {"inner_name": "topk", "fraction": 0.01}),
]


def tree_fidelity(code, grads, seed: int = 0) -> dict:
    """Per-leaf encode→decode roundtrip aggregated over the whole tree:
    rel error from total error energy, cosine from total dot/norms,
    bits/param from the summed payload bits — per-tensor codecs keep
    their per-leaf statistics, exactly as the train step runs them."""
    err2 = g2 = r2 = dot = 0.0
    bits = 0
    n = 0
    key = jax.random.key(seed)
    for i, g in enumerate(jax.tree.leaves(grads)):
        state = code.init_state(g.shape, g.dtype)
        rng = jax.random.fold_in(key, i) if code.needs_rng else None
        payload, _ = code.encode(g, state, rng)
        rec = code.decode(payload, g.shape, g.dtype)
        gf = np.asarray(g, np.float64).reshape(-1)
        rf = np.asarray(rec, np.float64).reshape(-1)
        err2 += float(np.sum((rf - gf) ** 2))
        g2 += float(np.sum(gf * gf))
        r2 += float(np.sum(rf * rf))
        dot += float(np.sum(rf * gf))
        bits += code.payload_bits(g.shape, g.dtype)
        n += gf.size
    return {
        "rel_error": (err2 / max(g2, 1e-300)) ** 0.5,
        "cosine": dot / max((r2 * g2) ** 0.5, 1e-300),
        "bits_per_param": bits / n,
        "params": n,
    }


def resnet18_grads(batch: int = 8):
    from pytorch_ps_mpi_tpu.models import ResNet18

    model = ResNet18(num_classes=10, small_inputs=True)
    k = jax.random.key(0)
    x = jax.random.normal(k, (batch, 32, 32, 3))
    y = jax.random.randint(jax.random.fold_in(k, 1), (batch,), 0, 10)
    params = model.init(jax.random.fold_in(k, 2), x[:1])

    def loss_fn(p, xx, yy):
        logits = model.apply(p, xx)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, yy[:, None], axis=1))

    return jax.jit(jax.grad(loss_fn))(params, x, y)


def bert_grads(config: str = "base", batch: int = 4, seq: int = 128):
    from pytorch_ps_mpi_tpu.models.bert import BertConfig, BertMLM, mlm_loss

    cfg = (BertConfig.base() if config == "base" else BertConfig.tiny())
    model = BertMLM(cfg)
    k = jax.random.key(0)
    tokens = jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.fold_in(k, 1), (batch, seq), 0,
                                 cfg.vocab_size)
    mask = jax.random.bernoulli(jax.random.fold_in(k, 2), 0.15, (batch, seq))
    params = model.init(jax.random.fold_in(k, 3), tokens[:1])

    def loss_fn(p):
        return mlm_loss(model.apply(p, tokens), targets, mask)

    return jax.jit(jax.grad(loss_fn))(params)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", default="resnet18,bert")
    ap.add_argument("--bert-config", default="base",
                    choices=["base", "tiny"])
    args = ap.parse_args(argv)
    os.makedirs("benchmarks/results", exist_ok=True)
    for model in args.models.split(","):
        if model == "resnet18":
            grads, label = resnet18_grads(), "resnet18"
        elif model == "bert":
            grads = bert_grads(args.bert_config)
            label = f"bert-{args.bert_config}"
        else:
            raise SystemExit(f"unknown model {model!r}")
        out = f"benchmarks/results/fidelity_{label}.jsonl"
        with open(out, "a") as f:
            for name, kw in CODECS:
                row = {"bench": "codec_fidelity", "model": label,
                       "codec": name, "codec_kw": kw,
                       "backend": jax.default_backend()}
                row.update(tree_fidelity(get_codec(name, **kw), grads))
                print(json.dumps(row), flush=True)
                f.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
