"""Codec-fidelity table: what each codec actually does to real gradients.

The offline half of the online `Codec.fidelity_probe` story (the online
half runs inside the async workers, ``telemetry/numerics.py``): one real
backprop of resnet18 / BERT, then every registered codec probed per leaf
and aggregated over the whole gradient tree — decode-after-encode
relative L2 error, cosine similarity, and achieved bits-per-parameter.
This is the measured form of the compression-utility trade the
reference's ``codings`` hook existed to explore: the sanity anchor
(identity ≈ 0 error), the cheap-cast tier (bf16/f16), and how much of
the gradient direction each aggressive codec actually keeps.

Run: ``python benchmarks/fidelity_bench.py [--models resnet18,bert]
[--bert-config base|tiny]``. Emits one JSON row per (model, codec) and
appends to ``benchmarks/results/fidelity_<model>.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs import get_codec

#: the probed configurations — the registry's full compression curve
CODECS = [
    ("identity", {}),
    ("bf16", {}),
    ("f16", {}),
    ("int8", {}),
    ("qsgd", {}),
    ("sign", {"use_pallas": False}),
    ("terngrad", {}),
    ("topk", {"fraction": 0.01}),
    ("randomk", {"fraction": 0.01}),
    ("threshold", {}),
    ("powersgd", {"rank": 2}),
    ("ef", {"inner_name": "topk", "fraction": 0.01}),
]


def tree_fidelity(code, grads, seed: int = 0) -> dict:
    """Per-leaf encode→decode roundtrip aggregated over the whole tree:
    rel error from total error energy, cosine from total dot/norms,
    bits/param from the summed payload bits — per-tensor codecs keep
    their per-leaf statistics, exactly as the train step runs them."""
    err2 = g2 = r2 = dot = 0.0
    bits = 0
    n = 0
    key = jax.random.key(seed)
    for i, g in enumerate(jax.tree.leaves(grads)):
        state = code.init_state(g.shape, g.dtype)
        rng = jax.random.fold_in(key, i) if code.needs_rng else None
        payload, _ = code.encode(g, state, rng)
        rec = code.decode(payload, g.shape, g.dtype)
        gf = np.asarray(g, np.float64).reshape(-1)
        rf = np.asarray(rec, np.float64).reshape(-1)
        err2 += float(np.sum((rf - gf) ** 2))
        g2 += float(np.sum(gf * gf))
        r2 += float(np.sum(rf * rf))
        dot += float(np.sum(rf * gf))
        bits += code.payload_bits(g.shape, g.dtype)
        n += gf.size
    return {
        "rel_error": (err2 / max(g2, 1e-300)) ** 0.5,
        "cosine": dot / max((r2 * g2) ** 0.5, 1e-300),
        "bits_per_param": bits / n,
        "params": n,
    }


#: aggregation-mode probes: codecs with a compressed-domain algebra,
#: measured as aggregate-vs-decode-sum rel error across worker counts —
#: ~0 for the exact algebras (the committed sanity anchor), a real
#: number for the approximate sign vote (its fidelity CONTRACT: the
#: serve loop ships the vote algebra only because this table bounds it)
AGG_CODECS = [
    ("int8", {}),
    ("qsgd", {}),
    ("terngrad", {}),
    ("topk", {"fraction": 0.01}),
    ("randomk", {"fraction": 0.01}),
    ("powersgd", {"rank": 2}),
    ("sign", {"use_pallas": False}),
]
AGG_WORLDS = (2, 4, 8)


def aggregate_fidelity(code, grads, world: int, seed: int = 0) -> dict:
    """Aggregate-vs-decode-sum relative L2 error over the whole tree.

    Worker payloads derive from the shared backprop gradient with a
    per-worker magnitude factor (``u_w ~ U[0.5, 1.5]``, so per-frame
    statistics — sign's mean|g|, int8's absmax — genuinely differ) AND
    additive minibatch-style noise at half the gradient's RMS (so
    workers genuinely DISAGREE on signs — a multiplicative factor alone
    leaves every sign bit identical, which the vote algebra handles
    exactly and would report a misleading 0). This is the regime that
    separates the exact algebras (error stays 0) from the sign vote
    approximation (mean-scale substitution, the number this table
    commits)."""
    err2 = ref2 = 0.0
    key = jax.random.key(seed)
    for i, g in enumerate(jax.tree.leaves(grads)):
        payloads = []
        state = code.init_state(g.shape, g.dtype)
        sigma = 0.5 * jnp.sqrt(jnp.mean(g.astype(jnp.float32) ** 2))
        for w in range(world):
            kw_ = jax.random.fold_in(jax.random.fold_in(key, i), w)
            scale = jax.random.uniform(kw_, (), minval=0.5, maxval=1.5)
            noise = sigma * jax.random.normal(
                jax.random.fold_in(kw_, 2), g.shape, jnp.float32)
            g_w = (g.astype(jnp.float32) + noise) * scale
            rng = jax.random.fold_in(kw_, 1) if code.needs_rng else None
            p, state = code.encode(g_w.astype(g.dtype), state, rng)
            payloads.append(p)
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *payloads)
        ref = np.asarray(
            code.decode_sum(stacked, g.shape, g.dtype), np.float64)
        agg_payload, meta = code.aggregate(stacked, g.shape, g.dtype)
        out = np.asarray(
            code.agg_decode(agg_payload, meta, g.shape, g.dtype),
            np.float64)
        err2 += float(np.sum((out - ref) ** 2))
        ref2 += float(np.sum(ref * ref))
    return {
        "world": world,
        "rel_error": (err2 / max(ref2, 1e-300)) ** 0.5,
        "exact": bool(code.agg_exact),
    }


def resnet18_grads(batch: int = 8):
    from pytorch_ps_mpi_tpu.models import ResNet18

    model = ResNet18(num_classes=10, small_inputs=True)
    k = jax.random.key(0)
    x = jax.random.normal(k, (batch, 32, 32, 3))
    y = jax.random.randint(jax.random.fold_in(k, 1), (batch,), 0, 10)
    params = model.init(jax.random.fold_in(k, 2), x[:1])

    def loss_fn(p, xx, yy):
        logits = model.apply(p, xx)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, yy[:, None], axis=1))

    return jax.jit(jax.grad(loss_fn))(params, x, y)


def bert_grads(config: str = "base", batch: int = 4, seq: int = 128):
    from pytorch_ps_mpi_tpu.models.bert import BertConfig, BertMLM, mlm_loss

    cfg = (BertConfig.base() if config == "base" else BertConfig.tiny())
    model = BertMLM(cfg)
    k = jax.random.key(0)
    tokens = jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.fold_in(k, 1), (batch, seq), 0,
                                 cfg.vocab_size)
    mask = jax.random.bernoulli(jax.random.fold_in(k, 2), 0.15, (batch, seq))
    params = model.init(jax.random.fold_in(k, 3), tokens[:1])

    def loss_fn(p):
        return mlm_loss(model.apply(p, tokens), targets, mask)

    return jax.jit(jax.grad(loss_fn))(params)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", default="resnet18,bert")
    ap.add_argument("--bert-config", default="base",
                    choices=["base", "tiny"])
    ap.add_argument("--aggregate", action="store_true",
                    help="also probe aggregate-vs-decode-sum fidelity "
                         "across worker counts (rows bench=agg_fidelity "
                         "into fidelity_agg_<model>.jsonl)")
    args = ap.parse_args(argv)
    os.makedirs("benchmarks/results", exist_ok=True)
    for model in args.models.split(","):
        if model == "resnet18":
            grads, label = resnet18_grads(), "resnet18"
        elif model == "bert":
            grads = bert_grads(args.bert_config)
            label = f"bert-{args.bert_config}"
        else:
            raise SystemExit(f"unknown model {model!r}")
        out = f"benchmarks/results/fidelity_{label}.jsonl"
        with open(out, "a") as f:
            for name, kw in CODECS:
                row = {"bench": "codec_fidelity", "model": label,
                       "codec": name, "codec_kw": kw,
                       "backend": jax.default_backend()}
                row.update(tree_fidelity(get_codec(name, **kw), grads))
                print(json.dumps(row), flush=True)
                f.write(json.dumps(row) + "\n")
        if args.aggregate:
            agg_out = f"benchmarks/results/fidelity_agg_{label}.jsonl"
            with open(agg_out, "a") as f:
                for name, kw in AGG_CODECS:
                    code = get_codec(name, **kw)
                    for world in AGG_WORLDS:
                        row = {"bench": "agg_fidelity", "model": label,
                               "codec": name, "codec_kw": kw,
                               "backend": jax.default_backend()}
                        row.update(aggregate_fidelity(code, grads, world))
                        print(json.dumps(row), flush=True)
                        f.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
