"""Scaling-efficiency benchmark: steps/sec vs worker count on a virtual
device mesh — the BASELINE.json "scaling efficiency" metric, measurable
without a pod by forcing N CPU host devices (the same mechanism the test
suite uses; on a real pod the identical code runs over ICI).

Run: ``python benchmarks/scaling_bench.py`` (forces CPU; do not use for
absolute numbers, only for the collective/step-structure scaling shape).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import time

import jax.numpy as jnp

from pytorch_ps_mpi_tpu import SGD
from pytorch_ps_mpi_tpu.mesh import make_mesh
from pytorch_ps_mpi_tpu.models import MLP
from pytorch_ps_mpi_tpu.data import cross_entropy_loss, synthetic_images


def run(world: int, steps: int = 30, per_worker_batch: int = 32):
    mesh = make_mesh(devices=jax.devices()[:world])
    model = MLP(features=(256, 10))
    data = synthetic_images("mnist", batch=per_worker_batch * world)
    x0, y0 = next(data)
    params = model.init(jax.random.key(0), x0)

    def loss_fn(p, b):
        x, y = b
        return cross_entropy_loss(model.apply(p, x), y)

    opt = SGD(params, mesh=mesh, lr=0.05, average=True)
    opt.step(loss_fn=loss_fn, batch=(x0, y0))  # compile
    t0 = time.perf_counter()
    for _, b in zip(range(steps), data):
        opt.step(loss_fn=loss_fn, batch=b)
    wall = time.perf_counter() - t0
    return steps / wall


def main():
    base = None
    print("| workers | steps/s | weak-scaling efficiency |")
    print("|---|---|---|")
    for world in [1, 2, 4, 8]:
        sps = run(world)
        if base is None:
            base = sps
        # weak scaling: per-worker batch fixed, ideal = flat steps/s
        print(f"| {world} | {sps:.1f} | {100 * sps / base:.0f}% |")


if __name__ == "__main__":
    main()
