"""Scaling-efficiency benchmark (BASELINE.json north-star metric).

Three layers of evidence, each honestly labeled (VERDICT r3 item 6):

1. **In-process sweep**: ResNet-18 data-parallel train step over 1→8
   virtual CPU devices, per-worker batch FIXED (weak scaling), with a
   per-step comm/compute breakdown from a real trace
   (``profiled_device_split``). Virtual devices share the host's fixed
   cores, so falling steps/s reflects compute CONTENTION, not collective
   cost — the transferable signal is the comm-time share column, which
   is what actually grows with world size on hardware.
2. **Cross-process (DCN) point**: the same step over an 8-device mesh
   split across 2 coordinated OS processes (``launch.py`` +
   ``jax.distributed``, 4 local devices each) — every psum crosses a
   real process boundary (loopback here; the identical code path is the
   multi-host pod's DCN hop).
3. **Extrapolation model**: weak-scaling efficiency at 8/64/256 chips
   from the standard ring-allreduce cost model
   ``T(W) = T_compute + 2·(W-1)/W · bytes/BW_link``, anchored to the
   MEASURED single-chip TPU step time (newest committed artifact, via
   ``utils.provenance``) and the gradient's wire bytes. The link
   bandwidth is a parameter (``--ici-gbytes``), not a measurement —
   the printed record says so.

Run: ``python benchmarks/scaling_bench.py [--steps 6] [--skip-dcn]``.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from pytorch_ps_mpi_tpu import SGD
from pytorch_ps_mpi_tpu.mesh import make_mesh
from pytorch_ps_mpi_tpu.models import ResNet18
from pytorch_ps_mpi_tpu.utils.tracing import profiled_device_split

PER_WORKER_BATCH = 32


def resnet18_param_count() -> int:
    """Exact parameter count of the benchmarked model (eval_shape — no
    device work); the extrapolation's wire bytes derive from THIS, so a
    model change can never silently stale the committed predictions."""
    import numpy as np

    model = ResNet18(num_classes=10, small_inputs=True)
    structs = jax.eval_shape(
        lambda k: model.init(k, jnp.ones((1, 32, 32, 3), jnp.float32)),
        jax.random.key(0),
    )
    return sum(int(np.prod(s.shape)) for s in jax.tree.leaves(structs))


def make_problem(world: int):
    mesh = make_mesh(devices=jax.devices()[:world])
    model = ResNet18(num_classes=10, small_inputs=True)
    batch = PER_WORKER_BATCH * world
    x = jax.random.normal(jax.random.key(1), (batch, 32, 32, 3))
    y = jax.random.randint(jax.random.key(2), (batch,), 0, 10)
    params = jax.jit(model.init)(jax.random.key(0), x[:1])

    from pytorch_ps_mpi_tpu.data import cross_entropy_loss

    def loss_fn(p, b):
        xb, yb = b
        return cross_entropy_loss(model.apply(p, xb), yb)

    opt = SGD(params, mesh=mesh, lr=0.05, average=True)
    return opt, loss_fn, (x, y)


def run_world(world: int, steps: int) -> dict:
    opt, loss_fn, batch = make_problem(world)
    opt.step(loss_fn=loss_fn, batch=batch)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(steps):
        _, data = opt.step(loss_fn=loss_fn, batch=batch)
    wall = time.perf_counter() - t0
    # one traced step for the comm/compute split (device-op durations)
    _, split = profiled_device_split(
        lambda: opt.step(loss_fn=loss_fn, batch=batch)
    )
    busy = split["device_busy_s"]
    return {
        "workers": world,
        "processes": 1,
        "per_worker_batch": PER_WORKER_BATCH,
        "steps_per_sec": round(steps / wall, 4),
        "step_ms": round(1e3 * wall / steps, 2),
        "comm_ms_per_dev": round(split["comm_s"] * 1e3, 2),
        "compute_ms_per_dev": round(split["compute_s"] * 1e3, 2),
        "comm_share": round(split["comm_s"] / busy, 4) if busy > 0 else 0.0,
        "wire_lowering": data["wire_lowering"],
        "wire_bytes_per_worker": data["wire_bytes_per_worker"],
    }


def run_dcn_point(steps: int, n_procs: int = 2,
                  timeout: float = 1200.0) -> dict | None:
    """8 devices across ``n_procs`` coordinated processes via launch.py
    (4x2 exercises a LARGER process topology on the same runtime path —
    every psum crosses 3 process boundaries instead of 1).

    Children write to temp FILES, not pipes — a rank blocked on a full
    unread pipe while the other rank waits in a collective would
    deadlock both until the timeout. A hang (TimeoutExpired) degrades to
    an error row so the extrapolation row still prints."""
    import tempfile

    dev_per_proc = 8 // n_procs
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={dev_per_proc}"
    )
    env.pop("JAX_PLATFORMS", None)
    logs = [tempfile.NamedTemporaryFile("w+", suffix=f".rank{r}.log",
                                        delete=False)
            for r in range(n_procs)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "pytorch_ps_mpi_tpu.launch",
             "--platform", "cpu",
             "--coordinator", f"localhost:{port}",
             "--num-processes", str(n_procs), "--process-id", str(r),
             os.path.join(REPO, "benchmarks", "scaling_worker.py"),
             str(PER_WORKER_BATCH), str(steps)],
            cwd=REPO, env=env, text=True,
            stdout=logs[r], stderr=subprocess.STDOUT,
        )
        for r in range(n_procs)
    ]
    deadline = time.time() + timeout
    timed_out = False
    try:
        for p in procs:
            try:
                p.wait(timeout=max(1.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                timed_out = True
                break
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    outs = []
    for f in logs:
        f.flush()
        f.seek(0)
        outs.append(f.read())
        f.close()
        os.unlink(f.name)
    if timed_out:
        # every rank's tail: the rank that actually crashed pre-collective
        # is usually not rank 0 or N-1
        tails = " / ".join(f"r{r}:{o[-160:]!r}" for r, o in enumerate(outs))
        return {"workers": 8, "processes": n_procs,
                "error": f"timeout after {timeout}s; rank logs: {tails}"}
    for r, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            return {"workers": 8, "processes": n_procs,
                    "error": f"rank {r} rc={p.returncode}: {out[-400:]}"}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("SCALING_ROW "):
                return json.loads(line[len("SCALING_ROW "):])
    return {"workers": 8, "processes": n_procs, "error": "no row emitted"}


def extrapolate(ici_gbytes: float) -> dict:
    """Ring-allreduce weak-scaling model anchored to the measured TPU
    step time from the newest committed artifact."""
    from pytorch_ps_mpi_tpu.utils.provenance import (
        load_tpu_records,
        newest_per_metric,
    )

    # drop errored rows and physically-impossible mfu (>= 1, the
    # pre-RTT-correction watcher bug) — but KEEP mfu == 0.0, which just
    # means the device's peak FLOPs table had no entry; the anchor needs
    # step_ms_device, not mfu
    records = [r for r in load_tpu_records(REPO)
               if "error" not in r
               and float(r.get("mfu", 0) or 0) < 1.0
               and r.get("step_ms_device")]
    newest = newest_per_metric(records)
    anchor = newest.get("resnet18_train_step_b256_bf16_steps_per_sec")
    t_comp_ms = anchor.get("step_ms_device") if anchor else None
    wire_bytes = resnet18_param_count() * 2  # bf16 wire (comm_dtype)
    model = {
        "metric": "scaling_extrapolation_ring_model",
        "model": "T(W) = T_compute + 2*(W-1)/W * wire_bytes / BW_link; "
                 "efficiency(W) = T_compute / T(W)",
        "t_compute_ms": t_comp_ms,
        "t_compute_provenance": (
            anchor.get("captured_by") if anchor else "no TPU artifact"
        ),
        "wire_bytes": wire_bytes,
        "ici_gbytes_per_s": ici_gbytes,
        "ici_note": (
            "link bandwidth is a PARAMETER (per-chip ICI, bidirectional "
            "ring), not a measurement from this host; single-chip tunnel "
            "cannot measure it"
        ),
    }
    if t_comp_ms:
        for w in (8, 64, 256):
            t_ring_ms = 2 * (w - 1) / w * wire_bytes / (ici_gbytes * 1e9) * 1e3
            model[f"predicted_efficiency_{w}chips"] = round(
                t_comp_ms / (t_comp_ms + t_ring_ms), 4
            )
    return model


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--skip-dcn", action="store_true")
    ap.add_argument("--ici-gbytes", type=float, default=90.0,
                    help="assumed per-chip ICI GB/s for the extrapolation "
                         "model (v5e-class default; a parameter, not a "
                         "measurement)")
    args = ap.parse_args()

    base = None
    for world in (1, 2, 4, 8):
        row = run_world(world, args.steps)
        if base is None:
            base = row["steps_per_sec"]
        row["weak_scaling_efficiency"] = round(row["steps_per_sec"] / base, 4)
        row["note"] = (
            "virtual CPU devices share fixed host cores: efficiency here "
            "is bounded by compute contention; comm_share is the "
            "transferable column"
        ) if world > 1 else "baseline"
        print(json.dumps(row), flush=True)

    if not args.skip_dcn:
        for n_procs in (2, 4):
            dcn = run_dcn_point(args.steps, n_procs=n_procs)
            if dcn is not None:
                dcn["kind"] = (
                    f"cross-process (DCN code path, {n_procs} procs, "
                    "loopback)"
                )
                if "steps_per_sec" in dcn and base:
                    dcn["weak_scaling_efficiency"] = round(
                        dcn["steps_per_sec"] / base, 4
                    )
                print(json.dumps(dcn), flush=True)

    print(json.dumps(extrapolate(args.ici_gbytes)), flush=True)


if __name__ == "__main__":
    main()
