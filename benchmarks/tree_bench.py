"""Root-ingest scaling: star vs hierarchical tree, 8 → 64 workers.

The claim under test (ISSUE 13 / DynamiQ, PAPERS.md): with a fixed pod
count, growing workers-per-pod grows each LEADER's fan-in but not the
root's — root ingest bytes per published version stay near-flat, while
the star grows linearly with worker count. Both legs run at a nonzero
emulated DCN RTT (``TPS_WAN_RTT_MS`` on every root-facing pusher) so
the topology pays the tax it would in a real cross-pod deployment.

Mechanics: the ROOT and the LEADERS are the real system under test —
an in-process ``serve()`` (tree mode where applicable) and real
``leader_main`` subprocesses folding compressed payloads with zero
per-push decodes. The leaf WORKERS are synthetic: each "pod" is one
subprocess running its workers as threads that seal and push a
pre-encoded payload through the real framed TCP wire (ctypes-level —
no per-worker jit, which is what makes 64 workers tractable on a
2-core CI box). Payload bytes, frame validation, trailers, staleness
accounting and the WAN shim are all the production path.

Gates (hard asserts, also written to the JSONL row):

- star root bytes/publish grow >= 6x from 8 to 64 workers (expect 8x);
- tree root bytes/publish grow <= 1.3x (expect ~1.0x — the trailer
  capacity is fixed at the deployment's max pod size on both legs);
- ``decodes_per_publish == 1.0`` at the root on the tree legs;
- zero per-push ingest decodes at every leader (scraped live from the
  leaders' /metrics before they exit).

Usage: ``python benchmarks/tree_bench.py [--quick] [--rtt-ms 4]``.
Appends a row to ``benchmarks/results/tree_bench.jsonl`` (gated by
``make tree-bench`` via bench_gate --trajectory).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RESULTS = os.path.join(REPO, "benchmarks", "results", "tree_bench.jsonl")

#: fixed pod count — workers grow per pod, the root's fan-in must not
PODS = 2
#: trailer capacity: the deployment's MAX pod size, constant across
#: legs so the tree's bytes/publish comparison is capacity-honest
SLOTS = 32

BASE_CFG = {
    "model": "mlp", "model_kw": {"features": (128, 16)},
    "in_shape": (32,), "batch": 8, "seed": 7,
    "codec": "topk", "codec_kw": {"fraction": 0.25},
    "optim": "sgd", "hyper": {"lr": 0.05},
    "frame_check": True, "transport": "tcp",
    "max_staleness": 10 ** 9,
    "leader_kw": {"group_codec": "identity", "idle_exit_s": 10.0,
                  "read_poll_s": 0.05},
}


def pusher_pod(argv=None) -> int:
    """One pod process: its workers as threads, each sealing + pushing
    a pre-encoded payload through the real framed wire. ``codec_kind``
    picks the wire: "upstream" (star → root, cfg codec) or "group"
    (tree → leader, the leaf hop's identity codec)."""
    import threading

    spec = json.loads(sys.argv[1] if argv is None else argv)
    cfg = spec["cfg"]
    wids = spec["wids"]
    host, port = spec["addr"].rsplit(":", 1)
    pushes = int(spec["pushes"])

    from pytorch_ps_mpi_tpu.codecs import get_codec
    from pytorch_ps_mpi_tpu.parallel import tcp
    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
    from pytorch_ps_mpi_tpu.parallel.dcn import CodecWire
    from pytorch_ps_mpi_tpu.resilience import frames

    _, params0, _, _ = make_problem(cfg)
    if spec["codec_kind"] == "group":
        code = get_codec(cfg["leader_kw"]["group_codec"])
    else:
        code = get_codec(cfg["codec"], **cfg.get("codec_kw", {}))
    wire = CodecWire(code, params0)
    rng = np.random.RandomState(int(cfg.get("seed", 0)))
    import jax

    grad = jax.tree.map(
        lambda x: rng.randn(*np.shape(x)).astype(np.float32), params0)
    payload = np.array(wire.encode_to_bytes(grad), copy=True)
    fp = frames.wire_fingerprint(wire, params0)
    lib = tcp.get_lib()

    import ctypes

    def one_worker(wid: int):
        import socket

        addr = socket.gethostbyname(host)
        h = lib.tps_worker_connect(addr.encode(), int(port), wid, 60000)
        assert h, f"pusher {wid} connect failed"
        buf = np.empty(frames.HEADER_BYTES + payload.nbytes, np.uint8)
        try:
            for s in range(pushes):
                sealed = frames.seal_frame(buf, payload, fp, step=s, seq=s)
                rc = lib.tps_worker_push_grad(
                    h, sealed.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_uint8)),
                    sealed.nbytes, 1, 60000)
                assert rc == 1, f"pusher {wid} push -> {rc}"
        finally:
            lib.tps_worker_close(h)

    threads = [threading.Thread(target=one_worker, args=(w,))
               for w in wids]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return 0


def _spawn_pod(cfg, wids, addr, codec_kind, pushes, rtt_ms):
    spec = {"cfg": cfg, "wids": wids, "addr": addr,
            "codec_kind": codec_kind, "pushes": pushes}
    src = (
        f"import sys; sys.path.insert(0, {REPO!r})\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from benchmarks.tree_bench import pusher_pod\n"
        "sys.exit(pusher_pod())\n"
    )
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "TPS_WAN_RTT_MS": str(rtt_ms)})
    return subprocess.Popen([sys.executable, "-c", src, json.dumps(spec)],
                            env=env)


def _scrape(port: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=3.0) as r:
        text = r.read().decode()
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or "{" in line:
            continue
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                pass
    return out


def _anatomy_summary(m: dict) -> dict:
    """Per-stage critical-path shares + the top advisor row from the
    serve metrics' anatomy section (RESULTS.md's star-vs-tree table)."""
    anat = m.get("anatomy")
    if not anat:
        return {}
    top = (anat["advisor"][0] if anat.get("advisor") else {})
    return {
        "rounds": anat["rounds"],
        "critical_shares": {c["stage"]: c["share"]
                            for c in anat["critical_path"]},
        "stage_p50_ms": {s: v["p50_ms"]
                         for s, v in anat.get("stages", {}).items()},
        "top_stage": top.get("stage"),
        "top_debottleneck_frac": (top.get("debottleneck") or {}).get(
            "saving_frac"),
    }


def run_star(n_workers: int, pushes: int, rtt_ms: float, timeout: float,
             anatomy_dir=None):
    """Star baseline: every pusher ships compressed frames straight to
    the root, paying the DCN RTT."""
    from pytorch_ps_mpi_tpu.codecs import get_codec
    from pytorch_ps_mpi_tpu.parallel.async_train import (
        join_workers,
        make_problem,
        serve,
    )
    from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSServer

    cfg = dict(BASE_CFG)
    cfg["n_workers"] = n_workers
    if anatomy_dir:
        cfg.update(lineage=True, lineage_dir=anatomy_dir)
    _, params0, _, _ = make_problem(cfg)
    root = TcpPSServer(0, num_workers=n_workers, template=params0,
                       max_staleness=10 ** 9,
                       code=get_codec(cfg["codec"], **cfg["codec_kw"]),
                       frame=True)
    addr = f"127.0.0.1:{root.port}"
    plan = np.array_split(np.arange(n_workers), PODS)
    pods = [_spawn_pod(cfg, [int(w) for w in wids], addr, "upstream",
                       pushes, rtt_ms) for wids in plan]
    t0, c0 = time.perf_counter(), time.process_time()
    try:
        # stop via stop_when + drain (NOT total_received): the batched
        # ingest counts frames the moment a batch pops, so a bare count
        # condition would exit with frames stranded in the inbox
        _, m = serve(root, cfg, total_grads=10 ** 9,
                     sync_barrier=True, timeout=timeout,
                     stop_when=lambda: (root.grads_received
                                        >= n_workers * pushes))
        wall = time.perf_counter() - t0
        cpu = time.process_time() - c0
        codes = join_workers(pods, timeout=60.0)
    finally:
        for p in pods:
            if p.poll() is None:
                p.terminate()
        root.close()
    assert codes == [0] * PODS, codes
    publishes = max(1.0, m["publish_version"] - 1)
    return {
        "workers": n_workers,
        "bytes_per_publish": m["bytes_received"] / publishes,
        "ingest_bytes_per_s": m["bytes_received"] / wall,
        "root_cpu_ms_per_publish": 1e3 * cpu / publishes,
        "frames_per_publish": m["grads_received"] / publishes,
        "decodes_per_publish": m["decodes_per_publish"],
        "agg_mode": m["agg_mode"],
        "anatomy": _anatomy_summary(m),
        "wall_s": wall,
    }


def _hop_summary(leader_stats: list) -> dict:
    """Leader-pipeline occupancy headline from the leaders' scraped
    ``ps_hop_*`` gauges (RESULTS.md's occupancy/headroom table): the
    hottest leader's busy fraction, the biggest streaming-headroom
    ratio, total hop rounds and ring drops across the tree."""
    busy = [s.get("ps_hop_busy_frac") for s in leader_stats]
    busy = [b for b in busy if b is not None]
    if not busy:
        return {}
    ratio = [s.get("ps_hop_stream_headroom_ratio", 1.0)
             for s in leader_stats]
    return {
        "busy_frac_max": max(busy),
        "headroom_ratio_max": max(ratio),
        "serial_ms": [s.get("ps_hop_serial_ms") for s in leader_stats],
        "ingest_wait_ms": [s.get("ps_hop_ingest_wait_ms")
                           for s in leader_stats],
        "rounds": sum(s.get("ps_hop_rounds_total", 0.0)
                      for s in leader_stats),
        "ring_drops": sum(s.get("ps_hop_ring_drops_total", 0.0)
                          for s in leader_stats),
    }


def run_tree(n_workers: int, pushes: int, rtt_ms: float, timeout: float,
             anatomy_dir=None, hop=False):
    """Tree leg: real leaders (one per pod) fold the pods' pushes and
    ship ONE compressed frame per round to the root over the emulated
    DCN; pod pushers ride the cheap intra-pod link (no RTT)."""
    from pytorch_ps_mpi_tpu.codecs import get_codec
    from pytorch_ps_mpi_tpu.parallel.async_train import (
        join_workers,
        make_problem,
        serve,
    )
    from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSServer
    from pytorch_ps_mpi_tpu.parallel.tree import (
        group_plan,
        leader_wid,
        read_leader_hello,
        spawn_leader,
    )

    group_size = n_workers // PODS
    cfg = dict(BASE_CFG)
    cfg.update(n_workers=n_workers, group_size=group_size,
               tree=True, tree_slots=SLOTS, metrics_port=0,
               tree_members=[leader_wid(n_workers, g)
                             for g in range(PODS)])
    if anatomy_dir:
        # root-side lineage + round anatomy: composed trailers expand
        # the leader hops, the leaders' hop logs land beside the root's
        cfg.update(lineage=True, lineage_dir=anatomy_dir)
    if hop:
        # leader-pipeline occupancy tracing: each leader's HopAnatomy
        # reconstructs its round into sub-stages and publishes the
        # ps_hop_* gauges this bench scrapes (min_rounds=1: the quick
        # leg folds few rounds and the gauges must still arm)
        cfg.update(hop_anatomy=True, hop_anatomy_kw={"min_rounds": 1})
    groups = group_plan(n_workers, group_size)
    assert len(groups) == PODS
    _, params0, _, _ = make_problem(cfg)
    root = TcpPSServer(0, num_workers=n_workers + PODS, template=params0,
                       max_staleness=10 ** 9,
                       code=get_codec(cfg["codec"], **cfg["codec_kw"]),
                       frame=True, tree_slots=SLOTS)
    addr = f"127.0.0.1:{root.port}"
    leaders, leader_metric_ports, pods = [], [], []
    leader_stats = []
    t0 = c0 = None
    try:
        for g, grp in enumerate(groups):
            # the leader IS on the DCN: its upstream pushes + snapshot
            # reads pay the RTT (the pod-side server costs nothing)
            p = spawn_leader([addr], g, grp, cfg,
                             env={"TPS_WAN_RTT_MS": str(rtt_ms)})
            hello = read_leader_hello(p)
            leaders.append(p)
            leader_metric_ports.append(hello.get("health_port"))
            pods.append(_spawn_pod(cfg, grp, hello["addr"], "group",
                                   pushes, 0.0))

        scraped = {"done": False}

        def stop_when():
            if root.tree_composed >= n_workers * pushes:
                if not scraped["done"]:
                    # scrape the leaders' invariants while they live
                    scraped["done"] = True
                    for port in leader_metric_ports:
                        if port:
                            try:
                                leader_stats.append(_scrape(port))
                            except Exception:
                                leader_stats.append({})
                return True
            return all(p.poll() is not None for p in pods + leaders)

        t0, c0 = time.perf_counter(), time.process_time()
        _, m = serve(root, cfg, total_grads=10 ** 9, sync_barrier=True,
                     timeout=timeout, stop_when=stop_when)
        wall = time.perf_counter() - t0
        cpu = time.process_time() - c0
        pod_codes = join_workers(pods, timeout=60.0)
        leader_codes = join_workers(leaders, timeout=60.0)
    finally:
        for p in pods + leaders:
            if p.poll() is None:
                p.terminate()
        root.close()
    assert pod_codes == [0] * PODS, pod_codes
    assert leader_codes == [0] * PODS, leader_codes
    publishes = max(1.0, m["publish_version"] - 1)
    return {
        "workers": n_workers,
        "bytes_per_publish": m["bytes_received"] / publishes,
        "ingest_bytes_per_s": m["bytes_received"] / wall,
        "root_cpu_ms_per_publish": 1e3 * cpu / publishes,
        "frames_per_publish": m["grads_received"] / publishes,
        "decodes_per_publish": m["decodes_per_publish"],
        "agg_mode": m["agg_mode"],
        "tree_composed": m["tree_composed"],
        "leader_decodes": [s.get("ps_tree_leader_decodes")
                           for s in leader_stats],
        "leader_upstream_pushes": [
            s.get("ps_tree_upstream_pushes_total") for s in leader_stats],
        "anatomy": _anatomy_summary(m),
        "hop": _hop_summary(leader_stats) if hop else {},
        "wall_s": wall,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI scale: fewer pushes per worker")
    ap.add_argument("--rtt-ms", type=float, default=4.0,
                    help="emulated DCN round trip (must be > 0: the "
                    "gate is only honest with a real DCN tax)")
    ap.add_argument("--anatomy", action="store_true",
                    help="arm root-side lineage + round anatomy per "
                    "leg and record per-stage critical-path shares "
                    "(RESULTS.md's star-vs-tree anatomy table)")
    ap.add_argument("--hop-anatomy", action="store_true",
                    help="arm per-leader hop occupancy tracing on the "
                    "tree legs and commit busy-fraction / streaming-"
                    "headroom headline numbers to the trajectory "
                    "(RESULTS.md's occupancy table)")
    ap.add_argument("--out", default=RESULTS)
    args = ap.parse_args(argv)
    assert args.rtt_ms > 0, "tree_bench requires a nonzero emulated RTT"
    pushes = 3 if args.quick else 8
    timeout = 240.0 if args.quick else 480.0

    import tempfile

    def _adir(tag):
        return (tempfile.mkdtemp(prefix=f"tree_anatomy_{tag}_")
                if args.anatomy else None)

    results = {"star": {}, "tree": {}}
    for n in (8, 64):
        print(f"== star  {n:3d} workers x {pushes} pushes "
              f"@ rtt {args.rtt_ms} ms", flush=True)
        results["star"][n] = run_star(n, pushes, args.rtt_ms, timeout,
                                      anatomy_dir=_adir(f"star{n}"))
        print("   ", {k: round(v, 3) if isinstance(v, float) else v
                      for k, v in results["star"][n].items()}, flush=True)
        print(f"== tree  {n:3d} workers ({PODS} pods)", flush=True)
        results["tree"][n] = run_tree(n, pushes, args.rtt_ms, timeout,
                                      anatomy_dir=_adir(f"tree{n}"),
                                      hop=args.hop_anatomy)
        print("   ", {k: (round(v, 3) if isinstance(v, float) else v)
                      for k, v in results["tree"][n].items()}, flush=True)

    star_growth = (results["star"][64]["bytes_per_publish"]
                   / results["star"][8]["bytes_per_publish"])
    tree_growth = (results["tree"][64]["bytes_per_publish"]
                   / results["tree"][8]["bytes_per_publish"])
    tree_dpp = results["tree"][64]["decodes_per_publish"]
    leader_decodes = [d for leg in results["tree"].values()
                     for d in leg["leader_decodes"] if d is not None]
    print(f"\nroot bytes/publish growth 8->64: star {star_growth:.2f}x, "
          f"tree {tree_growth:.2f}x")
    print(f"tree decodes/publish {tree_dpp}, leader ingest decodes "
          f"{leader_decodes}")

    # -- the gates ---------------------------------------------------------
    assert star_growth >= 6.0, (
        f"star baseline grew only {star_growth:.2f}x — the comparison "
        "is broken, not the tree")
    assert tree_growth <= 1.3, (
        f"tree root ingest grew {tree_growth:.2f}x from 8 to 64 workers "
        "(gate 1.3x) — the tree is no longer flat")
    assert all(leg["decodes_per_publish"] == 1.0
               and leg["agg_mode"] == 1.0
               for leg in results["tree"].values()), (
        "tree root must fold compressed frames with ONE decode per "
        f"published version: {results['tree']}")
    assert leader_decodes and all(d == 0.0 for d in leader_decodes), (
        f"leaders performed per-push ingest decodes: {leader_decodes}")

    row = {
        "bench": "tree_bench", "t": time.time(),
        "quick": bool(args.quick), "rtt_ms": args.rtt_ms,
        "pods": PODS, "pushes": pushes,
        "metrics": {
            "tree_bench.star_growth_x": round(star_growth, 4),
            "tree_bench.tree_growth_x": round(tree_growth, 4),
            "tree_bench.tree_root_cpu_ms_per_publish_64w": round(
                results["tree"][64]["root_cpu_ms_per_publish"], 4),
            "tree_bench.star_root_cpu_ms_per_publish_64w": round(
                results["star"][64]["root_cpu_ms_per_publish"], 4),
            "tree_bench.tree_bytes_per_publish_64w": round(
                results["tree"][64]["bytes_per_publish"], 1),
            "tree_bench.star_bytes_per_publish_64w": round(
                results["star"][64]["bytes_per_publish"], 1),
        },
        "legs": results,
    }
    if args.hop_anatomy:
        hop8 = results["tree"][8].get("hop") or {}
        hop64 = results["tree"][64].get("hop") or {}
        assert hop64.get("rounds", 0) > 0, (
            "--hop-anatomy armed but no leader published hop rounds — "
            f"scrapes: {results['tree'][64].get('hop')}")
        print(f"hop occupancy 64w: busy_max="
              f"{hop64.get('busy_frac_max', 0) * 100:.0f}%  "
              f"headroom_max={hop64.get('headroom_ratio_max', 1.0):.2f}x"
              f"  rounds={hop64.get('rounds', 0):.0f}  "
              f"drops={hop64.get('ring_drops', 0):.0f}")
        row["metrics"].update({
            "tree_bench.hop_busy_frac_8w": round(
                hop8.get("busy_frac_max", 0.0), 4),
            "tree_bench.hop_busy_frac_64w": round(
                hop64.get("busy_frac_max", 0.0), 4),
            "tree_bench.hop_headroom_ratio_8w": round(
                hop8.get("headroom_ratio_max", 1.0), 4),
            "tree_bench.hop_headroom_ratio_64w": round(
                hop64.get("headroom_ratio_max", 1.0), 4),
            "tree_bench.hop_ring_drops_64w": float(
                hop64.get("ring_drops", 0.0)),
        })
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(f"\nrow appended to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
