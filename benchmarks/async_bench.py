"""ResNet-50 async (AsySG shm PS) vs synchronous-barrier PS — BASELINE
config #3's async/straggler story, measured.

Same worker fleet both times (real jitted ResNet-50 fwd/bwd in every
worker process — no closed-form gradients anywhere), one deliberate
straggler. The synchronous PS applies one gradient from EVERY worker per
round, so its update rate is paced by the straggler; AsySG applies each
gradient on arrival, so fast workers keep streaming. The measured ratio
is the wall-clock benefit asynchrony exists for (Lian et al. 2015).

Honest labeling: this host is a single CPU core driving N worker
processes, so absolute steps/sec are meaningless — the async/sync RATIO
under an injected straggler is the evidence (and the protocol is
host-side by design; the device compute inside each worker is whatever
JAX backend the worker runs).

Run: ``python benchmarks/async_bench.py [--workers 4] [--batch 2]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # protocol bench: never touch the TPU

from pytorch_ps_mpi_tpu.parallel import dcn
from pytorch_ps_mpi_tpu.parallel.async_train import (
    join_workers,
    make_problem,
    serve,
    spawn_worker,
)
from pytorch_ps_mpi_tpu.utils.backend_guard import enable_compilation_cache

enable_compilation_cache()


def run(cfg, n_workers: int, sync_barrier: bool, total: int, code=None,
        max_staleness: int = 10**9):
    """One complete async job: server (shm or tcp per ``cfg['transport']``)
    + spawned jitted workers + serve loop + cleanup. The ONE server-
    lifecycle harness every protocol bench uses (transport_bench imports
    it) — fixes to worker-exit handling or cleanup land everywhere."""
    _, params0, _, _ = make_problem(cfg)
    if cfg.get("transport") == "tcp":
        from pytorch_ps_mpi_tpu.parallel import tcp

        server = tcp.TcpPSServer(
            0, num_workers=n_workers, template=params0,
            max_staleness=max_staleness, code=code,
        )
        name = f"127.0.0.1:{server.port}"
    else:
        name = f"/psq_bench_{os.getpid()}_{int(sync_barrier)}"
        server = dcn.ShmPSServer(
            name, num_workers=n_workers, template=params0,
            max_staleness=max_staleness, code=code,
        )
    procs = []
    try:
        procs = [spawn_worker(name, i, cfg) for i in range(n_workers)]
        _, m = serve(server, cfg, total_grads=0, total_received=total,
                     sync_barrier=sync_barrier, timeout=3600.0)
        for rc in join_workers(procs, timeout=600.0):
            if rc != 0:
                raise RuntimeError(f"worker exited {rc}")
    finally:
        server.close()
        join_workers(procs, timeout=5.0)  # failure path: reap, don't leak
    return m


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--fast-steps", type=int, default=8)
    ap.add_argument("--slow-steps", type=int, default=2)
    ap.add_argument("--slow-ms", type=float, default=4000.0)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--transport", default="shm", choices=["shm", "tcp"],
                    help="PS wire: shm (co-hosted) or tcp (the cross-host "
                         "DCN-role transport, here over localhost)")
    args = ap.parse_args()

    w = args.workers
    base = {
        "transport": args.transport,
        "model": args.model,
        "model_kw": {"num_classes": 10},
        "in_shape": (32, 32, 3),
        "batch": args.batch,
        "seed": 5,
        "optim": "sgd",
        # per-arrival updates (no averaging) need a cooler rate than a
        # synchronous sweep or the ResNet-50 loss visibly diverges
        "hyper": {"lr": 1e-4},
        "slow_ms": {str(w - 1): args.slow_ms},
        "open_timeout": 600.0,
        "push_timeout": 600.0,
    }

    # sync barrier: every worker contributes to every round, so all push
    # the same count; async: fast workers stream while the straggler naps
    sync_cfg = dict(base)
    sync_cfg["worker_steps"] = {str(i): args.slow_steps for i in range(w)}
    m_sync = run(sync_cfg, w, sync_barrier=True, total=w * args.slow_steps)

    async_cfg = dict(base)
    async_cfg["worker_steps"] = {
        **{str(i): args.fast_steps for i in range(w - 1)},
        str(w - 1): args.slow_steps,
    }
    m_async = run(
        async_cfg, w, sync_barrier=False,
        total=(w - 1) * args.fast_steps + args.slow_steps,
    )

    from pytorch_ps_mpi_tpu.utils.devtime import safe_ratio

    ratio = round(
        safe_ratio(m_async["updates_per_sec"], m_sync["updates_per_sec"]), 2
    )  # 0.0 = "sync run applied nothing before its deadline; not measured"
    print(json.dumps({
        "metric": f"{args.model}_async_vs_syncbarrier_updates_per_sec_ratio",
        "value": ratio,
        "unit": "x",
        "vs_baseline": ratio,
        "async_updates_per_sec": round(m_async["updates_per_sec"], 3),
        "sync_updates_per_sec": round(m_sync["updates_per_sec"], 3),
        "async_loss": round(m_async["loss_final"], 4),
        "sync_loss": round(m_sync["loss_final"], 4),
        # the staleness half of the tradeoff the ratio buys (canonical
        # schema quantiles — what the ps_staleness_p* gauges export)
        "async_staleness_p50": m_async["staleness_p50"],
        "async_staleness_p95": m_async["staleness_p95"],
        "async_staleness_p99": m_async["staleness_p99"],
        "workers": w,
        "transport": args.transport,
        "straggler_ms": args.slow_ms,
        "backend": "cpu (protocol bench; single-core host, ratio is the "
                   "evidence, absolute rates are not)",
    }, ensure_ascii=False), flush=True)


if __name__ == "__main__":
    main()
