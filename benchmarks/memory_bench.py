"""Peak-HBM measurement for ``donate_buffers`` (VERDICT r4 next #8).

``MPI_PS(donate_buffers=True)`` claims an in-place update cuts peak HBM
by roughly one params+opt-state copy (``ps.py`` docstring: ~2 GB at
BERT-base/Adam scale). This bench MEASURES it: each config runs in a
fresh subprocess (PJRT's ``peak_bytes_in_use`` is cumulative per
process, so a fresh process is the only honest per-config peak) that
takes 3 fused BERT-base MLM Adam steps on the live accelerator and
reports the device's peak allocation.

Run on a live TPU: ``python benchmarks/memory_bench.py``; emits one row
per config plus a summary with the measured savings. Off-TPU it emits an
honest skip (host-CPU backends report no device memory stats).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIGS = [
    {"donate": False, "remat": False},
    {"donate": True, "remat": False},
    # remat rides along: activation memory traded for recompute — the
    # other HBM lever, measured under the same protocol
    {"donate": True, "remat": True},
]


def run_one(donate: bool, remat: bool, batch: int, seq: int) -> None:
    """Subprocess body: 3 fused steps, then print peak HBM JSON."""
    import jax
    import jax.numpy as jnp

    from pytorch_ps_mpi_tpu import Adam
    from pytorch_ps_mpi_tpu.models.bert import BertConfig, BertMLM, mlm_loss

    cfg = BertConfig(dtype=jnp.bfloat16, max_position=max(512, seq),
                     remat=remat)
    model = BertMLM(cfg)
    key = jax.random.key(1)
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.fold_in(key, 1), (batch, seq),
                                 0, cfg.vocab_size)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 2), 0.15,
                                (batch, seq))
    params = jax.jit(model.init)(jax.random.key(0), tokens[:1])

    def loss_fn(p, b):
        t, tg, m = b
        return mlm_loss(model.apply(p, t), tg, m)

    opt = Adam(params, lr=1e-4, donate_buffers=donate)
    del params  # donation demands no outside reference
    for _ in range(3):
        loss, _ = opt.step(loss_fn=loss_fn, batch=(tokens, targets, mask))
    jax.block_until_ready(opt.params)
    dev = jax.devices()[0]
    stats = dev.memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    rec = {
        "metric": "bert_base_adam_peak_hbm_bytes",
        "donate_buffers": donate,
        "remat": remat,
        "batch": batch,
        "seq": seq,
        "value": peak,
        "unit": "bytes",
        "source": "runtime_memory_stats",
        "bytes_in_use_after": stats.get("bytes_in_use"),
        "largest_alloc": stats.get("largest_alloc_size"),
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", "?"),
        "loss_finite": bool(jnp.isfinite(loss)),
    }
    if peak is None:
        # the axon-tunneled PJRT plugin exposes no allocator stats —
        # fall back to XLA's buffer assignment for the compiled step,
        # where donation is visible as output buffers aliasing argument
        # buffers. Guarded: a backend with NEITHER stats nor
        # memory_analysis must still emit this config's row (the
        # bench's contract is one row per config, whatever happens)
        try:
            ma = opt.step_memory_analysis(loss_fn, (tokens, targets, mask))
            rec.update(value=ma.get("estimated_peak_bytes"),
                       source="xla_memory_analysis", **ma)
        except Exception as e:
            rec["fallback_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    print(json.dumps(rec), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--one", type=str, default=None,
                    help="internal: run one config json in-process")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args()

    if args.one is not None:
        cfg = json.loads(args.one)
        run_one(cfg["donate"], cfg["remat"], args.batch, args.seq)
        return

    from pytorch_ps_mpi_tpu.utils.backend_guard import ensure_live_backend

    import jax

    live = ensure_live_backend()
    if not (live and jax.default_backend() == "tpu"):
        print(json.dumps({
            "metric": "bert_base_adam_peak_hbm_bytes",
            "skipped": "host backend reports no device memory stats; "
                       "run on a live TPU",
            "backend": jax.default_backend(),
        }), flush=True)
        return

    rows = []
    for cfg in CONFIGS:
        # per-config try: a tunnel stall mid-config (the failure mode
        # the watcher exists for) must cost only that config's row, not
        # the remaining configs or the savings summary
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--one", json.dumps(cfg),
                 "--batch", str(args.batch), "--seq", str(args.seq)],
                capture_output=True, text=True, timeout=900,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
        except subprocess.TimeoutExpired:
            print(json.dumps({
                "metric": "bert_base_adam_peak_hbm_bytes",
                "config": cfg,
                "error": "timeout after 900s (tunnel stall?)",
            }), flush=True)
            continue
        for line in out.stdout.splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            print(line, flush=True)
            rows.append(rec)
        if out.returncode != 0:
            print(json.dumps({
                "metric": "bert_base_adam_peak_hbm_bytes",
                "config": cfg,
                "error": out.stderr[-500:],
            }), flush=True)

    peaks = {(r["donate_buffers"], r["remat"]): r.get("value")
             for r in rows if r.get("value")}
    if (False, False) in peaks and (True, False) in peaks:
        saved = peaks[(False, False)] - peaks[(True, False)]
        print(json.dumps({
            "metric": "donate_buffers_peak_hbm_saving_bytes",
            "value": saved,
            "unit": "bytes",
            "saved_gb": round(saved / 2 ** 30, 3),
            "peak_no_donate": peaks[(False, False)],
            "peak_donate": peaks[(True, False)],
            "peak_donate_remat": peaks.get((True, True)),
            "backend": "tpu",
        }), flush=True)


if __name__ == "__main__":
    main()
