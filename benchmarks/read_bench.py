"""Read-tier load bench: open-loop readers, delta economics, saturation.

Drives the parameter-serving read tier
(:mod:`pytorch_ps_mpi_tpu.serving`) the way the north star's "millions
of users" would: a publisher advancing versions with small inter-version
deltas while **hundreds of concurrent simulated readers** issue
version-conditional reads on an **open-loop** arrival schedule (each
request's latency is measured from its *scheduled* arrival time, so
queueing delay is charged to the server, not silently absorbed by a
closed loop that only asks as fast as it is answered).

Three stages:

1. **delta economics** — readers track the publisher via delta reads;
   bytes/read for deltas vs full snapshots from the core's own
   counters. The acceptance bar (``delta_reduction_x >= 5`` for small
   inter-version deltas) is asserted here.
2. **saturation sweep** — offered load swept past the read tier's
   capacity; per load: achieved rps, served p50/p99, shed count. The
   admission queue sheds overload with explicit retry-after replies, so
   the p99 of SERVED requests must stay bounded (no collapse) past the
   limit — also asserted.
3. (implicit) **coalescing** — identical-version delta asks within one
   version window ride one encode; the hit count is reported.

Artifacts: metric rows into ``benchmarks/results/read_bench_<date>.jsonl``
and one flat trajectory row appended to
``benchmarks/results/read_bench.jsonl`` for ``bench_gate --trajectory``.

Usage::

  python benchmarks/read_bench.py               # full (hundreds of readers)
  python benchmarks/read_bench.py --quick       # CI-scale
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")


def build_template(n_params: int) -> Dict[str, np.ndarray]:
    """A few-layer synthetic tree totalling ~n_params f32 elements (the
    read tier is agnostic to what the tree means)."""
    per = max(1, n_params // 4)
    return {
        "layer0": np.zeros((per,), np.float32),
        "layer1": np.zeros((per,), np.float32),
        "layer2": np.zeros((per,), np.float32),
        "head": np.zeros((n_params - 3 * per,), np.float32),
    }


class Publisher(threading.Thread):
    """Advance versions at a fixed cadence, perturbing ``change_frac``
    of the parameters per version (the small-inter-version-delta regime
    a converging trainer produces)."""

    def __init__(self, core, template, change_frac: float,
                 interval_s: float):
        super().__init__(daemon=True)
        from pytorch_ps_mpi_tpu.parallel.dcn import _flatten

        self._flatten = _flatten
        self.core = core
        self.flat = _flatten(template).copy()
        self.flat[:] = np.random.RandomState(0).randn(
            self.flat.size).astype(np.float32)
        self.n_change = max(1, int(change_frac * self.flat.size))
        self.interval_s = float(interval_s)
        self.rng = np.random.RandomState(1)
        self.stop_evt = threading.Event()
        self.published = 0

    def publish_once(self) -> None:
        idx = self.rng.choice(self.flat.size, self.n_change, replace=False)
        self.flat[idx] += self.rng.randn(self.n_change).astype(
            np.float32) * 1e-3
        self.core.publish(flat=self.flat.copy())
        self.published += 1

    def run(self) -> None:
        while not self.stop_evt.is_set():
            self.publish_once()
            self.stop_evt.wait(self.interval_s)

    def stop(self) -> None:
        self.stop_evt.set()
        self.join(timeout=5)


def run_delta_stage(core, template, serving_kw, *, readers: int,
                    reads_each: int, change_frac: float,
                    publish_interval: float) -> Dict[str, float]:
    """Readers track the publisher through deltas; returns the bytes
    economics from the core's own counters."""
    from pytorch_ps_mpi_tpu.serving import ServingReader

    pub = Publisher(core, template, change_frac, publish_interval)
    pub.publish_once()  # first full snapshot exists before readers start
    pub.start()
    errs: List[str] = []

    def reader_body(i: int) -> None:
        try:
            r = ServingReader("127.0.0.1", core.read_port, template,
                              serving_kw=serving_kw, timeout=30.0)
            for _ in range(reads_each):
                r.read_params()
                time.sleep(publish_interval * 0.7)
            r.close()
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(f"reader {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=reader_body, args=(i,))
               for i in range(readers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    pub.stop()
    if errs:
        raise RuntimeError("; ".join(errs[:3]))
    s = core.serving_snapshot()
    full_bytes = 4 * sum(int(np.prod(v.shape)) for v in template.values())
    delta_reads = max(1, s["reads_delta"])
    avg_delta_bytes = max(
        1.0, full_bytes - s["delta_bytes_saved"] / delta_reads)
    return {
        "full_bytes": float(full_bytes),
        "avg_delta_bytes": float(avg_delta_bytes),
        "delta_reduction_x": float(full_bytes / avg_delta_bytes),
        "delta_reads": float(s["reads_delta"]),
        "coalesce_hits": float(s["coalesce_hits"]),
        "not_modified": float(s["reads_not_modified"]),
        "versions_published": float(pub.published),
    }


def run_saturation(core, template, *, readers: int, offered_rps: float,
                   duration_s: float) -> Dict[str, float]:
    """Open-loop stage at one offered load.

    Two latency views per served request: **service** latency (request
    sent → reply received — what the bounded admission queue controls;
    this is the collapse gate) and **schedule** latency (from the
    open-loop arrival instant — charges client-side lateness too; past
    saturation this one grows by definition, because achieved < offered
    no matter how the server sheds). A reader that falls behind its
    schedule fast-forwards, counting the skipped arrivals as missed."""
    from pytorch_ps_mpi_tpu.serving.net import ReadClient

    service: List[float] = []
    schedule: List[float] = []
    sheds = [0]
    served = [0]
    missed = [0]
    lock = threading.Lock()
    t_start = time.perf_counter() + 0.2  # common epoch for all schedules
    per_reader = offered_rps / readers
    gap = 1.0 / per_reader if per_reader > 0 else duration_s

    def reader_body(i: int) -> None:
        try:
            c = ReadClient("127.0.0.1", core.read_port, timeout=30.0)
        except OSError:
            return
        my_service, my_schedule = [], []
        my_shed = my_served = my_missed = 0
        # staggered open-loop schedule: reader i fires at
        # t_start + (i/readers)*gap + k*gap
        next_t = t_start + (i / readers) * gap
        while next_t < t_start + duration_s:
            now = time.perf_counter()
            if now < next_t:
                time.sleep(next_t - now)
            elif now - next_t > 2 * gap:
                # hopelessly behind: fast-forward, count skipped slots
                skip = int((now - next_t) // gap)
                my_missed += skip
                next_t += skip * gap
            sent = time.perf_counter()
            try:
                kind, _, _, _, _ = c.request(have_version=0,
                                             want_delta=False)
            except (OSError, RuntimeError, ConnectionError):
                break
            done = time.perf_counter()
            if kind == "retry":
                my_shed += 1
            else:
                my_served += 1
                my_service.append(done - sent)
                my_schedule.append(done - next_t)
            next_t += gap
        c.close()
        with lock:
            service.extend(my_service)
            schedule.extend(my_schedule)
            sheds[0] += my_shed
            served[0] += my_served
            missed[0] += my_missed

    threads = [threading.Thread(target=reader_body, args=(i,))
               for i in range(readers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 60)
    sv = np.array(service) if service else np.array([0.0])
    sc = np.array(schedule) if schedule else np.array([0.0])
    wall = duration_s
    return {
        "offered_rps": float(offered_rps),
        "achieved_rps": float(served[0] / wall),
        "served": float(served[0]),
        "shed": float(sheds[0]),
        "missed": float(missed[0]),
        "shed_frac": float(sheds[0] / max(1, served[0] + sheds[0])),
        "p50_ms": float(np.percentile(sv, 50) * 1e3),
        "p99_ms": float(np.percentile(sv, 99) * 1e3),
        "sched_p99_ms": float(np.percentile(sc, 99) * 1e3),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--quick", action="store_true",
                    help="CI scale: fewer readers, shorter stages")
    ap.add_argument("--readers", type=int, default=None)
    ap.add_argument("--params", type=int, default=200_000)
    ap.add_argument("--change-frac", type=float, default=0.005,
                    help="fraction of params changed per version (the "
                         "small-delta regime)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    quick = args.quick
    readers = args.readers or (40 if quick else 200)
    template = build_template(args.params)
    serving_kw = {"ring": 16, "admission_depth": 32,
                  "retry_after_s": 0.02, "delta_bucket_mb": 1.0}
    cfg = {"read_port": 0, "serving_kw": serving_kw}

    from pytorch_ps_mpi_tpu.serving import ServingCore

    rows: List[dict] = []

    def metric(name: str, value: float, unit: str = "") -> None:
        rows.append({"metric": f"read_bench.{name}", "value": value,
                     "unit": unit})
        print(f"  {name:<28} {value:>12.3f} {unit}")

    t_wall0 = time.perf_counter()
    print(f"read_bench: {readers} readers, {args.params} params, "
          f"change_frac {args.change_frac}")

    # -- stage 1: delta economics ----------------------------------------
    core = ServingCore(None, cfg, template=template)
    econ = run_delta_stage(
        core, template, serving_kw,
        readers=readers, reads_each=6 if quick else 12,
        change_frac=args.change_frac, publish_interval=0.1)
    print("stage 1 — delta economics:")
    for k, v in econ.items():
        metric(k, v, "bytes" if k.endswith("bytes") else
               ("x" if k.endswith("_x") else ""))
    core.close()

    # -- stage 2: saturation sweep ---------------------------------------
    core = ServingCore(None, cfg, template=template)
    core.publish(flat=np.zeros(
        sum(int(np.prod(v.shape)) for v in template.values()), np.float32))
    sweep = ([100, 400, 1200] if quick
             else [200, 800, 2400, 6000, 12000])
    print("stage 2 — saturation sweep (full reads, open-loop):")
    curve = []
    for rps in sweep:
        row = run_saturation(core, template, readers=readers,
                             offered_rps=rps,
                             duration_s=2.0 if quick else 4.0)
        curve.append(row)
        print(f"  offered {row['offered_rps']:>7.0f}/s  achieved "
              f"{row['achieved_rps']:>7.0f}/s  service p50 "
              f"{row['p50_ms']:6.2f} ms  p99 {row['p99_ms']:7.2f} ms  "
              f"sched p99 {row['sched_p99_ms']:8.2f} ms  "
              f"shed {row['shed']:>6.0f} ({row['shed_frac']:.1%})")
        rows.append({"metric": "read_bench.saturation", **row})
    core.close()

    # bounded-past-the-limit check: compare the SERVED p99 at the highest
    # offered load (where shedding is active) against the lowest load's
    p99_lo = curve[0]["p99_ms"]
    p99_hi = curve[-1]["p99_ms"]
    metric("p99_low_load_ms", p99_lo, "ms")
    metric("p99_max_load_ms", p99_hi, "ms")
    metric("achieved_max_rps", max(c["achieved_rps"] for c in curve),
           "ops/sec")
    metric("shed_at_max", curve[-1]["shed"])

    wall = time.perf_counter() - t_wall0
    metric("wall_s", wall, "s")

    # -- acceptance assertions -------------------------------------------
    ok = True
    if econ["delta_reduction_x"] < 5.0:
        print(f"FAIL: delta_reduction_x {econ['delta_reduction_x']:.1f} "
              "< 5", file=sys.stderr)
        ok = False
    # "no collapse": the SERVICE p99 of served requests past the
    # admission limit stays within a generous bound of the low-load p99
    # — the bounded backlog caps server-side queueing, shedding absorbs
    # the rest (the schedule-relative p99 necessarily grows once
    # achieved < offered; it is reported, not gated)
    bound = max(50.0 * max(p99_lo, 1.0), 500.0)
    if p99_hi > bound:
        print(f"FAIL: served p99 collapsed past the admission limit "
              f"({p99_hi:.1f} ms > bound {bound:.1f} ms)", file=sys.stderr)
        ok = False

    os.makedirs(RESULTS_DIR, exist_ok=True)
    day = time.strftime("%Y-%m-%d")
    out = args.out or os.path.join(RESULTS_DIR, f"read_bench_{day}.jsonl")
    with open(out, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    # flat trajectory row for bench_gate
    with open(os.path.join(RESULTS_DIR, "read_bench.jsonl"), "a") as f:
        f.write(json.dumps({
            "bench": "read_bench", "t": time.time(),
            "wall_s": round(wall, 3),
            "delta_reduction_x": round(econ["delta_reduction_x"], 2),
            "p99_max_load_ms": round(p99_hi, 3),
            "achieved_max_rps": round(
                max(c["achieved_rps"] for c in curve), 1),
            "readers": readers, "quick": int(quick),
        }) + "\n")
    print(f"wrote {out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
