"""Read-tier load bench: open-loop readers, delta economics, saturation.

Drives the parameter-serving read tier
(:mod:`pytorch_ps_mpi_tpu.serving`) the way the north star's "millions
of users" would: a publisher advancing versions with small inter-version
deltas while **hundreds of concurrent simulated readers** issue
version-conditional reads on an **open-loop** arrival schedule (each
request's latency is measured from its *scheduled* arrival time, so
queueing delay is charged to the server, not silently absorbed by a
closed loop that only asks as fast as it is answered).

Four stages:

1. **delta economics** — readers track the publisher via delta reads;
   bytes/read for deltas vs full snapshots from the core's own
   counters. The acceptance bar (``delta_reduction_x >= 5`` for small
   inter-version deltas) is asserted here.
2. **saturation sweep (Python loop)** — offered load swept past the
   read tier's capacity; per load: achieved rps, served p50/p99, shed
   count. The admission queue sheds overload with explicit retry-after
   replies, so the p99 of SERVED requests must stay bounded (no
   collapse) past the limit — also asserted.
3. **saturation sweep (native tier)** — the same sweep through the C++
   epoll tier (``read_native``); its served p99 must obey the same
   bound, and its shed fraction at the highest offered load must not
   exceed the Python loop's (the native tier drains replies off the
   GIL, so overload turns into throughput, not sheds). Skipped without
   a toolchain / under ``PS_NO_NATIVE``.
4. **follower replica tree** — one root + 2 ``FollowerLoop`` replicas
   serving 3x the reader population of a single endpoint while the
   publisher advances; served p99 per endpoint is reported and the
   replica lag once the publisher stops must settle <= 2 versions.
5. **freshness propagation** (``--freshness``) — a root -> replica ->
   replica chain with FRS1 trailers armed: per-depth (1-hop and 2-hop)
   publish->visible latency and reader delivery age distributions, plus
   the per-hop relay latency quantiles the trailer's hop records carry.
   The table RESULTS.md cites comes from this stage.
(implicit) **coalescing** — identical-version delta asks within one
version window ride one encode; the hit count is reported.

Artifacts: metric rows into ``benchmarks/results/read_bench_<date>.jsonl``
and one flat trajectory row appended to
``benchmarks/results/read_bench.jsonl`` for ``bench_gate --trajectory``.

Usage::

  python benchmarks/read_bench.py               # full (hundreds of readers)
  python benchmarks/read_bench.py --quick       # CI-scale
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "results")


def build_template(n_params: int) -> Dict[str, np.ndarray]:
    """A few-layer synthetic tree totalling ~n_params f32 elements (the
    read tier is agnostic to what the tree means)."""
    per = max(1, n_params // 4)
    return {
        "layer0": np.zeros((per,), np.float32),
        "layer1": np.zeros((per,), np.float32),
        "layer2": np.zeros((per,), np.float32),
        "head": np.zeros((n_params - 3 * per,), np.float32),
    }


class Publisher(threading.Thread):
    """Advance versions at a fixed cadence, perturbing ``change_frac``
    of the parameters per version (the small-inter-version-delta regime
    a converging trainer produces)."""

    def __init__(self, core, template, change_frac: float,
                 interval_s: float):
        super().__init__(daemon=True)
        from pytorch_ps_mpi_tpu.parallel.dcn import _flatten

        self._flatten = _flatten
        self.core = core
        self.flat = _flatten(template).copy()
        self.flat[:] = np.random.RandomState(0).randn(
            self.flat.size).astype(np.float32)
        self.n_change = max(1, int(change_frac * self.flat.size))
        self.interval_s = float(interval_s)
        self.rng = np.random.RandomState(1)
        self.stop_evt = threading.Event()
        self.published = 0

    def publish_once(self) -> None:
        idx = self.rng.choice(self.flat.size, self.n_change, replace=False)
        self.flat[idx] += self.rng.randn(self.n_change).astype(
            np.float32) * 1e-3
        self.core.publish(flat=self.flat.copy())
        self.published += 1

    def run(self) -> None:
        while not self.stop_evt.is_set():
            self.publish_once()
            self.stop_evt.wait(self.interval_s)

    def stop(self) -> None:
        self.stop_evt.set()
        self.join(timeout=5)


def run_delta_stage(core, template, serving_kw, *, readers: int,
                    reads_each: int, change_frac: float,
                    publish_interval: float) -> Dict[str, float]:
    """Readers track the publisher through deltas; returns the bytes
    economics from the core's own counters."""
    from pytorch_ps_mpi_tpu.serving import ServingReader

    pub = Publisher(core, template, change_frac, publish_interval)
    pub.publish_once()  # first full snapshot exists before readers start
    pub.start()
    errs: List[str] = []

    def reader_body(i: int) -> None:
        try:
            r = ServingReader("127.0.0.1", core.read_port, template,
                              serving_kw=serving_kw, timeout=30.0)
            for _ in range(reads_each):
                r.read_params()
                time.sleep(publish_interval * 0.7)
            r.close()
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(f"reader {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=reader_body, args=(i,))
               for i in range(readers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    pub.stop()
    if errs:
        raise RuntimeError("; ".join(errs[:3]))
    s = core.serving_snapshot()
    full_bytes = 4 * sum(int(np.prod(v.shape)) for v in template.values())
    delta_reads = max(1, s["reads_delta"])
    avg_delta_bytes = max(
        1.0, full_bytes - s["delta_bytes_saved"] / delta_reads)
    return {
        "full_bytes": float(full_bytes),
        "avg_delta_bytes": float(avg_delta_bytes),
        "delta_reduction_x": float(full_bytes / avg_delta_bytes),
        "delta_reads": float(s["reads_delta"]),
        "coalesce_hits": float(s["coalesce_hits"]),
        "not_modified": float(s["reads_not_modified"]),
        "versions_published": float(pub.published),
    }


def run_saturation(core, template, *, readers: int, offered_rps: float,
                   duration_s: float) -> Dict[str, float]:
    """Open-loop stage at one offered load.

    Two latency views per served request: **service** latency (request
    sent → reply received — what the bounded admission queue controls;
    this is the collapse gate) and **schedule** latency (from the
    open-loop arrival instant — charges client-side lateness too; past
    saturation this one grows by definition, because achieved < offered
    no matter how the server sheds). A reader that falls behind its
    schedule fast-forwards, counting the skipped arrivals as missed."""
    from pytorch_ps_mpi_tpu.serving.net import ReadClient

    service: List[float] = []
    schedule: List[float] = []
    sheds = [0]
    served = [0]
    missed = [0]
    lock = threading.Lock()
    t_start = time.perf_counter() + 0.2  # common epoch for all schedules
    per_reader = offered_rps / readers
    gap = 1.0 / per_reader if per_reader > 0 else duration_s

    def reader_body(i: int) -> None:
        try:
            c = ReadClient("127.0.0.1", core.read_port, timeout=30.0)
        except OSError:
            return
        my_service, my_schedule = [], []
        my_shed = my_served = my_missed = 0
        # staggered open-loop schedule: reader i fires at
        # t_start + (i/readers)*gap + k*gap
        next_t = t_start + (i / readers) * gap
        while next_t < t_start + duration_s:
            now = time.perf_counter()
            if now < next_t:
                time.sleep(next_t - now)
            elif now - next_t > 2 * gap:
                # hopelessly behind: fast-forward, count skipped slots
                skip = int((now - next_t) // gap)
                my_missed += skip
                next_t += skip * gap
            sent = time.perf_counter()
            try:
                kind, _, _, _, _ = c.request(have_version=0,
                                             want_delta=False)
            except (OSError, RuntimeError, ConnectionError):
                break
            done = time.perf_counter()
            if kind == "retry":
                my_shed += 1
            else:
                my_served += 1
                my_service.append(done - sent)
                my_schedule.append(done - next_t)
            next_t += gap
        c.close()
        with lock:
            service.extend(my_service)
            schedule.extend(my_schedule)
            sheds[0] += my_shed
            served[0] += my_served
            missed[0] += my_missed

    threads = [threading.Thread(target=reader_body, args=(i,))
               for i in range(readers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 60)
    sv = np.array(service) if service else np.array([0.0])
    sc = np.array(schedule) if schedule else np.array([0.0])
    wall = duration_s
    return {
        "offered_rps": float(offered_rps),
        "achieved_rps": float(served[0] / wall),
        "served": float(served[0]),
        "shed": float(sheds[0]),
        "missed": float(missed[0]),
        "shed_frac": float(sheds[0] / max(1, served[0] + sheds[0])),
        "p50_ms": float(np.percentile(sv, 50) * 1e3),
        "p99_ms": float(np.percentile(sv, 99) * 1e3),
        "sched_p99_ms": float(np.percentile(sc, 99) * 1e3),
    }


def run_replica_tree(template, serving_kw, *, readers_per: int,
                     offered_rps: float, duration_s: float,
                     change_frac: float, publish_interval: float
                     ) -> Dict[str, float]:
    """Root + 2 followers serving 3x the single-endpoint reader
    population while the publisher advances. Lag is the real version
    gap (root latest - replica latest), sampled throughout."""
    from pytorch_ps_mpi_tpu.serving import FollowerLoop, ServingCore

    root = ServingCore(None, {"read_port": 0, "serving_kw": serving_kw},
                       template=template)
    pub = Publisher(root, template, change_frac, publish_interval)
    pub.publish_once()
    reps, loops = [], []
    for _ in range(2):
        rep = ServingCore(None, {"read_port": 0,
                                 "serving_kw": serving_kw},
                          template=template)
        loops.append(FollowerLoop(
            rep, "127.0.0.1", root.read_port, template=template,
            poll_s=publish_interval / 4, serving_kw=serving_kw).start())
        reps.append(rep)
    deadline = time.time() + 30
    while (any(r.latest_version(None) == 0 for r in reps)
           and time.time() < deadline):
        time.sleep(0.01)
    if any(r.latest_version(None) == 0 for r in reps):
        raise RuntimeError("replicas never caught the root's snapshot")
    pub.start()

    lag_max = [0]
    stop = threading.Event()

    def sample_lag() -> None:
        while not stop.is_set():
            gap = max(root.latest_version(None) - r.latest_version(None)
                      for r in reps)
            lag_max[0] = max(lag_max[0], gap)
            time.sleep(0.02)

    sampler = threading.Thread(target=sample_lag, daemon=True)
    sampler.start()
    endpoints = [root] + reps
    results: List[Optional[dict]] = [None] * len(endpoints)

    def drive(i: int) -> None:
        results[i] = run_saturation(endpoints[i], template,
                                    readers=readers_per,
                                    offered_rps=offered_rps,
                                    duration_s=duration_s)

    drivers = [threading.Thread(target=drive, args=(i,))
               for i in range(len(endpoints))]
    for t in drivers:
        t.start()
    for t in drivers:
        t.join(timeout=duration_s + 120)
    pub.stop()
    # quiesce: followers must converge on the final root version
    deadline = time.time() + 30
    while (any(r.latest_version(None) != root.latest_version(None)
               for r in reps) and time.time() < deadline):
        time.sleep(0.01)
    lag_final = max(root.latest_version(None) - r.latest_version(None)
                    for r in reps)
    stop.set()
    sampler.join(timeout=5)
    relayed = sum(r.read_metrics()["follower_bytes_relayed"]
                  for r in reps)
    done = [r for r in results if r is not None]
    for fl in loops:
        fl.close()
    for c in reps + [root]:
        c.close()
    return {
        "endpoints": float(len(endpoints)),
        "readers_total": float(readers_per * len(endpoints)),
        "served_total": float(sum(r["served"] for r in done)),
        "achieved_rps_total": float(sum(r["achieved_rps"]
                                        for r in done)),
        "p99_ms": float(max(r["p99_ms"] for r in done)),
        "shed_frac": float(max(r["shed_frac"] for r in done)),
        "lag_max": float(lag_max[0]),
        "lag_final": float(lag_final),
        "relayed_bytes": float(relayed),
        "versions_published": float(pub.published),
    }


def run_freshness_stage(template, serving_kw, *, duration_s: float,
                        publish_interval: float, change_frac: float
                        ) -> Dict[str, float]:
    """Root -> replica -> replica chain under a live publisher: the
    freshness plane measured at both depths. Edge readers at hop 1 and
    hop 2 request FRS1 trailers with every read; a per-core
    ``FreshnessTracker`` folds the relayed birth records into per-hop
    relay latency windows. All clocks are one host here, so ages are
    real wall deltas (accurate to the followers' poll interval — the
    lower-envelope skew fit absorbs the minimum poll delay)."""
    from pytorch_ps_mpi_tpu.serving import (
        FollowerLoop,
        ServingCore,
        ServingReader,
    )
    from pytorch_ps_mpi_tpu.telemetry.freshness import FreshnessTracker

    root = ServingCore(None, {"read_port": 0, "serving_kw": serving_kw},
                       template=template)
    pub = Publisher(root, template, change_frac, publish_interval)
    pub.publish_once()
    core_a = ServingCore(None, {"read_port": 0, "serving_kw": serving_kw},
                         template=template)
    core_b = ServingCore(None, {"read_port": 0, "serving_kw": serving_kw},
                         template=template)
    tr_b = FreshnessTracker(core=core_b, name="bench-hop2")
    loops = [
        FollowerLoop(core_a, "127.0.0.1", root.read_port,
                     template=template, poll_s=publish_interval / 4,
                     serving_kw=serving_kw).start(),
        FollowerLoop(core_b, "127.0.0.1", core_a.read_port,
                     template=template, poll_s=publish_interval / 4,
                     serving_kw=serving_kw).start(),
    ]
    deadline = time.time() + 30
    while (any(c.latest_version(None) == 0 for c in (core_a, core_b))
           and time.time() < deadline):
        time.sleep(0.01)
    if any(c.latest_version(None) == 0 for c in (core_a, core_b)):
        raise RuntimeError("freshness chain never caught the snapshot")
    pub.start()

    ages: Dict[int, List[float]] = {1: [], 2: []}
    visible: Dict[int, List[float]] = {1: [], 2: []}
    rejects = [0]

    def drive(depth: int, core) -> None:
        from pytorch_ps_mpi_tpu.telemetry.freshness import (
            visible_latency_ms,
        )

        r = ServingReader("127.0.0.1", core.read_port, template,
                          serving_kw=serving_kw, timeout=30.0)
        t_end = time.perf_counter() + duration_s
        try:
            while time.perf_counter() < t_end:
                _, ver = r.read_params()
                doc = r.fresh
                if doc is not None and doc["version"] == ver \
                        and doc["hop_count"] == depth:
                    ages[depth].append(r.fresh_age_ms())
                    vis = visible_latency_ms(doc)
                    if vis is not None:
                        visible[depth].append(vis)
                time.sleep(publish_interval * 0.5)
        finally:
            rejects[0] += r.fresh_rejects
            r.close()

    drivers = [threading.Thread(target=drive, args=(d, c))
               for d, c in ((1, core_a), (2, core_b))]
    for t in drivers:
        t.start()
    for t in drivers:
        t.join(timeout=duration_s + 60)
    pub.stop()
    hopq = tr_b.hop_quantiles_ms()
    for fl in loops:
        fl.close()
    for c in (core_b, core_a, root):
        c.close()
    out: Dict[str, float] = {
        "versions_published": float(pub.published),
        "fresh_rejects": float(rejects[0]),
    }
    for d in (1, 2):
        a = np.array(ages[d]) if ages[d] else np.array([0.0])
        v = np.array(visible[d]) if visible[d] else np.array([0.0])
        out[f"hop{d}_deliveries"] = float(len(ages[d]))
        out[f"hop{d}_age_p50_ms"] = float(np.percentile(a, 50))
        out[f"hop{d}_age_p95_ms"] = float(np.percentile(a, 95))
        out[f"hop{d}_visible_p50_ms"] = float(np.percentile(v, 50))
        out[f"hop{d}_visible_p95_ms"] = float(np.percentile(v, 95))
        q = hopq.get(d) or {}
        out[f"hop{d}_relay_p50_ms"] = float(q.get("p50", 0.0))
        out[f"hop{d}_relay_p95_ms"] = float(q.get("p95", 0.0))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--quick", action="store_true",
                    help="CI scale: fewer readers, shorter stages")
    ap.add_argument("--readers", type=int, default=None)
    ap.add_argument("--params", type=int, default=200_000)
    ap.add_argument("--change-frac", type=float, default=0.005,
                    help="fraction of params changed per version (the "
                         "small-delta regime)")
    ap.add_argument("--freshness", action="store_true",
                    help="run the 1/2-hop freshness propagation stage")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    quick = args.quick
    readers = args.readers or (40 if quick else 200)
    template = build_template(args.params)
    serving_kw = {"ring": 16, "admission_depth": 32,
                  "retry_after_s": 0.02, "delta_bucket_mb": 1.0}
    # stages 1-2 pin the Python selectors loop (the legacy baseline the
    # trajectory rows track); stage 3 re-runs the sweep natively
    cfg = {"read_port": 0, "read_native": False, "serving_kw": serving_kw}

    from pytorch_ps_mpi_tpu.serving import ServingCore
    from pytorch_ps_mpi_tpu.serving.native_read import get_read_lib
    from pytorch_ps_mpi_tpu.utils.native import fast_path_disabled

    rows: List[dict] = []

    def metric(name: str, value: float, unit: str = "") -> None:
        rows.append({"metric": f"read_bench.{name}", "value": value,
                     "unit": unit})
        print(f"  {name:<28} {value:>12.3f} {unit}")

    t_wall0 = time.perf_counter()
    print(f"read_bench: {readers} readers, {args.params} params, "
          f"change_frac {args.change_frac}")

    # -- stage 1: delta economics ----------------------------------------
    core = ServingCore(None, cfg, template=template)
    econ = run_delta_stage(
        core, template, serving_kw,
        readers=readers, reads_each=6 if quick else 12,
        change_frac=args.change_frac, publish_interval=0.1)
    print("stage 1 — delta economics:")
    for k, v in econ.items():
        metric(k, v, "bytes" if k.endswith("bytes") else
               ("x" if k.endswith("_x") else ""))
    core.close()

    # -- stage 2: saturation sweep (Python loop) -------------------------
    n_flat = sum(int(np.prod(v.shape)) for v in template.values())
    sweep = ([100, 400, 1200] if quick
             else [200, 800, 2400, 6000, 12000])
    dur = 2.0 if quick else 4.0

    def run_sweep(label: str, core_cfg: dict) -> List[dict]:
        core = ServingCore(None, core_cfg, template=template)
        want_native = core_cfg.get("read_native") not in (False, None)
        if core.read_native is not want_native:
            raise RuntimeError(
                f"{label}: expected read_native={want_native} but the "
                f"core armed read_native={core.read_native}")
        core.publish(flat=np.zeros(n_flat, np.float32))
        print(f"{label} (full reads, open-loop):")
        out = []
        for rps in sweep:
            row = run_saturation(core, template, readers=readers,
                                 offered_rps=rps, duration_s=dur)
            out.append(row)
            print(f"  offered {row['offered_rps']:>7.0f}/s  achieved "
                  f"{row['achieved_rps']:>7.0f}/s  service p50 "
                  f"{row['p50_ms']:6.2f} ms  p99 {row['p99_ms']:7.2f} ms  "
                  f"sched p99 {row['sched_p99_ms']:8.2f} ms  "
                  f"shed {row['shed']:>6.0f} ({row['shed_frac']:.1%})")
        core.close()
        return out

    curve = run_sweep("stage 2 — saturation sweep, Python loop", cfg)
    for row in curve:
        rows.append({"metric": "read_bench.saturation", **row})

    # -- stage 3: the same sweep through the native tier -----------------
    native_armed = not fast_path_disabled() and get_read_lib() is not None
    ncurve: List[dict] = []
    if native_armed:
        ncurve = run_sweep(
            "stage 3 — saturation sweep, native C++ tier",
            {**cfg, "read_native": True})
        for row in ncurve:
            rows.append({"metric": "read_bench.saturation_native", **row})
    else:
        print("stage 3 — SKIPPED (native read tier unavailable)")

    # -- stage 4: follower replica tree ----------------------------------
    tree = run_replica_tree(
        template, serving_kw,
        readers_per=max(8, readers // (2 if quick else 1) // 3),
        offered_rps=sweep[-1] / 3.0, duration_s=dur,
        change_frac=args.change_frac, publish_interval=0.1)
    print("stage 4 — follower replica tree (1 root + 2 replicas):")
    for k, v in tree.items():
        metric(f"tree_{k}", v,
               "ms" if k.endswith("_ms") else
               ("bytes" if k.endswith("bytes") else ""))

    # -- stage 5: freshness propagation (1/2-hop) ------------------------
    fresh: Optional[Dict[str, float]] = None
    if args.freshness:
        fresh = run_freshness_stage(
            template, serving_kw, duration_s=dur,
            change_frac=args.change_frac, publish_interval=0.1)
        print("stage 5 — freshness propagation (root -> replica -> "
              "replica):")
        for k, v in fresh.items():
            metric(f"fresh_{k}", v, "ms" if k.endswith("_ms") else "")
    else:
        print("stage 5 — SKIPPED (pass --freshness)")

    # bounded-past-the-limit check: compare the SERVED p99 at the highest
    # offered load (where shedding is active) against the lowest load's
    p99_lo = curve[0]["p99_ms"]
    p99_hi = curve[-1]["p99_ms"]
    metric("p99_low_load_ms", p99_lo, "ms")
    metric("p99_max_load_ms", p99_hi, "ms")
    metric("achieved_max_rps", max(c["achieved_rps"] for c in curve),
           "ops/sec")
    metric("shed_at_max", curve[-1]["shed"])
    shed_frac_py = curve[-1]["shed_frac"]
    metric("shed_frac_at_max", shed_frac_py)
    np99_hi = shed_frac_nat = None
    if ncurve:
        np99_hi = ncurve[-1]["p99_ms"]
        shed_frac_nat = ncurve[-1]["shed_frac"]
        metric("native_p99_max_load_ms", np99_hi, "ms")
        metric("native_achieved_max_rps",
               max(c["achieved_rps"] for c in ncurve), "ops/sec")
        metric("native_shed_frac_at_max", shed_frac_nat)

    wall = time.perf_counter() - t_wall0
    metric("wall_s", wall, "s")

    # -- acceptance assertions -------------------------------------------
    ok = True
    if econ["delta_reduction_x"] < 5.0:
        print(f"FAIL: delta_reduction_x {econ['delta_reduction_x']:.1f} "
              "< 5", file=sys.stderr)
        ok = False
    # "no collapse": the SERVICE p99 of served requests past the
    # admission limit stays within a generous bound of the low-load p99
    # — the bounded backlog caps server-side queueing, shedding absorbs
    # the rest (the schedule-relative p99 necessarily grows once
    # achieved < offered; it is reported, not gated)
    bound = max(50.0 * max(p99_lo, 1.0), 500.0)
    if p99_hi > bound:
        print(f"FAIL: served p99 collapsed past the admission limit "
              f"({p99_hi:.1f} ms > bound {bound:.1f} ms)", file=sys.stderr)
        ok = False
    if np99_hi is not None:
        # the native tier obeys the same no-collapse bound, and its shed
        # fraction at the highest offered load must not EXCEED the
        # Python loop's (drains off the GIL: overload becomes
        # throughput, not sheds; small epsilon for scheduler noise)
        if np99_hi > bound:
            print(f"FAIL: native served p99 collapsed "
                  f"({np99_hi:.1f} ms > bound {bound:.1f} ms)",
                  file=sys.stderr)
            ok = False
        if shed_frac_nat > shed_frac_py + 0.05:
            print(f"FAIL: native shed fraction at max load "
                  f"({shed_frac_nat:.1%}) exceeds the Python loop's "
                  f"({shed_frac_py:.1%})", file=sys.stderr)
            ok = False
    if tree["lag_final"] > 2.0:
        print(f"FAIL: replica lag settled at {tree['lag_final']:.0f} "
              "versions (> 2) after the publisher stopped",
              file=sys.stderr)
        ok = False
    if fresh is not None:
        # sanity, not a latency SLO: both depths must actually deliver
        # trailers, none may be rejected, and the 2-hop birth records
        # must carry both relay hops' latencies
        if (fresh["hop1_deliveries"] < 1 or fresh["hop2_deliveries"] < 1
                or fresh["fresh_rejects"] > 0
                or fresh["hop2_relay_p50_ms"] <= 0.0):
            print("FAIL: freshness stage delivered no usable trailers "
                  f"({json.dumps(fresh)})", file=sys.stderr)
            ok = False

    os.makedirs(RESULTS_DIR, exist_ok=True)
    day = time.strftime("%Y-%m-%d")
    out = args.out or os.path.join(RESULTS_DIR, f"read_bench_{day}.jsonl")
    with open(out, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    # flat trajectory row for bench_gate
    with open(os.path.join(RESULTS_DIR, "read_bench.jsonl"), "a") as f:
        f.write(json.dumps({
            "bench": "read_bench", "t": time.time(),
            "wall_s": round(wall, 3),
            "delta_reduction_x": round(econ["delta_reduction_x"], 2),
            "p99_max_load_ms": round(p99_hi, 3),
            "achieved_max_rps": round(
                max(c["achieved_rps"] for c in curve), 1),
            "native_p99_max_load_ms": (round(np99_hi, 3)
                                       if np99_hi is not None else None),
            "native_shed_frac_at_max": (round(shed_frac_nat, 4)
                                        if shed_frac_nat is not None
                                        else None),
            "tree_p99_ms": round(tree["p99_ms"], 3),
            "tree_lag_final": tree["lag_final"],
            "fresh_hop1_age_p95_ms": (round(fresh["hop1_age_p95_ms"], 3)
                                      if fresh is not None else None),
            "fresh_hop2_age_p95_ms": (round(fresh["hop2_age_p95_ms"], 3)
                                      if fresh is not None else None),
            "readers": readers, "quick": int(quick),
        }) + "\n")
    print(f"wrote {out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
