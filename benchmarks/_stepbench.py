"""Shared train-step timing recipe for the model-family benches.

One implementation of the honest step measurement (bert_bench,
gpt_bench, and the bench.py model lines all need the same thing):
jit the step, pull measured FLOPs from XLA cost analysis, time one call
(wall, includes the tunnel fetch RTT) and a K-step fused ``lax.scan``
(device time per step, RTT-subtracted — ``utils/devtime.timed``), and
return the common emit fields. ``devtime``'s docstring forbids bench
consumers from re-rolling the timing recipe; this module is the one
place the *step-bench* variant of it lives.
"""

from __future__ import annotations

import time

import jax

from pytorch_ps_mpi_tpu.utils.devtime import (
    peak_flops_for,
    rtt_floor,
    rtt_subtracted_ms,
    safe_ratio,
    timed,
)


def step_timing_fields(train_step, params, state, batch, scan_k: int = 8,
                       reps: int = 5) -> dict:
    """Measure ``train_step(params, state, batch) -> (params, state, loss)``
    and return the shared metric fields (steps/sec in ``value``)."""
    fn = jax.jit(train_step)
    flops = 0.0
    compile_s = None
    try:
        t0 = time.perf_counter()
        compiled = fn.lower(params, state, batch).compile()
        # the single-step program's AOT compile wall — through the
        # tunnel's remote_compile this is what bounds a bench window,
        # and it is the number scan_layers exists to cut
        compile_s = round(time.perf_counter() - t0, 2)
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
    except Exception:
        pass

    @jax.jit
    def scanned(params, state, batch):
        def body(c, _):
            p, s, _ = train_step(c[0], c[1], batch)
            return (p, s), None

        (p, s), _ = jax.lax.scan(body, (params, state), None, length=scan_k)
        return p, s

    wall_s, dev_s = timed(
        lambda: fn(params, state, batch),
        lambda: scanned(params, state, batch),
        scan_k, reps=reps,
    )
    peak = peak_flops_for()
    return {
        "value": round(safe_ratio(1.0, dev_s), 3),
        "unit": "steps/sec",
        "step_ms_device": round(dev_s * 1e3, 2),
        "wall_ms_per_call": round(wall_s * 1e3, 2),
        "rtt_probe_ms": round(rtt_floor() * 1e3, 2),
        "rtt_subtracted_ms": rtt_subtracted_ms(),
        "flops_per_step": flops,
        "compile_s": compile_s,
        "mfu": round(safe_ratio(flops, dev_s * peak), 4) if peak else 0.0,
        "device_kind": jax.devices()[0].device_kind,
    }
