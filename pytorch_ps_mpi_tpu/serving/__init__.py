"""Parameter-serving read tier: versioned snapshots, delta reads,
admission control, and the reusable :class:`ServingCore`.

The write (gradient) path got PRs 1–6 of attention; this package is the
read side the north star's "millions of users" actually hit:

- :mod:`.snapshots` — immutable, refcounted, versioned snapshots in a
  ring of the last K publishes, fanned out zero-copy (``memoryview``);
- :mod:`.delta` — "I have v, give me v→latest" answered with a
  dtype-bucketed exact sparse delta (lossy codecs opt-in behind a
  fidelity probe), falling back to a full snapshot when v aged out;
- :mod:`.net` — the request/reply wire, an event-loop read server with
  bounded-admission load shedding + request coalescing, and the
  :class:`~.net.ServingReader` client;
- :mod:`.core` — :class:`ServingCore`, the extraction that lets the
  trainer serve loop, the sharded PS, and a read-only replica all run
  the same read tier (with per-tenant namespaces) and the same
  monitor/metrics plumbing.
"""

from pytorch_ps_mpi_tpu.serving.core import (
    DEFAULT_TENANT,
    SERVING_KNOBS,
    ServingCore,
)
from pytorch_ps_mpi_tpu.serving.delta import DELTA_KNOBS, DeltaCodec
from pytorch_ps_mpi_tpu.serving.follower import FollowerLoop
from pytorch_ps_mpi_tpu.serving.net import (
    ReadClient,
    ReadTierServer,
    ServingReader,
)
from pytorch_ps_mpi_tpu.serving.snapshots import Snapshot, SnapshotStore

__all__ = [
    "DEFAULT_TENANT",
    "SERVING_KNOBS",
    "ServingCore",
    "DELTA_KNOBS",
    "DeltaCodec",
    "FollowerLoop",
    "ReadClient",
    "ReadTierServer",
    "ServingReader",
    "Snapshot",
    "SnapshotStore",
]
