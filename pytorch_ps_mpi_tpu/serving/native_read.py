"""Native PSR1 read tier: ctypes bindings + the drop-in server wrapper.

The C++ half lives in ``native/tcpps.cpp`` (``tps_read_*`` exports): an
epoll event loop that accepts, validates, and answers PSR1 reads
entirely in C++ — zero syscalls for idle readers, zero-copy ``writev``
of frozen snapshot/delta views, byte-identical replies to the
``serving/net.py`` selectors loop (the tested fallback, still armed by
``PS_NO_NATIVE`` or ``cfg["read_native"] = False``).

:class:`NativeReadServer` is the Python wrapper with the same surface
:class:`~.net.ReadTierServer` exposes to :class:`~.core.ServingCore`
(``port`` / ``queue_depth()`` / ``connections()`` / ``close()``), plus
the publish hook that makes version-window boundaries the ONLY Python
involvement: on every :meth:`~.core.ServingCore.publish` it pins the
frozen snapshot, pre-encodes the ring's ``base -> latest`` deltas once
(the native tier then fans each encode out to every coalesced reader),
and hands ``(ptr, len, token)`` views to C++. When the last in-flight
send of a superseded buffer drains, its token surfaces through
``tps_read_released`` and the pump thread fires the release hook — the
ring unpin the Python loop ran in ``done()``.

Threading contract (why the ``thread-affinity`` pragmas below are
sound, unlike the single-threaded TPS1/psqueue handles that rule
protects): every ``tps_read_*`` entry point locks the server's own
mutex in C++; the pump thread, the publish thread, and metrics scrape
threads are all sanctioned callers by design.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Callable, Dict, Optional

import numpy as np

_read_lib: Optional[ctypes.CDLL] = None
_read_lib_failed = False


class _ReadStats(ctypes.Structure):
    """Mirror of native/tcpps.cpp ReadStats (128 bytes, packed)."""

    _pack_ = 1
    _fields_ = [
        ("conns", ctypes.c_uint64),
        ("accepted_total", ctypes.c_uint64),
        ("pending", ctypes.c_uint64),
        ("reads_total", ctypes.c_uint64),
        ("reads_full", ctypes.c_uint64),
        ("reads_delta", ctypes.c_uint64),
        ("reads_not_modified", ctypes.c_uint64),
        ("reads_shed", ctypes.c_uint64),
        ("reads_error", ctypes.c_uint64),
        ("rejected_frames", ctypes.c_uint64),
        ("eof_mid_request", ctypes.c_uint64),
        ("coalesce_hits", ctypes.c_uint64),
        ("delta_bytes_saved", ctypes.c_uint64),
        ("bytes_sent", ctypes.c_uint64),
        ("pump_calls", ctypes.c_uint64),
        ("pump_ns", ctypes.c_uint64),
    ]


assert ctypes.sizeof(_ReadStats) == 128


class _ReadFreshStats(ctypes.Structure):
    """Mirror of native/tcpps.cpp ReadFreshStats (32 bytes, packed)."""

    _pack_ = 1
    _fields_ = [
        ("latest_version", ctypes.c_uint64),
        ("last_publish_wall", ctypes.c_double),
        ("fresh_replies", ctypes.c_uint64),
        ("min_have_version", ctypes.c_uint64),
    ]


assert ctypes.sizeof(_ReadFreshStats) == 32


def get_read_lib() -> Optional[ctypes.CDLL]:
    """Build (once) and load the ``tps_read_*`` entry points from
    native/tcpps.cpp; None without a toolchain or when the cached
    library predates the read tier (the mtime rebuild makes that a
    hand-copied-library corner case)."""
    global _read_lib, _read_lib_failed
    if _read_lib is not None:
        return _read_lib
    if _read_lib_failed:
        return None
    from pytorch_ps_mpi_tpu.utils.native import build_and_load

    lib = build_and_load("tcpps.cpp")
    if lib is None:
        _read_lib_failed = True
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    try:
        lib.tps_read_create.restype = ctypes.c_void_p
        lib.tps_read_create.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                        ctypes.c_uint64, ctypes.c_double,
                                        ctypes.c_char_p]
        lib.tps_read_port.restype = ctypes.c_uint16
        lib.tps_read_port.argtypes = [ctypes.c_void_p]
        lib.tps_read_publish.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, u8p,
            ctypes.c_uint64, ctypes.c_uint64]
        lib.tps_read_add_delta.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, u8p,
            ctypes.c_uint64, ctypes.c_uint64]
        lib.tps_read_pump.restype = ctypes.c_int
        lib.tps_read_pump.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tps_read_released.restype = ctypes.c_int
        lib.tps_read_released.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        lib.tps_read_stats.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(_ReadStats)]
        lib.tps_read_set_fresh.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, u8p, ctypes.c_uint64,
            ctypes.c_double]
        lib.tps_read_fresh_stats.restype = ctypes.c_int
        lib.tps_read_fresh_stats.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(_ReadFreshStats)]
        lib.tps_read_set_admission.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_double]
        lib.tps_read_wake.argtypes = [ctypes.c_void_p]
        lib.tps_read_close.argtypes = [ctypes.c_void_p]
    except AttributeError:
        _read_lib_failed = True
        return None
    _verify_read_abi(lib)
    _read_lib = lib
    return _read_lib


def _verify_read_abi(lib: ctypes.CDLL) -> None:
    """Load-time twin of the abi-drift rule for the read plane: re-read
    the PSR1 struct sizes/magic from the loaded library and refuse it on
    any mismatch with serving/net.py."""
    from pytorch_ps_mpi_tpu.serving import net as _net

    lib.tps_abi_psr_magic.restype = ctypes.c_uint32
    lib.tps_abi_psr_req_bytes.restype = ctypes.c_uint32
    lib.tps_abi_psr_rep_bytes.restype = ctypes.c_uint32
    lib.tps_abi_read_stats_bytes.restype = ctypes.c_uint32
    lib.tps_abi_read_fresh_stats_bytes.restype = ctypes.c_uint32
    checks = (
        ("PSR1 magic", int(lib.tps_abi_psr_magic()), _net.MAGIC),
        ("PSR1 request bytes", int(lib.tps_abi_psr_req_bytes()),
         _net._REQ.size),
        ("PSR1 reply bytes", int(lib.tps_abi_psr_rep_bytes()),
         _net._REP.size),
        ("ReadStats bytes", int(lib.tps_abi_read_stats_bytes()),
         ctypes.sizeof(_ReadStats)),
        ("ReadFreshStats bytes", int(lib.tps_abi_read_fresh_stats_bytes()),
         ctypes.sizeof(_ReadFreshStats)),
    )
    for what, native_v, py_v in checks:
        if native_v != py_v:
            raise RuntimeError(
                f"native/tcpps.cpp read-tier ABI drift: {what} is "
                f"{native_v} in the loaded library but {py_v} on the "
                "Python side — rebuild native/_build or reconcile")


class NativeReadServer:
    """The C++ read tier behind :class:`~.core.ServingCore`.

    Same construction/teardown surface as
    :class:`~.net.ReadTierServer`; the pump runs on a daemon thread that
    blocks in ``tps_read_pump`` (GIL released) and drains release
    tokens. Raises ``RuntimeError`` when the native listener cannot be
    created — the core then falls back to the Python loop.
    """

    native = True

    def __init__(self, core, port: int = 0, host: str = "0.0.0.0"):
        lib = get_read_lib()
        if lib is None:
            raise RuntimeError("native read tier unavailable")
        self.core = core
        self._lib = lib
        self._handle = lib.tps_read_create(  # psanalyze: ok thread-affinity
            host.encode(), int(port), int(core.admission_depth),
            float(core.retry_after_s), core.default_tenant.encode())
        if not self._handle:
            raise RuntimeError(
                f"tps_read_create failed (host {host!r} port {port})")
        self.port = int(lib.tps_read_port(self._handle))  # psanalyze: ok thread-affinity
        # token -> release hook (ring unpin / delta-buffer drop); shared
        # between the publish thread (insert) and the pump thread (pop)
        self._pins: Dict[int, Callable[[], None]] = {}
        self._pins_lock = threading.Lock()
        self._next_token = 1
        self._final_stats: Dict[str, int] = {}
        # tenants this wrapper has published (the C API is per-tenant;
        # fresh_stats_all iterates this set) + the post-close capture
        self._tenants: set = {core.default_tenant}
        self._final_fresh: Dict[str, Dict[str, float]] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump_loop, daemon=True,
            name=f"read-native:{self.port}")
        self._thread.start()

    # -- pump thread ------------------------------------------------------
    def _pump_loop(self) -> None:
        toks = (ctypes.c_uint64 * 64)()
        while not self._stop.is_set():
            self._lib.tps_read_pump(self._handle, 50)  # psanalyze: ok thread-affinity
            while True:
                n = self._lib.tps_read_released(  # psanalyze: ok thread-affinity
                    self._handle, toks, 64)
                if n <= 0:
                    break
                for i in range(n):
                    self._release(int(toks[i]))

    def _release(self, token: int) -> None:
        with self._pins_lock:
            hook = self._pins.pop(token, None)
        if hook is not None:
            hook()

    def _token(self, hook: Callable[[], None]) -> int:
        with self._pins_lock:
            tok = self._next_token
            self._next_token += 1
            self._pins[tok] = hook
        return tok

    # -- publish boundary -------------------------------------------------
    def on_publish(self, tenant: str, version: int, store,
                   fresh: bytes = b"", publish_wall: float = 0.0) -> None:
        """Version-window boundary: pin the new latest, pre-encode the
        ring's deltas, install everything natively (including the FRS1
        freshness trailer — copied by C++, no pin needed). Called from
        the publish path right after ``store.put``."""
        self._tenants.add(tenant)
        latest = store.acquire(int(version))
        if latest is None:
            return  # evicted already (ring 1 races) — nothing to serve
        u8p = ctypes.POINTER(ctypes.c_uint8)
        flat_u8 = latest.flat.view(np.uint8)
        tok = self._token(lambda s=latest, st=store: st.release(s))
        self._lib.tps_read_publish(  # psanalyze: ok thread-affinity
            self._handle, tenant.encode(), int(version),
            flat_u8.ctypes.data_as(u8p), flat_u8.nbytes, tok)
        self.set_fresh(tenant, fresh, publish_wall)
        # pre-encode base -> latest for every ring-resident base: the
        # one encode per (base, latest) pair the Python path coalesces
        # lazily happens HERE, once, so serving it never touches Python
        try:
            codec = self.core._delta(tenant)
        except ValueError:
            return  # no template recorded: full reads only
        for base_version in store.versions():
            if base_version >= int(version):
                continue
            base = store.acquire(base_version)
            if base is None:
                continue
            try:
                payload = codec.encode(base.flat, latest.flat)
            except Exception:
                payload = None  # size drift etc: full fallback
            finally:
                store.release(base)
            if payload is None:
                continue  # delta not worth it: native serves full
            pay_u8 = payload.view(np.uint8)
            dtok = self._token(lambda p=payload: None)  # keepalive ref
            self._lib.tps_read_add_delta(  # psanalyze: ok thread-affinity
                self._handle, tenant.encode(), int(base_version),
                pay_u8.ctypes.data_as(u8p), pay_u8.nbytes, dtok)

    # -- ReadTierServer surface -------------------------------------------
    def stats(self) -> Dict[str, int]:
        # after close() the C++ counters are gone — serve the final block
        # captured at teardown so post-run accounting (server.metrics()
        # after server.close()) matches the Python loop, whose counters
        # live on the core object and survive teardown
        if self._handle is None:
            return dict(self._final_stats)
        st = _ReadStats()
        self._lib.tps_read_stats(self._handle, ctypes.byref(st))  # psanalyze: ok thread-affinity
        return {name: int(getattr(st, name)) for name, _ in st._fields_}

    def set_fresh(self, tenant: str, fresh: bytes,
                  publish_wall: float = 0.0) -> None:
        """Install (or clear, ``b""``) the FRS1 trailer the C++ tier
        attaches to want_fresh FULL/DELTA replies for ``tenant``."""
        if self._handle is None:
            return
        buf = (ctypes.c_uint8 * max(len(fresh), 1)).from_buffer_copy(
            fresh or b"\x00")
        self._lib.tps_read_set_fresh(  # psanalyze: ok thread-affinity
            self._handle, tenant.encode(), buf, len(fresh),
            float(publish_wall))

    def fresh_stats_all(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant freshness export: latest_version /
        last_publish_wall / fresh_replies / min_have_version. Serves the
        teardown capture after :meth:`close` (same discipline as
        :meth:`stats`), so post-run accounting still sees it."""
        if self._handle is None:
            return {t: dict(v) for t, v in self._final_fresh.items()}
        out: Dict[str, Dict[str, float]] = {}
        fs = _ReadFreshStats()
        for tenant in sorted(self._tenants):
            ok = self._lib.tps_read_fresh_stats(  # psanalyze: ok thread-affinity
                self._handle, tenant.encode(), ctypes.byref(fs))
            if not ok:
                continue
            out[tenant] = {
                "latest_version": int(fs.latest_version),
                "last_publish_wall": float(fs.last_publish_wall),
                "fresh_replies": int(fs.fresh_replies),
                "min_have_version": int(fs.min_have_version),
            }
        return out

    def queue_depth(self) -> int:
        return self.stats()["pending"]

    def connections(self) -> int:
        return self.stats()["conns"]

    def set_admission(self, depth: int, retry_after_s: float) -> None:
        self._lib.tps_read_set_admission(  # psanalyze: ok thread-affinity
            self._handle, int(depth), float(retry_after_s))

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._lib.tps_read_wake(self._handle)  # psanalyze: ok thread-affinity
        self._thread.join(timeout=5)
        self._final_stats = self.stats()
        self._final_fresh = self.fresh_stats_all()
        self._lib.tps_read_close(self._handle)  # psanalyze: ok thread-affinity
        self._handle = None
        # every pin the released queue never surfaced is dropped now —
        # the C++ side is gone, so no view can still be in flight
        with self._pins_lock:
            hooks = list(self._pins.values())
            self._pins.clear()
        for hook in hooks:
            hook()
