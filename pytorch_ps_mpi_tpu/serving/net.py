"""Read-tier wire: request/reply protocol, server event loop, reader client.

The transport half of the read tier. One event-loop thread
(:class:`ReadTierServer`, ``selectors``-based — hundreds of concurrent
reader connections cost one thread, not one thread each) accepts
connections, parses version-conditional read requests, applies
**admission control** (a bounded backlog; requests past
``admission_depth`` get an immediate retry-after reply instead of
queueing unboundedly — p99 stays bounded because excess load is shed at
the door, never absorbed), and answers through the
:class:`~.core.ServingCore`:

- **not-modified** when the reader's version is current (8-byte header
  reply, no payload);
- **delta** when the reader's base version is still in the snapshot
  ring (codec-encoded by :class:`~.delta.DeltaCodec`, encoded ONCE per
  (base, latest) pair and fanned out to every coalesced reader);
- **full** otherwise — the payload is the snapshot's frozen buffer sent
  as a zero-copy ``memoryview`` (refcount-pinned until the last byte is
  flushed), never an intermediate copy.

Reply headers are assembled in a small **preallocated buffer pool**
(returned to the pool when drained) so the steady-state serving path
allocates nothing per request.

The loop thread touches ONLY Python/numpy state (the snapshot store and
counters) — never a native transport handle, preserving the PR 3/4
discipline that keeps the shm/tcp pumps single-threaded.

Protocol (little-endian)::

  request:  u32 magic 'PSR1' | u8 op (1=READ) | u8 flags (bit0
            want_delta, bit1 want_fresh) | u16 tenant_len
            | u64 have_version | tenant utf-8 bytes
  reply:    u32 magic | u8 kind (0 full / 1 delta / 2 not-modified /
            3 retry / 4 error) | u8 fresh_len | u16 pad | u64 version
            | u64 base_version | f64 retry_after_s | u64 payload_len
            | payload | fresh trailer (fresh_len bytes)

The ``fresh_len`` byte reuses the header's previously-zero pad byte:
when the reader sets ``FLAG_WANT_FRESH`` and the reply delivers a
version (full/delta), an FRS1 freshness trailer (see
:mod:`pytorch_ps_mpi_tpu.telemetry.freshness`) rides AFTER the payload
and its length rides in ``fresh_len``. Readers that never set the flag
receive byte-identical replies to the pre-freshness wire — the
native-vs-Python reply-parity invariant (and every old reader) is
untouched. The trailer is capped well under 255 bytes by the hop cap.

Client side: :class:`ReadClient` is the one-request/one-reply socket
primitive; :class:`ServingReader` is the stateful reader the tests and
the load bench use — it remembers the version it holds, asks for
deltas, applies them locally, honors retry-after on shed, and falls
back to full reads when its version aged out of the ring.
"""

from __future__ import annotations

import collections
import selectors
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

PyTree = Any

MAGIC = 0x31525350  # "PSR1"
OP_READ = 1
FLAG_WANT_DELTA = 1
FLAG_WANT_FRESH = 2

KIND_FULL, KIND_DELTA, KIND_NOT_MODIFIED, KIND_RETRY, KIND_ERROR = range(5)
KIND_NAMES = {KIND_FULL: "full", KIND_DELTA: "delta",
              KIND_NOT_MODIFIED: "not_modified", KIND_RETRY: "retry",
              KIND_ERROR: "error"}

_REQ = struct.Struct("<IBBHQ")
_REP = struct.Struct("<IBBHQQdQ")


def pack_request(have_version: int = 0, want_delta: bool = True,
                 tenant: str = "", want_fresh: bool = False) -> bytes:
    t = tenant.encode()
    flags = ((FLAG_WANT_DELTA if want_delta else 0)
             | (FLAG_WANT_FRESH if want_fresh else 0))
    return _REQ.pack(MAGIC, OP_READ, flags, len(t), int(have_version)) + t


class _BufferPool:
    """Preallocated reply-header buffers, recycled when a send drains —
    the read tier's steady state allocates no per-request header bytes."""

    def __init__(self, size: int = _REP.size, prealloc: int = 64):
        self.size = int(size)
        self._free: List[bytearray] = [bytearray(self.size)
                                       for _ in range(prealloc)]
        self._lock = threading.Lock()
        self.allocations = prealloc

    def get(self) -> bytearray:
        with self._lock:
            if self._free:
                return self._free.pop()
            self.allocations += 1
        return bytearray(self.size)

    def put(self, buf: bytearray) -> None:
        with self._lock:
            self._free.append(buf)


class _Conn:
    __slots__ = ("sock", "rx", "tx", "closing")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rx = bytearray()
        # tx: deque of [memoryview, on_drained] — on_drained releases a
        # pinned snapshot or returns a pooled header buffer
        self.tx: collections.deque = collections.deque()
        self.closing = False


class ReadTierServer:
    """Event-loop read server over a :class:`~.core.ServingCore`.

    ``port=0`` auto-assigns (read back via ``.port``). ``close()`` stops
    the loop thread and closes every connection.
    """

    def __init__(self, core, port: int = 0, host: str = "0.0.0.0",
                 max_per_tick: int = 64):
        self.core = core
        self.max_per_tick = int(max_per_tick)
        self._pool = _BufferPool()
        self._sel = selectors.DefaultSelector()
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, int(port)))
        self._listen.listen(256)
        self._listen.setblocking(False)
        self.port = int(self._listen.getsockname()[1])
        self._sel.register(self._listen, selectors.EVENT_READ, None)
        # admission backlog: parsed-but-unanswered requests. Depth past
        # the core's admission_depth is shed at PARSE time.
        self._backlog: collections.deque = collections.deque()
        # torn-frame accounting (same fields as the native tier's
        # ReadStats): bad-magic/op requests and peers that vanished with
        # a partial request still buffered
        self.rejected_frames = 0
        self.eof_mid_request = 0
        self._conns: Dict[socket.socket, _Conn] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"read-tier:{self.port}")
        self._thread.start()

    # -- loop -------------------------------------------------------------
    def queue_depth(self) -> int:
        return len(self._backlog)

    def connections(self) -> int:
        return len(self._conns)

    def _loop(self) -> None:
        while not self._stop.is_set():
            # never sleep while admitted requests are still queued: a
            # burst deeper than max_per_tick drains in back-to-back
            # iterations instead of one 50 ms select timeout per batch
            events = self._sel.select(
                timeout=0.0 if self._backlog else 0.05)
            for key, mask in events:
                if key.fileobj is self._listen:
                    self._accept()
                    continue
                conn = key.data
                if mask & selectors.EVENT_READ:
                    self._readable(conn)
                if mask & selectors.EVENT_WRITE:
                    self._flush(conn)
            self._process_backlog()
        # teardown on the loop thread — no cross-thread socket races
        for conn in list(self._conns.values()):
            self._drop(conn)
        try:
            self._sel.unregister(self._listen)
        except Exception:
            pass
        self._listen.close()
        self._sel.close()

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _drop(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except Exception:
            pass
        # run every pending drain hook: pinned snapshots must be released
        # even when the reader disappeared mid-send
        while conn.tx:
            _, done = conn.tx.popleft()
            if done is not None:
                done()
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _readable(self, conn: _Conn) -> None:
        try:
            chunk = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            if conn.rx:
                self.eof_mid_request += 1
            self._drop(conn)
            return
        if not chunk:
            if conn.rx:
                # peer hung up mid-frame: a partial request was buffered
                self.eof_mid_request += 1
            self._drop(conn)
            return
        conn.rx += chunk
        while True:
            req = self._parse_one(conn)
            if req is None:
                break
            if len(self._backlog) >= self.core.admission_depth:
                # admission control: shed at the door with an explicit
                # retry-after — the backlog never grows past the knob,
                # so queued work (and reply latency) stays bounded
                self.core.note_shed()
                self._reply(conn, KIND_RETRY, self.core.latest_version(
                    req[2]), 0, None,
                    retry_after=self.core.retry_after_s)
            else:
                self._backlog.append((conn, req))

    def _parse_one(self, conn: _Conn
                   ) -> Optional[Tuple[int, bool, str, bool]]:
        """One complete request off the rx buffer, or None."""
        if len(conn.rx) < _REQ.size:
            return None
        magic, op, flags, tlen, have = _REQ.unpack_from(conn.rx, 0)
        if magic != MAGIC or op != OP_READ:
            self.rejected_frames += 1
            conn.rx.clear()
            self._reply(conn, KIND_ERROR, 0, 0, b"bad request magic/op")
            conn.closing = True
            return None
        total = _REQ.size + tlen
        if len(conn.rx) < total:
            return None
        tenant = bytes(conn.rx[_REQ.size:total]).decode(errors="replace")
        del conn.rx[:total]
        return (int(have), bool(flags & FLAG_WANT_DELTA), tenant,
                bool(flags & FLAG_WANT_FRESH))

    def _process_backlog(self) -> None:
        for _ in range(min(self.max_per_tick, len(self._backlog))):
            conn, (have, want_delta, tenant, want_fresh) = (
                self._backlog.popleft())
            if conn.sock not in self._conns:
                continue  # reader went away while queued
            t0 = time.perf_counter()
            fresh = b""
            try:
                kind, version, base, payload, done = self.core.handle_read(
                    have_version=have, want_delta=want_delta,
                    tenant=tenant or None)
                if want_fresh and kind in (KIND_FULL, KIND_DELTA):
                    # the trailer must describe exactly the version this
                    # reply delivers — a publish racing in between
                    # yields b"" (no trailer) rather than a stale stamp
                    fresh = self.core.fresh_trailer(tenant or None,
                                                    version)
            except Exception as e:
                # one bad request/publish must never kill the loop thread
                # serving everyone else: answer with an error and move on
                kind, version, base, done = KIND_ERROR, 0, 0, None
                payload = f"{type(e).__name__}: {e}".encode()
            self._reply(conn, kind, version, base, payload,
                        done=done,
                        retry_after=(self.core.retry_after_s
                                     if kind == KIND_RETRY else 0.0),
                        fresh=fresh)
            self.core.observe_read(time.perf_counter() - t0)

    def _reply(self, conn: _Conn, kind: int, version: int, base: int,
               payload, done=None, retry_after: float = 0.0,
               fresh: bytes = b"") -> None:
        if isinstance(payload, (bytes, bytearray)):
            payload = memoryview(payload)
        elif isinstance(payload, np.ndarray):
            payload = memoryview(payload.view(np.uint8))
        plen = payload.nbytes if payload is not None else 0
        hdr = self._pool.get()
        _REP.pack_into(hdr, 0, MAGIC, kind, len(fresh), 0, int(version),
                       int(base), float(retry_after), plen)
        pool = self._pool
        conn.tx.append((memoryview(hdr), lambda b=hdr: pool.put(b)))
        if payload is not None:
            # zero-copy: the payload rides as a view of the frozen
            # snapshot / cached delta buffer; `done` un-pins it after
            # the last byte goes out
            conn.tx.append((payload, done))
        elif done is not None:
            done()
        if fresh:
            # freshness trailer after the payload; tiny and immutable,
            # so it rides as its own bytes object with no drain hook
            conn.tx.append((memoryview(fresh), None))
        self._want_write(conn)
        self._flush(conn)

    def _want_write(self, conn: _Conn) -> None:
        try:
            self._sel.modify(conn.sock, selectors.EVENT_READ
                             | selectors.EVENT_WRITE, conn)
        except Exception:
            pass

    def _flush(self, conn: _Conn) -> None:
        while conn.tx:
            mv, done = conn.tx[0]
            try:
                n = conn.sock.send(mv)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._drop(conn)
                return
            if n < len(mv):
                conn.tx[0] = (mv[n:], done)
                return
            conn.tx.popleft()
            if done is not None:
                done()
        # drained: back to read-only interest
        try:
            self._sel.modify(conn.sock, selectors.EVENT_READ, conn)
        except Exception:
            pass
        if conn.closing:
            self._drop(conn)

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=5)


class ReadClient:
    """Blocking one-request/one-reply client for the read-tier wire."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 tenant: str = ""):
        self.tenant = tenant
        #: raw FRS1 trailer from the last full/delta reply (b"" when the
        #: server sent none or the request didn't ask)
        self.last_fresh = b""
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        try:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._sock.settimeout(timeout)

    def _recv_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("read-tier server closed connection")
            out += chunk
        return bytes(out)

    def request(self, have_version: int = 0, want_delta: bool = True,
                want_fresh: bool = False
                ) -> Tuple[str, int, int, float, bytes]:
        """Returns ``(kind, version, base_version, retry_after_s,
        payload_bytes)`` — kind is one of full/delta/not_modified/retry/
        error. A freshness trailer, when requested and sent, lands in
        :attr:`last_fresh` (return shape stays stable for old callers)."""
        self._sock.sendall(pack_request(have_version, want_delta,
                                        self.tenant, want_fresh))
        hdr = self._recv_exact(_REP.size)
        magic, kind, fresh_len, _, version, base, retry_after, plen = (
            _REP.unpack(hdr))
        if magic != MAGIC:
            raise ConnectionError(f"bad reply magic 0x{magic:08x}")
        payload = self._recv_exact(plen) if plen else b""
        self.last_fresh = self._recv_exact(fresh_len) if fresh_len else b""
        name = KIND_NAMES.get(kind, "error")
        if name == "error":
            raise RuntimeError(
                f"read-tier error: {payload.decode(errors='replace')}")
        return name, int(version), int(base), float(retry_after), payload

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ServingReader:
    """Stateful parameter reader over the read tier.

    Holds (flat vector, version) between reads so every subsequent
    ``read_params`` is a conditional request: not-modified when current,
    a delta when the base is still in the server's ring, a full snapshot
    otherwise. Shed replies are honored by sleeping ``retry_after_s``
    and retrying (bounded by ``max_retries``) — the cooperative-backoff
    contract that keeps p99 bounded past the admission limit.
    """

    def __init__(self, host: str, port: int, template: PyTree,
                 tenant: str = "", timeout: float = 10.0,
                 want_delta: bool = True, max_retries: int = 100,
                 serving_kw: Optional[dict] = None,
                 want_fresh: bool = True):
        from pytorch_ps_mpi_tpu.serving.delta import DeltaCodec

        self.client = ReadClient(host, port, timeout=timeout, tenant=tenant)
        self.template = template
        self.want_delta = bool(want_delta)
        self.want_fresh = bool(want_fresh)
        self.max_retries = int(max_retries)
        self.delta = DeltaCodec.from_knobs(template, serving_kw or {})
        self.version = 0
        self._flat: Optional[np.ndarray] = None
        self._tree: Optional[PyTree] = None
        # accounting (the load bench reads these)
        self.reads = 0
        self.full_reads = 0
        self.delta_reads = 0
        self.not_modified = 0
        self.shed_retries = 0
        self.bytes_received = 0
        # freshness: the last version delivery's FRS1 trailer (raw +
        # decoded), its local receive wall, and the (upstream stamp,
        # local recv) pairs the lower-envelope skew fit consumes
        self.fresh_raw = b""
        self.fresh: Optional[Dict[str, Any]] = None
        self.fresh_recv_wall = 0.0
        self.fresh_rejects = 0
        self._skew_pairs: collections.deque = collections.deque(maxlen=64)

    def read_params(self) -> Tuple[PyTree, int]:
        from pytorch_ps_mpi_tpu.parallel.dcn import _unflatten

        for _ in range(self.max_retries):
            kind, version, base, retry_after, payload = self.client.request(
                have_version=self.version if self._flat is not None else 0,
                want_delta=self.want_delta and self._flat is not None,
                want_fresh=self.want_fresh,
            )
            self.bytes_received += len(payload)
            if kind == "retry":
                self.shed_retries += 1
                time.sleep(max(retry_after, 0.001))
                continue
            self.reads += 1
            if kind == "not_modified":
                self.not_modified += 1
                return self._tree, self.version
            if kind == "delta":
                if base != self.version or self._flat is None:
                    raise RuntimeError(
                        f"delta against base {base} but reader holds "
                        f"{self.version}")
                self._flat = self.delta.apply(self._flat, payload)
                self.delta_reads += 1
            else:  # full
                self._flat = np.frombuffer(payload, np.float32).copy()
                self.full_reads += 1
            self.version = int(version)
            self._tree = _unflatten(self._flat, self.template)
            if self.client.last_fresh:
                self._note_fresh(self.client.last_fresh)
            return self._tree, self.version
        raise TimeoutError(
            f"read shed {self.shed_retries} times; gave up after "
            f"{self.max_retries} attempts")

    # -- freshness --------------------------------------------------------
    def _note_fresh(self, raw: bytes) -> None:
        from pytorch_ps_mpi_tpu.telemetry import freshness as _fresh

        try:
            doc = _fresh.unpack_trailer(raw)
        except ValueError:
            # truncated/corrupt trailer: reject, keep the previous one
            self.fresh_rejects += 1
            return
        now = time.time()
        self.fresh_raw, self.fresh, self.fresh_recv_wall = raw, doc, now
        # newest upstream-clock stamp in the trailer vs our receive wall
        stamp = (doc["hops"][-1]["arrival_wall"] if doc["hops"]
                 else doc["publish_wall"])
        self._skew_pairs.append((stamp, now))

    def reader_skew_s(self) -> float:
        """Lower-envelope estimate of (this reader's clock − the served
        trailer's last-hop clock); 0.0 until a pair exists. Absorbs the
        minimum poll+transfer delay — see the freshness module
        docstring's skew caveat."""
        if not self._skew_pairs:
            return 0.0
        from pytorch_ps_mpi_tpu.telemetry.lineage import (
            estimate_clock_offset,
        )

        return estimate_clock_offset(list(self._skew_pairs))

    def fresh_age_ms(self, now: Optional[float] = None) -> float:
        """Wall age (reader clock) of the version this reader currently
        holds; 0.0 before any trailer arrived."""
        if self.fresh is None:
            return 0.0
        from pytorch_ps_mpi_tpu.telemetry import freshness as _fresh

        t = time.time() if now is None else float(now)
        birth = _fresh.birth_wall_local(self.fresh) + self.reader_skew_s()
        return max(0.0, (t - birth) * 1e3)

    def fresh_delivery_row(self, reader: str = "reader") -> Dict[str, Any]:
        """One reader-delivery row for the freshness plane
        (:meth:`FreshnessTracker.note_delivery`'s input shape)."""
        doc = self.fresh
        return {
            "reader": reader,
            "tenant": self.client.tenant or "default",
            "version": self.version,
            "age_ms": round(self.fresh_age_ms(), 3),
            "hop_count": doc["hop_count"] if doc is not None else 0,
            "root_gen": doc["root_gen"] if doc is not None else 0,
            "t": time.time(),
        }

    def close(self) -> None:
        self.client.close()
