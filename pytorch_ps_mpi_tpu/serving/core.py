"""ServingCore: the reusable parameter-serving core the trainer loop sits on.

Before this module, ``async_train.serve()`` owned everything: the
poll→update→publish trainer loop, the monitor plumbing (health,
numerics, lineage), the metrics endpoint, AND the only read path (the
blocking full-snapshot ``read_params``). That made the read side
inseparable from training — a sharded PS or a read-only replica could
not serve parameters without dragging the trainer loop along.

:class:`ServingCore` is the extraction. It owns:

- the **snapshot store(s)** (:class:`~.snapshots.SnapshotStore`) — one
  refcounted ring of immutable versions per *tenant* namespace, so one
  core (and one sharded PS fleet) serves many jobs;
- the **read path** — version-conditional reads answered as
  not-modified / delta (:class:`~.delta.DeltaCodec`) / full, with an
  **encode cache** that coalesces identical-version requests into one
  encode per (base, latest) pair per published version;
- the **admission knobs** the network loop (:class:`~.net.ReadTierServer`)
  enforces — bounded backlog depth, retry-after period — plus every
  read-tier counter (``reads_total``, ``reads_shed``,
  ``coalesce_hits``, ``delta_bytes_saved``, latency histogram) surfaced
  through the canonical server metrics and the scrape registry;
- the **monitor plumbing** previously inlined in ``serve()`` — the
  HealthMonitor / NumericsMonitor / LineageTracker construction and the
  ``/metrics`` + ``/health`` HTTP endpoint — so every consumer of the
  core (trainer serve loop, shard server, read-only replica) gets the
  same observability surface from the same code.

``serve()`` is now a *user* of this core (zero behavior change: unarmed,
``publish`` degrades to the transport's own publish and no store
exists); ``parallel/sharded.server_main`` arms it per shard under a
per-shard tenant; ``examples/serve_readonly.py`` runs it with no server
and no trainer loop at all.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from pytorch_ps_mpi_tpu.serving.delta import DELTA_KNOBS, DeltaCodec
from pytorch_ps_mpi_tpu.serving.net import (
    KIND_DELTA,
    KIND_ERROR,
    KIND_FULL,
    KIND_NOT_MODIFIED,
    KIND_RETRY,
)
from pytorch_ps_mpi_tpu.serving.snapshots import SnapshotStore

PyTree = Any

DEFAULT_TENANT = "default"

#: serving knobs and their defaults (overridable via ``cfg["serving_kw"]``)
SERVING_KNOBS: Dict[str, Any] = {
    "ring": 8,              # snapshot ring depth (versions kept)
    "admission_depth": 64,  # read backlog bound; past it requests shed
    "retry_after_s": 0.05,  # suggested client backoff on a shed reply
    "rate_window_s": 5.0,   # reads/s window for the /health section
    **DELTA_KNOBS,
}

# read-latency buckets: 10 us in-process hits through multi-second stalls
_READ_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5)


def _seq_quantile(sorted_xs, q: float) -> float:
    """Nearest-rank quantile of an already-sorted sequence; 0.0 empty."""
    if not sorted_xs:
        return 0.0
    return float(sorted_xs[min(len(sorted_xs) - 1,
                               int(round(q * (len(sorted_xs) - 1))))])


class ServingCore:
    """Snapshots + read path + monitor plumbing, independent of any loop.

    ``server`` is a PS transport server (Shm/Tcp) or ``None`` for a
    standalone (read-only / test) core; ``cfg`` is the fleet config dict
    serve() already threads everywhere. The read tier arms on
    ``cfg["serving"]`` (in-process store only) or ``cfg["read_port"]``
    (store + network read server; 0 = auto-assign, read back via
    ``.read_port``). ``monitors=False`` skips the health/numerics/
    lineage construction for callers that build their own (the sharded
    shard-server does).
    """

    def __init__(self, server=None, cfg: Optional[Dict[str, Any]] = None,
                 *, template: PyTree = None, monitors: bool = True,
                 tenant: str = DEFAULT_TENANT, registry=None,
                 read_host: str = "0.0.0.0"):
        cfg = cfg or {}
        self.cfg = cfg
        self.server = server
        self.default_tenant = str(tenant)
        self.template = (template if template is not None
                         else getattr(server, "template", None))
        self.knobs = dict(SERVING_KNOBS)
        self.knobs.update(cfg.get("serving_kw") or {})
        self.armed = bool(cfg.get("serving")
                          or cfg.get("read_port") is not None)
        self.admission_depth = int(self.knobs["admission_depth"])
        self.retry_after_s = float(self.knobs["retry_after_s"])

        # -- monitor plumbing (the serve() extraction) --------------------
        self.health = None
        self.numerics = None
        self.lineage = None
        self.anatomy = None
        self.metrics_http_port: Optional[int] = None
        if server is not None:
            server.serving_core = self
            if monitors:
                self._build_monitors(cfg)

        if server is not None:
            self._reg = server.scrape_registry()
        else:
            from pytorch_ps_mpi_tpu.telemetry import MetricsRegistry

            self._reg = registry if registry is not None else MetricsRegistry()

        # -- read-path state ----------------------------------------------
        self._lock = threading.Lock()
        self._stores: Dict[str, SnapshotStore] = {}
        self._templates: Dict[str, PyTree] = {}
        self._deltas: Dict[str, DeltaCodec] = {}
        self._versions: Dict[str, int] = {}
        self._tenant_reads: Dict[str, int] = {}
        self._encode_cache: Dict[Tuple[str, int, int], np.ndarray] = {}
        self._rate: Dict[int, int] = {}  # monotonic-second -> read count
        self.reads_total = 0
        self.reads_full = 0
        self.reads_delta = 0
        self.reads_not_modified = 0
        self.reads_shed = 0
        self.coalesce_hits = 0
        self.delta_bytes_saved = 0
        self.ring_ageouts = 0
        self.delta_full_fallbacks = 0
        # -- freshness plane (telemetry.freshness) ------------------------
        # tenant -> {"version", "blob", "doc", "birth_local"}: the FRS1
        # birth record of the version currently being served, stamped at
        # publish (root) or relayed+extended (follower republish)
        self._fresh: Dict[str, Dict[str, Any]] = {}
        # recent publish->visible-here latencies (ms); empty at the root
        # (a hop-less birth has no propagation to measure)
        self._fresh_lat: collections.deque = collections.deque(maxlen=512)
        # smallest nonzero have_version answered per tenant — how stale
        # the laggiest reader was when it asked (native tier folds its
        # own pair in at teardown)
        self._fresh_min_have: Dict[str, int] = {}
        self.fresh_replies = 0  # replies that carried an FRS1 trailer
        # distinguishes server generations in birth records: a restarted
        # root's version numbers restart too, and readers must not join
        # ages across generations
        self.fresh_root_gen = int(time.time()) & 0xFFFFFFFF
        # optional monitor (telemetry.freshness.FreshnessTracker): set
        # directly on standalone cores, found via the transport server's
        # attribute otherwise — see arm_observability
        self.freshness_tracker = None
        self._read_hist = self._reg.histogram(
            "ps_read_seconds", _READ_BUCKETS,
            "read-tier request service time (parse -> reply queued)")
        self._t0 = time.monotonic()

        if self.armed and self.template is not None:
            # the default tenant's store exists from construction so the
            # first publish and the first read cannot race its creation
            self._ensure_tenant(self.default_tenant, self.template)

        self.read_server = None
        self.read_port: Optional[int] = None
        self.read_native = False
        # follower-tier accounting (set by serving.follower.FollowerLoop)
        self.replica_lag_versions = 0
        self.follower_bytes_relayed = 0
        if self.armed and cfg.get("read_port") is not None:
            rn = cfg.get("read_native", "auto")
            if rn not in (False, "off", 0):
                from pytorch_ps_mpi_tpu.utils.native import (
                    fast_path_disabled,
                )

                if not fast_path_disabled():
                    from pytorch_ps_mpi_tpu.serving.native_read import (
                        NativeReadServer,
                        get_read_lib,
                    )

                    if get_read_lib() is not None:
                        try:
                            self.read_server = NativeReadServer(
                                self, port=int(cfg["read_port"]),
                                host=read_host)
                            self.read_native = True
                        except RuntimeError:
                            self.read_server = None  # port taken etc.
            if self.read_server is None:
                from pytorch_ps_mpi_tpu.serving.net import ReadTierServer

                self.read_server = ReadTierServer(
                    self, port=int(cfg["read_port"]), host=read_host)
            self.read_port = self.read_server.port

        # standalone core (no transport server): serve /metrics + /health
        # from an endpoint of our own, same routes as PSServerTelemetry
        self._own_http = None
        self._fleet = None
        self._fleet_registration = None
        if server is None:
            http_port = cfg.get("metrics_port")
            if http_port is None:
                http_port = cfg.get("health_port")
            if http_port is not None:
                from pytorch_ps_mpi_tpu.telemetry.http_server import (
                    MetricsHTTPServer,
                )

                self._own_http = MetricsHTTPServer(
                    self._reg.prometheus_text, port=int(http_port),
                    routes={"/health": lambda: (json.dumps(
                        {"armed": False, "workers": [],
                         "ts": time.time(),
                         "uptime_s": round(
                             time.monotonic() - self._t0, 3),
                         "serving": self.serving_snapshot()}),
                        "application/json")},
                )
                self.metrics_http_port = self._own_http.port
            # the read tier joins the fleet pane like any server: with a
            # fleet_dir it registers its endpoint (default name "read-
            # tier") and serves the merged /fleet snapshot itself
            if cfg.get("fleet") or cfg.get("fleet_dir"):
                from pytorch_ps_mpi_tpu.telemetry import fleet as _fleet

                self._fleet = _fleet.FleetMonitor(
                    endpoints=cfg.get("fleet_endpoints"),
                    fleet_dir=cfg.get("fleet_dir"),
                    **(cfg.get("fleet_kw") or {}))
                if self._own_http is not None:
                    self._own_http.add_route(
                        "/fleet", self._fleet.render_http)
                    if cfg.get("fleet_dir"):
                        fname = str(cfg.get("fleet_name") or "read-tier")
                        _fleet.register_endpoint(
                            cfg["fleet_dir"], fname,
                            self._own_http.port,
                            role=cfg.get("fleet_role", "read"),
                            **(cfg.get("fleet_meta") or {}))
                        self._fleet_registration = (cfg["fleet_dir"],
                                                    fname)
        self._register_scrape()

    # -- monitor plumbing -------------------------------------------------
    def _build_monitors(self, cfg: Dict[str, Any]) -> None:
        """Health / numerics / lineage monitors + the metrics endpoint —
        verbatim the construction ``serve()`` used to inline, so every
        core-based server wires observability identically."""
        server = self.server
        if (cfg.get("health") or cfg.get("health_dir")
                or cfg.get("health_port") is not None):
            from pytorch_ps_mpi_tpu.telemetry.diagnosis import HealthMonitor

            # attaches itself to server.health_monitor (the /health
            # route) and registers its instruments on the scrape registry
            self.health = HealthMonitor(server, cfg)
        if (cfg.get("numerics") or cfg.get("numerics_dir")
                or cfg.get("numerics_kw")):
            from pytorch_ps_mpi_tpu.telemetry.numerics import NumericsMonitor

            # attaches itself to server.numerics_monitor: canonical
            # metrics grow the numerics keys, /health gains "numerics",
            # and the serve loop validates every consumed push
            self.numerics = NumericsMonitor(server, cfg)
        if cfg.get("lineage") or cfg.get("lineage_dir"):
            if getattr(server, "frame", False):
                from pytorch_ps_mpi_tpu.telemetry.lineage import (
                    LineageTracker,
                )

                # attaches itself to server.lineage_tracker: framed_poll
                # feeds it every consumed push's trace ID
                self.lineage = LineageTracker(server, cfg)
                anat = cfg.get("anatomy", "auto")
                if anat not in (False, "off", 0):
                    # the round-anatomy causal profiler rides armed
                    # lineage by default ("auto"): exact per-round
                    # critical paths + the what-if advisor, fed one
                    # publish row per version by the tracker; opt out
                    # with cfg["anatomy"] = False / "off"
                    from pytorch_ps_mpi_tpu.telemetry.anatomy import (
                        RoundAnatomy,
                    )

                    self.anatomy = RoundAnatomy(server, cfg)
                    self.lineage.anatomy = self.anatomy
            else:
                # the trace ID rides the v2 frame header — without
                # frames there is nothing on the wire to trace
                print("lineage tracing requires frame_check=True; "
                      "not armed", flush=True)
        http_port = cfg.get("metrics_port")
        if http_port is None:
            http_port = cfg.get("health_port")  # same endpoint serves both
        if http_port is not None and hasattr(server, "start_metrics_http"):
            self.metrics_http_port = server.start_metrics_http(
                int(http_port))
            print(f"prometheus /metrics + /health on port "
                  f"{self.metrics_http_port}", flush=True)
        # the fleet observability plane (metrics history / SLO watchdog /
        # continuous profiler / fleet pane) — attached AFTER the endpoint
        # so fleet registration can carry the bound port; the mixin owns
        # the construction so the sharded shard-server wires identically
        if hasattr(server, "arm_observability"):
            server.arm_observability(cfg)

    def tick(self) -> None:
        """Monitor upkeep at the owning loop's tick cadence (same-thread
        with the transport pumps, like the monitors require)."""
        if self.health is not None:
            self.health.tick()
        if self.numerics is not None:
            self.numerics.tick()
        srv = self.server
        if srv is not None and srv.timeseries_db is not None:
            # TSDB sample + SLO sweep (both self-throttled) — one attr
            # check per tick when the observability plane is unarmed
            srv.observability_tick()

    # -- publish ----------------------------------------------------------
    def _ensure_tenant(self, tenant: str, template: PyTree
                       ) -> SnapshotStore:
        with self._lock:
            store = self._stores.get(tenant)
            if store is None:
                store = SnapshotStore(int(self.knobs["ring"]))
                self._stores[tenant] = store
                if template is not None:
                    self._templates[tenant] = template
                self._tenant_reads.setdefault(tenant, 0)
            return store

    def publish(self, params: PyTree = None, *, flat: np.ndarray = None,
                tenant: Optional[str] = None,
                version: Optional[int] = None,
                template: PyTree = None,
                fresh: Optional[bytes] = None) -> int:
        """Publish one version: through the transport server (primary
        tenant) and/or into the snapshot ring (when the read tier is
        armed). Returns the published version.

        Unarmed with a server this is EXACTLY ``server.publish(params)``
        — the legacy trainer path pays nothing for the read tier it
        isn't running. Side tenants (``tenant != default``) and
        serverless cores version locally (pass ``version=`` to pin, e.g.
        a restored checkpoint's version).

        ``fresh`` is a relayed FRS1 trailer (a follower republishing an
        upstream version passes the upstream trailer with its own hop
        appended, preserving the ROOT's birth record); ``None`` stamps a
        new birth here — this core IS the root for the version.
        """
        tenant = tenant or self.default_tenant
        primary = (self.server is not None
                   and tenant == self.default_tenant)
        if not self.armed:
            if not primary:
                raise ValueError(
                    "read tier is unarmed: side-tenant/serverless publish "
                    "has nowhere to go (set cfg['serving'] or "
                    "cfg['read_port'])")
            self.server.publish(params)
            return self.server.version
        if flat is None:
            from pytorch_ps_mpi_tpu.parallel.dcn import _flatten

            flat = _flatten(params)
        if primary:
            self.server.publish_flat(flat)
            version = self.server.version
        elif version is None:
            version = self._versions.get(tenant, 0) + 1
        version = int(version)
        self._versions[tenant] = version
        store = self._stores.get(tenant)
        if store is None:
            store = self._ensure_tenant(
                tenant, template if template is not None
                else (params if params is not None else self.template))
        store.put(version, flat)
        with self._lock:
            # new latest ends the coalescing window: cached encodes
            # against the previous latest can never be served again
            for k in [k for k in self._encode_cache if k[0] == tenant]:
                del self._encode_cache[k]
        blob, doc = self._stamp_fresh(tenant, version, fresh)
        if self.read_native:
            # version-window boundary: hand the frozen snapshot + the
            # ring's pre-encoded deltas (and the version's freshness
            # trailer) to the native tier — the ONLY Python the native
            # read path ever runs
            self.read_server.on_publish(
                tenant, version, store, fresh=blob,
                publish_wall=(doc["publish_wall"] if doc is not None
                              else 0.0))
        return version

    def _stamp_fresh(self, tenant: str, version: int,
                     fresh: Optional[bytes]
                     ) -> Tuple[bytes, Optional[Dict[str, Any]]]:
        """Install the version's FRS1 birth record: stamp a new one
        (root publish) or validate and adopt a relayed trailer
        (follower republish). A malformed relay trailer is REJECTED —
        the version serves with no trailer rather than a corrupt one."""
        from pytorch_ps_mpi_tpu.telemetry import freshness as _freshness

        if fresh is None:
            blob = _freshness.pack_birth(version, time.time(),
                                         self.fresh_root_gen)
        elif not fresh:
            # relay with nothing to relay (upstream sent no trailer):
            # serve the version untrailered — a birth record is carried
            # end-to-end or not at all, never re-stamped mid-chain
            return b"", None
        else:
            blob = bytes(fresh)
        try:
            doc = _freshness.unpack_trailer(blob)
        except ValueError:
            ft = self._fresh_tracker()
            if ft is not None:
                ft.note_reject()
            return b"", None
        with self._lock:
            self._fresh[tenant] = {
                "version": version, "blob": blob, "doc": doc,
                "birth_local": _freshness.birth_wall_local(doc)}
            vis = _freshness.visible_latency_ms(doc)
            if vis is not None:
                self._fresh_lat.append(vis)
        ft = self._fresh_tracker()
        if ft is not None:
            ft.note_publish(tenant, doc)
        return blob, doc

    def _fresh_tracker(self):
        ft = self.freshness_tracker
        if ft is None and self.server is not None:
            ft = getattr(self.server, "freshness_tracker", None)
        return ft

    def fresh_trailer(self, tenant: Optional[str] = None,
                      version: Optional[int] = None) -> bytes:
        """The FRS1 trailer to attach to a reply delivering ``version``
        (b"" when none is installed or a publish raced the reply onto a
        different version). Counts the reply — the Python twin of the
        native tier's ``fresh_replies``."""
        rec = self._fresh.get(tenant or self.default_tenant)
        if rec is None:
            return b""
        if version is not None and rec["version"] != int(version):
            return b""
        with self._lock:
            self.fresh_replies += 1
        return rec["blob"]

    def fresh_doc(self, tenant: Optional[str] = None
                  ) -> Optional[Dict[str, Any]]:
        """Decoded trailer of the version currently served (or None)."""
        rec = self._fresh.get(tenant or self.default_tenant)
        return rec["doc"] if rec is not None else None

    def fresh_ages_ms(self, now: Optional[float] = None
                      ) -> Dict[str, float]:
        """Age-of-information gauge, per tenant: wall age (local clock)
        of the version each tenant currently serves. Grows continuously
        between publishes, snaps down when a fresher version lands."""
        t = time.time() if now is None else float(now)
        with self._lock:
            return {tn: max(0.0, (t - rec["birth_local"]) * 1e3)
                    for tn, rec in self._fresh.items()}

    def serving_age_ms(self, now: Optional[float] = None) -> float:
        """Worst-tenant age — the canonical ``serving_age_ms`` key."""
        ages = self.fresh_ages_ms(now)
        return max(ages.values()) if ages else 0.0

    # -- read path --------------------------------------------------------
    def _delta(self, tenant: str) -> DeltaCodec:
        dc = self._deltas.get(tenant)
        if dc is None:
            tmpl = self._templates.get(tenant)
            if tmpl is None:
                raise ValueError(f"no template recorded for tenant "
                                 f"{tenant!r}")
            dc = DeltaCodec.from_knobs(tmpl, self.knobs)
            with self._lock:  # scrape threads iterate _deltas under it
                dc = self._deltas.setdefault(tenant, dc)
        return dc

    def latest_version(self, tenant: Optional[str] = None) -> int:
        store = self._stores.get(tenant or self.default_tenant)
        if store is None:
            return 0
        snap = store.latest()
        return snap.version if snap is not None else 0

    def note_shed(self) -> None:
        with self._lock:
            self.reads_shed += 1

    # -- control-plane actuators ------------------------------------------
    def set_admission_depth(self, depth: int) -> None:
        """Live admission-depth change (the controller's read-tier
        tuning): the network loop reads ``core.admission_depth`` at
        every enqueue, so the new bound applies to the next request."""
        if depth < 1:
            raise ValueError(f"admission depth must be >= 1, got {depth}")
        self.admission_depth = int(depth)
        if self.read_native:
            self.read_server.set_admission(self.admission_depth,
                                           self.retry_after_s)

    # -- follower-tier accounting (serving.follower.FollowerLoop) ---------
    def set_replica_lag(self, lag: float) -> None:
        """Versions this replica is behind its upstream (0 = current).
        Fractional values are meaningful: the follower feeds its
        EWMA-decayed lag here, so a spike fades over a few polls
        instead of snapping to zero the moment the replica catches
        up."""
        with self._lock:
            self.replica_lag_versions = max(0.0, float(lag))

    def note_relayed(self, nbytes: int) -> None:
        """Bytes this follower pulled from upstream and re-served."""
        with self._lock:
            self.follower_bytes_relayed += max(0, int(nbytes))

    def set_ring(self, ring: int) -> None:
        """Live snapshot-ring resize across every tenant store (and for
        stores created later)."""
        self.knobs["ring"] = int(ring)
        with self._lock:
            stores = list(self._stores.values())
        for store in stores:
            store.resize(int(ring))

    def observe_read(self, dur_s: float) -> None:
        self._read_hist.observe(float(dur_s))

    def handle_read(self, have_version: int = 0, want_delta: bool = True,
                    tenant: Optional[str] = None):
        """Answer one read: ``(kind, version, base, payload, done)``.

        ``payload`` is ``None`` (not-modified / retry), a frozen flat
        snapshot array (full — send zero-copy, call ``done()`` when the
        bytes are out to release the ring pin), or a cached delta buffer
        (shared by every coalesced reader; kept alive by its reference).
        Safe from any thread — only store/cache/counter state is touched,
        never a native transport handle.
        """
        tenant = tenant or self.default_tenant
        store = self._stores.get(tenant)
        if store is None:
            return (KIND_ERROR, 0, 0,
                    f"unknown tenant {tenant!r}".encode(), None)
        latest = store.acquire(None)
        if latest is None:
            # nothing published yet: ask the reader to come back
            return KIND_RETRY, 0, 0, None, None
        try:
            return self._answer_read(store, latest, int(have_version),
                                     want_delta, tenant)
        except BaseException:
            # never leak the ring pin: an encode error (template drift,
            # size mismatch) surfaces to the caller, not as a permanently
            # held snapshot
            store.release(latest)
            raise

    def _answer_read(self, store, latest, have: int, want_delta: bool,
                     tenant: str):
        version = latest.version
        now_s = int(time.monotonic())
        with self._lock:
            self.reads_total += 1
            self._tenant_reads[tenant] = (
                self._tenant_reads.get(tenant, 0) + 1)
            # per-second rate buckets: no cap, unlike a bounded timestamp
            # deque which silently under-reports rates past maxlen/window.
            # Pruned HERE too (not just on /health reads) so a server
            # scraped only via /metrics never accumulates old buckets.
            self._rate[now_s] = self._rate.get(now_s, 0) + 1
            if len(self._rate) > int(self.knobs["rate_window_s"]) + 2:
                cutoff = now_s - int(self.knobs["rate_window_s"])
                for sec in [s for s in self._rate if s < cutoff]:
                    del self._rate[sec]
            if have > 0:
                # oldest-served-version accounting (freshness plane):
                # the laggiest base any reader still held when asking
                mh = self._fresh_min_have.get(tenant)
                if mh is None or have < mh:
                    self._fresh_min_have[tenant] = have
        if have == version:
            store.release(latest)
            with self._lock:
                self.reads_not_modified += 1
            return KIND_NOT_MODIFIED, version, have, None, None
        full_bytes = latest.nbytes
        if want_delta and have > 0:
            key = (tenant, have, version)
            with self._lock:
                payload = self._encode_cache.get(key)
            if payload is not None:
                # coalesced: same (base -> latest) ask within this
                # version's window rides the one existing encode
                store.release(latest)
                with self._lock:
                    self.reads_delta += 1
                    self.coalesce_hits += 1
                    self.delta_bytes_saved += max(
                        0, full_bytes - payload.nbytes)
                return KIND_DELTA, version, have, payload, None
            base = store.acquire(have)
            if base is None:
                with self._lock:
                    self.ring_ageouts += 1  # aged out: full fallback
            else:
                try:
                    payload = self._delta(tenant).encode(
                        base.flat, latest.flat)
                finally:
                    store.release(base)
                if payload is None:
                    with self._lock:
                        self.delta_full_fallbacks += 1
                else:
                    with self._lock:
                        self._encode_cache[key] = payload
                        self.reads_delta += 1
                        self.delta_bytes_saved += max(
                            0, full_bytes - payload.nbytes)
                    store.release(latest)
                    return KIND_DELTA, version, have, payload, None
        with self._lock:
            self.reads_full += 1
        done = (lambda s=latest, st=store: st.release(s))
        return KIND_FULL, version, 0, latest.flat, done

    def acquire_latest(self, tenant: Optional[str] = None):
        """In-process zero-copy read: pin and return the latest
        :class:`~.snapshots.Snapshot` (``.view()`` is the shared bytes)
        — release with :meth:`release` when done. None before the first
        publish."""
        store = self._stores.get(tenant or self.default_tenant)
        return store.acquire(None) if store is not None else None

    def release(self, snap, tenant: Optional[str] = None) -> None:
        store = self._stores.get(tenant or self.default_tenant)
        if store is not None:
            store.release(snap)

    # -- accounting -------------------------------------------------------
    def reads_per_s(self) -> float:
        window = max(1.0, float(self.knobs["rate_window_s"]))
        now = time.monotonic()
        cutoff = int(now - window)
        with self._lock:
            for sec in [s for s in self._rate if s < cutoff]:
                del self._rate[sec]
            n = sum(self._rate.values())
        span = min(window, max(now - self._t0, 1e-6))
        return n / span if span > 0 else 0.0

    def _quantile_ms(self, q: float) -> float:
        import math

        v = self._read_hist.approx_quantile(q)
        return 0.0 if math.isnan(v) else v * 1e3

    def _native_stats(self) -> Optional[Dict[str, int]]:
        """The native tier's counter block, or None on the Python loop."""
        if not self.read_native or self.read_server is None:
            return None
        try:
            return self.read_server.stats()
        except Exception:
            return None  # torn down mid-scrape

    def read_metrics(self) -> Dict[str, float]:
        """The canonical serving keys (all float; zeros before traffic).
        With the native tier armed its C++ counters merge in here — one
        schema whichever loop served the bytes."""
        with self._lock:
            out = {
                "reads_total": float(self.reads_total),
                "delta_bytes_saved": float(self.delta_bytes_saved),
                "reads_shed": float(self.reads_shed),
                "coalesce_hits": float(self.coalesce_hits),
                "reads_not_modified": float(self.reads_not_modified),
                "replica_lag_versions": float(self.replica_lag_versions),
                "follower_bytes_relayed": float(
                    self.follower_bytes_relayed),
            }
        nat = self._native_stats()
        out["native_read_conns"] = float(nat["conns"]) if nat else 0.0
        if nat is not None:
            for src, dst in (("reads_total", "reads_total"),
                             ("reads_shed", "reads_shed"),
                             ("coalesce_hits", "coalesce_hits"),
                             ("reads_not_modified", "reads_not_modified"),
                             ("delta_bytes_saved", "delta_bytes_saved")):
                out[dst] += float(nat[src])
        out["read_p50_ms"] = self._quantile_ms(0.50)
        out["read_p95_ms"] = self._quantile_ms(0.95)
        # freshness plane: publish->visible latency quantiles (zeros at
        # the root, which has no propagation hops), worst-tenant age of
        # the version being served, and this node's hop depth
        with self._lock:
            lat = sorted(self._fresh_lat)
            hops = max((rec["doc"]["hop_count"]
                        for rec in self._fresh.values()), default=0)
        out["read_fresh_p50_ms"] = _seq_quantile(lat, 0.50)
        out["read_fresh_p95_ms"] = _seq_quantile(lat, 0.95)
        out["serving_age_ms"] = self.serving_age_ms()
        out["fresh_hop_count"] = float(hops)
        return out

    def serving_snapshot(self) -> Dict[str, Any]:
        """The ``/health`` ``serving`` section: ring occupancy, queue
        depth, per-tenant read counts, shed/coalesce counters."""
        with self._lock:
            tenants = {
                t: {**store.snapshot(),
                    "reads": self._tenant_reads.get(t, 0)}
                for t, store in self._stores.items()
            }
            counters = {
                "reads_total": self.reads_total,
                "reads_full": self.reads_full,
                "reads_delta": self.reads_delta,
                "reads_not_modified": self.reads_not_modified,
                "reads_shed": self.reads_shed,
                "coalesce_hits": self.coalesce_hits,
                "delta_bytes_saved": self.delta_bytes_saved,
                "ring_ageouts": self.ring_ageouts,
                "delta_full_fallbacks": self.delta_full_fallbacks,
            }
            lossy_fallbacks = sum(d.lossy_fallbacks
                                  for d in self._deltas.values())
        out = {
            "armed": self.armed,
            "read_port": self.read_port,
            "admission_depth": self.admission_depth,
            "retry_after_s": self.retry_after_s,
            "queue_depth": (self.read_server.queue_depth()
                            if self.read_server is not None else 0),
            "connections": (self.read_server.connections()
                            if self.read_server is not None else 0),
            "reads_per_s": round(self.reads_per_s(), 3),
            "read_p50_ms": round(self._quantile_ms(0.50), 4),
            "read_p95_ms": round(self._quantile_ms(0.95), 4),
            "lossy_fallbacks": lossy_fallbacks,
            "tenants": tenants,
            **counters,
        }
        nat = getattr(self.server, "_native_read_stats", None)
        if nat is not None:
            # the transport's own GET_PARAMS path (worker reads): total
            # + cheap not-modified replies, counted natively
            out["native_reads"] = {"total": int(nat[0]),
                                   "not_modified": int(nat[1])}
        out["read_native"] = self.read_native
        nrs = self._native_stats()
        if nrs is not None:
            # the native PSR1 tier's full counter block — its serves
            # also fold into the canonical counters above
            out["native_read"] = nrs
            for k in ("reads_total", "reads_full", "reads_delta",
                      "reads_not_modified", "reads_shed",
                      "coalesce_hits", "delta_bytes_saved"):
                out[k] += nrs[k]
        elif self.read_server is not None and not self.read_native:
            # torn-frame accounting on the Python loop (the native tier
            # reports the same fields inside native_read)
            out["rejected_frames"] = self.read_server.rejected_frames
            out["eof_mid_request"] = self.read_server.eof_mid_request
        out["replica_lag_versions"] = self.replica_lag_versions
        out["follower_bytes_relayed"] = self.follower_bytes_relayed
        out["freshness"] = self.freshness_snapshot()
        return out

    def freshness_snapshot(self) -> Dict[str, Any]:
        """The ``/health`` serving section's freshness pane: per-tenant
        age of information + birth records, the publish->visible
        quantiles, trailer-reply and laggiest-reader accounting (native
        tier's live pair included when armed)."""
        now = time.time()
        with self._lock:
            tenants = {
                tn: {"version": rec["version"],
                     "age_ms": round(
                         max(0.0, (now - rec["birth_local"]) * 1e3), 3),
                     "hop_count": rec["doc"]["hop_count"],
                     "publish_wall": rec["doc"]["publish_wall"],
                     "root_gen": rec["doc"]["root_gen"]}
                for tn, rec in self._fresh.items()
            }
            lat = sorted(self._fresh_lat)
            out = {
                "tenants": tenants,
                "read_fresh_p50_ms": round(_seq_quantile(lat, 0.50), 3),
                "read_fresh_p95_ms": round(_seq_quantile(lat, 0.95), 3),
                "fresh_replies": self.fresh_replies,
                "min_have_version": dict(self._fresh_min_have),
            }
        if self.read_native and self.read_server is not None:
            nf = self.read_server.fresh_stats_all()
            if nf:
                out["native_fresh"] = nf
        return out

    def _register_scrape(self) -> None:
        def collect(r) -> None:
            if self.server is None:
                # a standalone (read-only) core has no ps_server_registry
                # emitting the fleet poller's ordering/aging gauges —
                # emit them here so a restarted read tier is detectable
                # (uptime resets) and its samples can be aged
                r.gauge("ps_scrape_ts_seconds",
                        "wall-clock timestamp of this scrape").set(
                            time.time())
                r.gauge("ps_uptime_seconds",
                        "monotonic age of this serving-core generation"
                        ).set(max(0.0, time.monotonic() - self._t0))
            m = self.read_metrics()
            r.counter("ps_reads_total",
                      "read-tier requests served (all kinds)").set(
                          m["reads_total"])
            r.counter("ps_reads_shed_total",
                      "read requests shed by admission control").set(
                          m["reads_shed"])
            r.counter("ps_coalesce_hits_total",
                      "delta reads served from an existing encode").set(
                          m["coalesce_hits"])
            r.counter("ps_delta_bytes_saved_total",
                      "payload bytes saved by delta replies vs full "
                      "snapshots").set(m["delta_bytes_saved"])
            r.counter("ps_reads_not_modified_total",
                      "version-conditional reads answered without a "
                      "payload").set(m["reads_not_modified"])
            r.gauge("ps_read_p50_ms",
                    "read-tier service time p50 (ms)").set(
                        m["read_p50_ms"])
            r.gauge("ps_read_p95_ms",
                    "read-tier service time p95 (ms)").set(
                        m["read_p95_ms"])
            r.gauge("ps_read_queue_depth",
                    "read requests awaiting service").set(
                        float(self.read_server.queue_depth()
                              if self.read_server is not None else 0))
            r.gauge("ps_native_read_conns",
                    "reader connections open on the native PSR1 "
                    "tier").set(m["native_read_conns"])
            r.gauge("ps_replica_lag_versions",
                    "versions this replica trails its upstream "
                    "(follower tier; 0 standalone)").set(
                        m["replica_lag_versions"])
            r.counter("ps_follower_bytes_relayed_total",
                      "bytes pulled from upstream and re-served by "
                      "this follower").set(m["follower_bytes_relayed"])
            r.gauge("ps_serving_age_ms",
                    "wall age of the version currently being served "
                    "(worst tenant; the age-of-information gauge)").set(
                        m["serving_age_ms"])
            r.gauge("ps_read_fresh_p50_ms",
                    "publish->visible-here propagation latency p50 "
                    "(ms; zero at the root)").set(m["read_fresh_p50_ms"])
            r.gauge("ps_read_fresh_p95_ms",
                    "publish->visible-here propagation latency p95 "
                    "(ms; zero at the root)").set(m["read_fresh_p95_ms"])
            r.gauge("ps_fresh_hop_count",
                    "replica hops recorded in the served version's "
                    "freshness trailer (this node's tree depth)").set(
                        m["fresh_hop_count"])
            r.counter("ps_fresh_replies_total",
                      "replies that carried an FRS1 freshness "
                      "trailer").set(float(self.fresh_replies))
            with self._lock:
                occ = sum(len(s._order) for s in self._stores.values())
                tenants = len(self._stores)
            r.gauge("ps_serving_ring_occupancy",
                    "snapshots resident across all tenant rings").set(
                        float(occ))
            r.gauge("ps_serving_tenants",
                    "tenant namespaces with a snapshot ring").set(
                        float(tenants))

        self._reg.add_collector(collect)

    @property
    def registry(self):
        return self._reg

    def close(self) -> None:
        """Tear down the network read server and any standalone HTTP
        endpoint. Monitors are closed by their owner (serve() closes
        numerics/lineage exactly as before the extraction)."""
        if self.read_server is not None:
            self.read_server.close()
            # the native tier's counters die with its C++ handle: fold
            # the final block (captured at teardown) into the core's own
            # counters so post-close accounting — server.metrics() after
            # server.close() — reads the same whichever loop served
            nrs = self._native_stats()
            if nrs is not None:
                with self._lock:
                    for k in ("reads_total", "reads_full", "reads_delta",
                              "reads_not_modified", "reads_shed",
                              "coalesce_hits", "delta_bytes_saved"):
                        setattr(self, k, getattr(self, k) + nrs[k])
                self.read_native = False
            # …and the per-tenant freshness pair (trailered replies +
            # laggiest reader base) folds the same way
            fs_all = getattr(self.read_server, "fresh_stats_all",
                             lambda: {})()
            with self._lock:
                for tn, fs in (fs_all or {}).items():
                    self.fresh_replies += int(fs["fresh_replies"])
                    mh = int(fs["min_have_version"])
                    if mh:
                        cur = self._fresh_min_have.get(tn)
                        self._fresh_min_have[tn] = (
                            mh if cur is None else min(cur, mh))
            self.read_server = None
        reg, self._fleet_registration = self._fleet_registration, None
        if reg is not None:
            from pytorch_ps_mpi_tpu.telemetry.fleet import (
                deregister_endpoint,
            )

            deregister_endpoint(*reg)
        if self._own_http is not None:
            self._own_http.close()
            self._own_http = None
