"""Version-to-version parameter deltas for the read tier.

A reader holding version ``v`` asks the server for "v → latest"; this
module builds (and applies) the answer. The flat f32 parameter vector is
segmented into dtype-bucketed ~MB-scale sections via
:func:`~pytorch_ps_mpi_tpu.bucketing.plan_buckets` (the published
snapshot wire is all-f32, so the plan degenerates to contiguous
leaf-order segments — the point is that section boundaries follow layer
boundaries, so an update that touched two layers ships two sections,
not the whole model), and each *changed* section is encoded either
sparse (index+value of changed elements, the SparCML index-merge shape)
or dense (the section's new values verbatim), whichever is smaller.
Unchanged sections ship nothing.

**Exact by default**: changed elements are detected by *bit* compare
(u32 views — NaN- and -0.0-proof) and the payload carries the NEW values
verbatim, so ``apply(base, encode(base, latest)) == latest`` bit for
bit. **Lossy opt-in**: pass a codec (``codecs.get_codec`` name) and
sections ride its encoded form of the dense diff — guarded by a PR 5
style fidelity probe: at probe cadence the encoder measures the
decode-after-encode relative L2 error of the diff it is about to ship
and *sticky-disables* the lossy path (falling back to exact, counted)
the moment it exceeds ``max_rel_error``. Both ends must construct the
same ``DeltaCodec`` config — it joins the wire agreement exactly like
``CodecWire``'s codec/bucket config.

Payload format (little-endian)::

  u32 magic 'PSD1' | u32 n_sections | u64 total_elems
  per section:
    u32 mode (0 sparse / 1 dense / 2 lossy) | u32 start | u32 count
    | u32 n  (sparse: nnz; dense: count; lossy: payload bytes)
    | body   (sparse: u32 idx[n] then f32 val[n]; dense: f32 val[count];
              lossy: packed codec payload arrays for a (count,) f32 diff)

``encode`` returns ``None`` when the delta would not beat the full
snapshot (the caller then serves a full read — counted, never silent).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

PyTree = Any

MAGIC = 0x31445350  # "PSD1"
_HEADER = struct.Struct("<IIQ")
_SECTION = struct.Struct("<IIII")
MODE_SPARSE, MODE_DENSE, MODE_LOSSY = 0, 1, 2

#: tuning knobs and their defaults (overridable via ``cfg["serving_kw"]``)
DELTA_KNOBS: Dict[str, Any] = {
    "delta_bucket_mb": 4.0,     # section granularity (0 = one section)
    "delta_codec": None,        # codec registry name; None = exact only
    "delta_codec_kw": {},       # constructor kwargs for the lossy codec
    "delta_max_rel_error": 0.05,  # fidelity gate for the lossy path
    "delta_probe_every": 16,    # lossy fidelity probe cadence (encodes)
    "delta_min_saving": 0.9,    # ship delta only if < this x full bytes
}


def _flat_segments(template: PyTree, bucket_mb: float,
                   total: int) -> List[Tuple[int, int]]:
    """Contiguous ``(start, count)`` segments of the flat f32 vector,
    derived from the dtype-bucket plan over an all-f32 view of the
    template (one dtype group → buckets keep leaf/flatten order, so the
    cumulative sizes ARE the flat offsets)."""
    if total == 0:
        return []
    if bucket_mb is None or bucket_mb <= 0:
        return [(0, total)]
    import jax

    from pytorch_ps_mpi_tpu.bucketing import plan_buckets

    f32_tmpl = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(tuple(np.shape(l)), np.float32),
        template,
    )
    plan = plan_buckets(f32_tmpl, bucket_mb)
    segs, off = [], 0
    for b in plan.buckets:
        segs.append((off, int(b.size)))
        off += int(b.size)
    assert off == total, f"segment plan covers {off} of {total} elements"
    return segs


class DeltaCodec:
    """Encode/apply exact (or guarded-lossy) flat-vector deltas.

    Construct with the SAME arguments on server and reader — the config
    is part of the wire agreement (exact mode is self-describing, but
    the lossy mode's codec payload layout is not).
    """

    def __init__(self, template: PyTree, bucket_mb: float = 4.0,
                 codec: Optional[str] = None,
                 codec_kw: Optional[dict] = None,
                 max_rel_error: float = 0.05, probe_every: int = 16,
                 min_saving: float = 0.9):
        from pytorch_ps_mpi_tpu.parallel.dcn import _flat_size

        self.total = int(_flat_size(template))
        self.full_bytes = self.total * 4
        self.segments = _flat_segments(template, bucket_mb, self.total)
        self.max_rel_error = float(max_rel_error)
        self.probe_every = max(1, int(probe_every))
        self.min_saving = float(min_saving)
        self.code = None
        if codec:
            from pytorch_ps_mpi_tpu.codecs import get_codec

            self.code = get_codec(codec, **(codec_kw or {}))
        #: sticky lossy state: True until a fidelity probe fails
        self.lossy_ok = self.code is not None
        self.lossy_fallbacks = 0
        self.last_probe_rel_error: Optional[float] = None
        self._encodes = 0
        self._codec_specs: Dict[int, List[Tuple[tuple, np.dtype]]] = {}

    # -- lossy helpers ----------------------------------------------------
    def _specs_for(self, count: int) -> List[Tuple[tuple, np.dtype]]:
        """Flat payload specs of the lossy codec on a (count,) f32 input
        (cached per section size) — the same eval_shape derivation
        ``CodecWire`` uses."""
        specs = self._codec_specs.get(count)
        if specs is None:
            import jax
            import jax.numpy as jnp

            struct_ = jax.eval_shape(
                lambda: self.code.encode(
                    jnp.zeros((count,), jnp.float32),
                    self.code.init_state((count,), jnp.float32),
                    jax.random.key(0) if self.code.needs_rng else None,
                )
            )[0]
            specs = [(tuple(x.shape), np.dtype(x.dtype))
                     for x in jax.tree.leaves(struct_)]
            self._codec_specs[count] = specs
        return specs

    def _lossy_encode(self, diff: np.ndarray,
                      probe: bool) -> Optional[bytes]:
        """Codec-encode one section's dense diff; None when the fidelity
        probe rejects it (sticky) or the codec errors."""
        import jax

        try:
            rng = (jax.random.key(0x5EED) if self.code.needs_rng else None)
            payload, _ = self.code.encode(
                diff, self.code.init_state(diff.shape, diff.dtype), rng)
            if probe:
                rec = np.asarray(
                    self.code.decode(payload, diff.shape, diff.dtype),
                    np.float32)
                dn = float(np.linalg.norm(diff))
                rel = float(np.linalg.norm(rec - diff) / max(dn, 1e-30))
                self.last_probe_rel_error = rel
                if rel > self.max_rel_error:
                    # the codec measurably mangles THIS distribution of
                    # diffs — disable lossy for the rest of the run
                    self.lossy_ok = False
                    self.lossy_fallbacks += 1
                    return None
            parts = [np.ascontiguousarray(np.asarray(x)).reshape(-1)
                     .view(np.uint8)
                     for x in jax.tree.leaves(payload)]
            return b"".join(p.tobytes() for p in parts)
        except Exception:
            self.lossy_ok = False
            self.lossy_fallbacks += 1
            return None

    def _lossy_apply(self, base_seg: np.ndarray,
                     body: memoryview) -> np.ndarray:
        import jax

        from pytorch_ps_mpi_tpu.utils.serialization import read_arrays

        count = base_seg.size
        specs = self._specs_for(count)
        arrays = read_arrays(body, specs, copy=False)
        struct_ = jax.tree.structure(
            jax.eval_shape(
                lambda: self.code.encode(
                    np.zeros((count,), np.float32),
                    self.code.init_state((count,), np.float32),
                    jax.random.key(0) if self.code.needs_rng else None,
                )
            )[0]
        )
        payload = jax.tree.unflatten(struct_, arrays)
        diff = np.asarray(
            self.code.decode(payload, (count,), np.float32), np.float32)
        return base_seg + diff

    # -- encode -----------------------------------------------------------
    def encode(self, base: np.ndarray,
               latest: np.ndarray) -> Optional[np.ndarray]:
        """Delta payload bytes (uint8 ndarray) for base → latest, or
        ``None`` when a full snapshot is the better answer."""
        if base.size != self.total or latest.size != self.total:
            raise ValueError(
                f"flat size mismatch: template {self.total}, "
                f"base {base.size}, latest {latest.size}")
        self._encodes += 1
        probe = (self._encodes % self.probe_every) == 1 or self.probe_every == 1
        bv = base.view(np.uint32)
        lv = latest.view(np.uint32)
        sections: List[Tuple[int, int, int, bytes, np.ndarray, np.ndarray]] = []
        total_bytes = _HEADER.size
        for start, count in self.segments:
            seg_b = bv[start:start + count]
            seg_l = lv[start:start + count]
            idx = np.nonzero(seg_b != seg_l)[0]
            nnz = int(idx.size)
            if nnz == 0:
                continue
            vals = latest[start:start + count]
            sparse_bytes = 8 * nnz
            dense_bytes = 4 * count
            if self.code is not None and self.lossy_ok:
                diff = vals - base[start:start + count]
                body = self._lossy_encode(
                    np.ascontiguousarray(diff, np.float32), probe)
                if body is not None and len(body) < min(sparse_bytes,
                                                        dense_bytes):
                    sections.append((MODE_LOSSY, start, count, body,
                                     None, None))
                    total_bytes += _SECTION.size + len(body)
                    continue
            if sparse_bytes < dense_bytes:
                sections.append((MODE_SPARSE, start, count, b"",
                                 idx.astype(np.uint32), vals[idx]))
                total_bytes += _SECTION.size + sparse_bytes
            else:
                sections.append((MODE_DENSE, start, count, b"",
                                 None, vals))
                total_bytes += _SECTION.size + dense_bytes
        if total_bytes >= self.min_saving * self.full_bytes:
            return None  # delta not worth it: serve a full snapshot
        out = np.empty(total_bytes, np.uint8)
        _HEADER.pack_into(out, 0, MAGIC, len(sections), self.total)
        off = _HEADER.size
        for mode, start, count, body, idx, vals in sections:
            if mode == MODE_LOSSY:
                n = len(body)
            elif mode == MODE_SPARSE:
                n = int(idx.size)
            else:
                n = count
            _SECTION.pack_into(out, off, mode, start, count, n)
            off += _SECTION.size
            if mode == MODE_LOSSY:
                out[off:off + len(body)] = np.frombuffer(body, np.uint8)
                off += len(body)
            elif mode == MODE_SPARSE:
                ib = np.ascontiguousarray(idx).view(np.uint8)
                out[off:off + ib.nbytes] = ib
                off += ib.nbytes
                vb = np.ascontiguousarray(vals, np.float32).view(np.uint8)
                out[off:off + vb.nbytes] = vb
                off += vb.nbytes
            else:
                vb = np.ascontiguousarray(vals, np.float32).view(np.uint8)
                out[off:off + vb.nbytes] = vb
                off += vb.nbytes
        assert off == total_bytes
        return out

    # -- apply ------------------------------------------------------------
    def apply(self, base: np.ndarray, payload) -> np.ndarray:
        """Rebuild the latest flat vector from ``base`` + a delta payload
        (bytes-like). Returns a NEW array; ``base`` is untouched."""
        mv = memoryview(payload)
        if mv.nbytes < _HEADER.size:
            raise ValueError("truncated delta payload (no header)")
        magic, n_sections, total = _HEADER.unpack_from(mv, 0)
        if magic != MAGIC:
            raise ValueError(f"bad delta magic 0x{magic:08x}")
        if total != base.size:
            raise ValueError(
                f"delta for {total} elements applied to base of {base.size}")
        out = np.array(base, np.float32, copy=True)
        off = _HEADER.size
        for _ in range(n_sections):
            mode, start, count, n = _SECTION.unpack_from(mv, off)
            off += _SECTION.size
            if mode == MODE_SPARSE:
                idx = np.frombuffer(mv, np.uint32, n, off)
                off += 4 * n
                vals = np.frombuffer(mv, np.float32, n, off)
                off += 4 * n
                out[start:start + count][idx] = vals
            elif mode == MODE_DENSE:
                vals = np.frombuffer(mv, np.float32, count, off)
                off += 4 * count
                out[start:start + count] = vals
            elif mode == MODE_LOSSY:
                if self.code is None:
                    raise ValueError(
                        "lossy delta section but this DeltaCodec has no "
                        "codec configured (wire agreement drift)")
                out[start:start + count] = self._lossy_apply(
                    out[start:start + count], mv[off:off + n])
                off += n
            else:
                raise ValueError(f"unknown delta section mode {mode}")
        if off != mv.nbytes:
            raise ValueError(
                f"delta payload has {mv.nbytes - off} trailing bytes")
        return out

    @classmethod
    def from_knobs(cls, template: PyTree, knobs: Dict[str, Any]
                   ) -> "DeltaCodec":
        """Construct from a ``DELTA_KNOBS``-shaped dict (the
        ``cfg["serving_kw"]`` path — both ends call this with the same
        cfg, which is what keeps the wire agreement single-sourced)."""
        k = dict(DELTA_KNOBS)
        k.update({key: v for key, v in knobs.items() if key in DELTA_KNOBS})
        return cls(
            template,
            bucket_mb=float(k["delta_bucket_mb"]),
            codec=k["delta_codec"],
            codec_kw=k["delta_codec_kw"],
            max_rel_error=float(k["delta_max_rel_error"]),
            probe_every=int(k["delta_probe_every"]),
            min_saving=float(k["delta_min_saving"]),
        )
