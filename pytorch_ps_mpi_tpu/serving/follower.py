"""Follower tier: a read-only replica that subscribes to an upstream
read tier's delta stream and re-serves it.

:class:`FollowerLoop` is the subscription side of the distribution
tree: it runs a :class:`~.net.ServingReader` against the upstream
(root or intermediate replica) read port and republishes every new
version — pinned to the UPSTREAM's version number — into a local
:class:`~.core.ServingCore`, whose own read server (native or Python)
then serves downstream readers or further replicas.  Chaining
follower → follower builds the tree: the trainer-side core serves N
replicas instead of N×10⁴ readers, and every hop re-serves deltas from
its own ring, so "I have v → latest" stays cheap at every level.

Pacing is demand-driven, tpu_watch-style: each ``not_modified`` poll
doubles the sleep up to ``max_poll_s`` (an idle follower stops burning
a core); any new version snaps it back to ``poll_s``.  Upstream loss
(root restart, network partition) is survived by the resilient
reconnect path — the reader is torn down and re-dialed with the same
exponential backoff, and the replica keeps serving its last published
version the whole time (readers see a stale-but-consistent tree, never
an error).

Accounting flows into the canonical metrics surface through the local
core: ``replica_lag_versions`` (how far this replica trails the latest
upstream version it has observed — EWMA-decayed on idle polls, never
snapped to zero, so a lag spike stays visible for a few windows) and
``follower_bytes_relayed`` (bytes pulled from upstream and re-served),
plus optional ``kind="reader_round"`` anatomy rows so the replica's
pull cadence is visible next to the server rounds that produced the
versions.

Freshness: every republish relays the upstream version's FRS1 birth
record with this hop's record appended (arrival wall on THIS clock,
skew vs upstream from the reader's lower-envelope fit), so a version's
trailer accumulates the whole chain root → … → this replica and the
local core's age-of-information gauge is meaningful across hosts.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np


class FollowerLoop:
    """Subscribe to an upstream read tier; republish into ``core``.

    Parameters
    ----------
    core:
        The local :class:`~.core.ServingCore` to republish into (armed
        with its own ``read_port`` so downstream readers can connect).
    host, port:
        Upstream read-tier endpoint (the root's — or another replica's
        — ``read_port``).
    template:
        Parameter pytree template (defaults to ``core.template``);
        required to decode the upstream payloads.
    poll_s / max_poll_s:
        Pull cadence bounds: every ``not_modified`` doubles the sleep
        from ``poll_s`` up to ``max_poll_s``; a new version resets it.
    anatomy:
        Optional :class:`~..telemetry.anatomy.RoundAnatomy`; each poll
        that lands a new version writes a ``reader_round`` row.
    """

    def __init__(self, core, host: str, port: int, *,
                 template=None, tenant: str = "",
                 poll_s: float = 0.25, max_poll_s: float = 8.0,
                 timeout: float = 10.0,
                 serving_kw: Optional[dict] = None,
                 anatomy=None):
        self.core = core
        self.host = str(host)
        self.port = int(port)
        self.template = template if template is not None else core.template
        if self.template is None:
            raise ValueError("FollowerLoop needs a parameter template "
                             "(pass template= or arm the core with one)")
        self.tenant = str(tenant)
        self.poll_s = float(poll_s)
        self.max_poll_s = max(float(max_poll_s), self.poll_s)
        self.timeout = float(timeout)
        self.serving_kw = dict(serving_kw or {})
        self.anatomy = anatomy
        from pytorch_ps_mpi_tpu.telemetry.diagnosis import Ewma

        # replica lag decays through an EWMA (the diagnosis.py
        # discipline) instead of snapping to zero on idle polls: a lag
        # spike observed at pull time stays visible to the controller
        # for a few windows instead of vanishing one poll later
        self._lag_ewma = Ewma(alpha=0.25)
        self._reader = None
        self._sleep_s = self.poll_s
        self._relayed_mark = 0  # reader.bytes_received already credited
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # accounting (smokes/tests read these)
        self.polls = 0
        self.republished = 0
        self.not_modified = 0
        self.reconnects = 0
        self.upstream_version = 0
        self.last_error: Optional[str] = None

    # -- one pull ---------------------------------------------------------
    def _connect(self):
        from pytorch_ps_mpi_tpu.serving.net import ServingReader

        reader = ServingReader(
            self.host, self.port, self.template, tenant=self.tenant,
            timeout=self.timeout, serving_kw=self.serving_kw)
        self._relayed_mark = 0
        return reader

    def _extend_trailer(self, reader, version: int) -> bytes:
        """The upstream trailer for ``version`` with THIS hop's record
        appended (arrival wall on this clock, skew vs upstream from the
        reader's lower-envelope fit). ``b""`` — republish with no
        trailer — when upstream sent none or it describes a different
        version (a publish raced the pull): the birth record is
        relayed exactly or not at all, never re-stamped downstream."""
        doc = reader.fresh
        if doc is None or doc["version"] != version:
            return b""
        from pytorch_ps_mpi_tpu.telemetry.freshness import append_hop

        try:
            return append_hop(reader.fresh_raw, doc["hop_count"] + 1,
                              reader.fresh_recv_wall,
                              skew_ms=reader.reader_skew_s() * 1e3)
        except ValueError:
            return b""

    def _teardown(self) -> None:
        if self._reader is not None:
            try:
                self._reader.close()
            except Exception:
                pass
            self._reader = None

    def repoint(self, host: str, port: int) -> bool:
        """Re-parent the subscription (structural control: the replica
        tree reshapes under scale-out/in): tear down the current reader
        and aim the next poll at ``host:port``.  Idempotent; safe to
        call from another thread — the poll loop only ever sees a
        ``None`` reader and re-dials the (atomically updated) endpoint.
        The local core keeps serving its last published version across
        the switch, and version pinning is upstream-global (the root's
        counter), so a re-parented replica never goes backwards."""
        host, port = str(host), int(port)
        if (host, port) == (self.host, self.port) \
                and self._reader is not None:
            return False
        self.host, self.port = host, port
        self._teardown()
        self._sleep_s = self.poll_s  # re-dial promptly on the new parent
        return True

    def step(self) -> Dict[str, Any]:
        """One poll against upstream.  Returns a status row
        (``outcome`` is one of ``republished`` / ``not_modified`` /
        ``retry``); never raises — upstream failures become
        ``outcome="retry"`` with the reconnect backoff armed."""
        self.polls += 1
        t0 = time.perf_counter()
        try:
            if self._reader is None:
                self._reader = self._connect()
                self.reconnects += 1
            reader = self._reader
            before = self.core.latest_version(None)
            _, version = reader.read_params()
            self.upstream_version = max(self.upstream_version, int(version))
            # credit only the NEW bytes this poll pulled off the wire
            fresh = reader.bytes_received - self._relayed_mark
            self._relayed_mark = reader.bytes_received
            if fresh > 0:
                self.core.note_relayed(fresh)
            lag = max(0, int(version) - before)
            if int(version) > before:
                # lag as observed at pull time: how far downstream was
                # behind the instant the new version arrived — folded
                # into the EWMA, so it decays over later polls instead
                # of being clobbered back to zero
                self._lag_ewma.update(float(lag))
                self.core.set_replica_lag(self._lag_ewma.value)
                # the store adopts + freezes its input; the reader keeps
                # applying deltas to _flat, so hand the ring a copy
                self.core.publish(
                    flat=np.array(reader._flat, dtype=np.float32),
                    version=int(version), template=self.template,
                    fresh=self._extend_trailer(reader, int(version)))
                self.republished += 1
                self._sleep_s = self.poll_s
                outcome = "republished"
                row = {"outcome": outcome, "version": int(version),
                       "lag": lag,
                       # wall age (this clock, skew-corrected) of the
                       # version at the moment it was pulled
                       "age_ms": round(reader.fresh_age_ms(), 3),
                       "relayed_bytes": int(max(fresh, 0)),
                       "pull_s": round(time.perf_counter() - t0, 6),
                       "upstream": f"{self.host}:{self.port}"}
                if self.anatomy is not None:
                    self.anatomy.observe_reader_round(dict(row))
                return row
            self.not_modified += 1
            # idle: the observed lag DECAYS (EWMA toward zero) — the
            # replica is provably current, but the spike that preceded
            # catch-up stays visible for a few windows
            self._lag_ewma.update(0.0)
            self.core.set_replica_lag(self._lag_ewma.value)
            # idle: exponential backoff so a quiet upstream costs ~0
            self._sleep_s = min(self._sleep_s * 2.0, self.max_poll_s)
            outcome = "not_modified"
        except (ConnectionError, TimeoutError, OSError, RuntimeError) as e:
            # resilient reconnect: drop the broken reader, back off, and
            # re-dial next poll — the local core keeps serving its last
            # published version throughout (root-restart survival)
            self.last_error = f"{type(e).__name__}: {e}"
            self._teardown()
            self._sleep_s = min(max(self._sleep_s, self.poll_s) * 2.0,
                                self.max_poll_s)
            outcome = "retry"
        return {"outcome": outcome, "version": self.upstream_version,
                "lag": max(0, self.upstream_version
                           - self.core.latest_version(None)),
                "sleep_s": round(self._sleep_s, 3)}

    # -- lifecycle --------------------------------------------------------
    def run(self, stop: Optional[threading.Event] = None) -> None:
        """Poll until ``stop`` (or :meth:`close`) is set."""
        stop = stop or self._stop
        while not (stop.is_set() or self._stop.is_set()):
            self.step()
            stop.wait(self._sleep_s)
        self._teardown()

    def start(self) -> "FollowerLoop":
        """Run :meth:`run` on a daemon thread (the serve_readonly
        ``--follow-endpoint`` path)."""
        self._thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"follower:{self.host}:{self.port}")
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout + 5)
            self._thread = None
        self._teardown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
