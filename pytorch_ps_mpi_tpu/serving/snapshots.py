"""Immutable, refcounted, versioned parameter snapshots (ring of last K).

The storage half of the read tier (:mod:`pytorch_ps_mpi_tpu.serving`):
every ``publish`` lands one :class:`Snapshot` — the flat f32 parameter
vector, frozen (``writeable=False``) so a reader can never observe a
torn or mutated view — in a bounded ring of the last ``ring`` versions.
Readers ``acquire`` a version (refcount++), fan out zero-copy
``memoryview``\\ s of its bytes (the shm-transport story: N concurrent
readers share ONE buffer; the TCP read tier sends the same view through
the socket without an intermediate copy), and ``release`` when done.

Eviction is ring-driven (oldest version beyond K drops out), but an
evicted snapshot that still has readers stays alive until its last
``release`` — the refcount is what makes handing out zero-copy views
safe, and what the ``refs_out`` occupancy metric reports. Everything is
guarded by one lock; operations are O(1)-ish dictionary moves, so the
publish hot path pays a few microseconds when serving is armed and
nothing at all when it is not (the :class:`~.core.ServingCore` only
instantiates a store when the read tier is on).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np


class Snapshot:
    """One published version: an immutable flat f32 vector + metadata."""

    __slots__ = ("version", "flat", "created_wall", "refs")

    def __init__(self, version: int, flat: np.ndarray):
        flat = np.ascontiguousarray(flat, np.float32)
        # freeze: every reader shares this ONE buffer; a writable array
        # would let an in-process reader corrupt every other reader's view
        flat.flags.writeable = False
        self.version = int(version)
        self.flat = flat
        self.created_wall = time.time()
        self.refs = 0

    @property
    def nbytes(self) -> int:
        return self.flat.nbytes

    def view(self) -> memoryview:
        """Zero-copy read-only bytes of the snapshot (valid while the
        snapshot is held — acquire before fanning views out)."""
        return memoryview(self.flat.view(np.uint8))


class SnapshotStore:
    """Ring of the last ``ring`` published versions, refcounted.

    ``put`` is called from the serve/publish thread; ``acquire`` /
    ``release`` / ``latest`` from reader threads — one lock covers the
    ring bookkeeping (the array payloads themselves are immutable, so
    readers never hold the lock while using a snapshot).
    """

    def __init__(self, ring: int = 8):
        if ring < 1:
            raise ValueError(f"snapshot ring must hold >= 1, got {ring}")
        self.ring = int(ring)
        self._lock = threading.Lock()
        self._by_version: Dict[int, Snapshot] = {}
        self._order: List[int] = []  # insertion order (versions ascend)
        # evicted-but-still-referenced snapshots: alive until release
        self._zombies: Dict[int, Snapshot] = {}
        self.puts = 0
        self.evictions = 0

    def put(self, version: int, flat: np.ndarray) -> Snapshot:
        """Land one immutable snapshot; evicts past the ring bound.
        ``flat`` is adopted (callers pass a freshly flattened vector —
        the store freezes it; pass a copy if you must keep writing)."""
        snap = Snapshot(version, flat)
        with self._lock:
            prev = self._by_version.get(snap.version)
            if prev is not None:
                # re-publish of a pinned version (serve_readonly can pin
                # versions from checkpoint contents): replace, never leave
                # a duplicate _order entry whose eviction would drop the
                # live snapshot
                self._order.remove(snap.version)
                if prev.refs > 0:
                    self._zombies[snap.version] = prev
            self._by_version[snap.version] = snap
            self._order.append(snap.version)
            self.puts += 1
            while len(self._order) > self.ring:
                old = self._order.pop(0)
                dropped = self._by_version.pop(old, None)
                self.evictions += 1
                if dropped is not None and dropped.refs > 0:
                    # readers still hold it: parked until the last release
                    self._zombies[old] = dropped
        return snap

    def resize(self, ring: int) -> None:
        """Live ring-depth change (the control plane's read-tier tuning).
        Growing simply admits more versions; shrinking evicts the oldest
        immediately with the same refcount discipline as :meth:`put`
        (held snapshots park as zombies until their last release)."""
        if ring < 1:
            raise ValueError(f"snapshot ring must hold >= 1, got {ring}")
        with self._lock:
            self.ring = int(ring)
            while len(self._order) > self.ring:
                old = self._order.pop(0)
                dropped = self._by_version.pop(old, None)
                self.evictions += 1
                if dropped is not None and dropped.refs > 0:
                    self._zombies[old] = dropped

    def latest(self) -> Optional[Snapshot]:
        with self._lock:
            if not self._order:
                return None
            return self._by_version[self._order[-1]]

    def get(self, version: int) -> Optional[Snapshot]:
        """Ring lookup (NOT acquired — use :meth:`acquire` to hold it)."""
        with self._lock:
            return self._by_version.get(int(version))

    def acquire(self, version: Optional[int] = None) -> Optional[Snapshot]:
        """Pin a version (``None`` = latest) against eviction-death;
        returns None when it is not in the ring (aged out — the caller
        falls back to a full read of latest)."""
        with self._lock:
            if version is None:
                if not self._order:
                    return None
                snap = self._by_version[self._order[-1]]
            else:
                snap = self._by_version.get(int(version))
                if snap is None:
                    return None
            snap.refs += 1
            return snap

    def release(self, snap: Snapshot) -> None:
        with self._lock:
            snap.refs -= 1
            if snap.refs <= 0:
                self._zombies.pop(snap.version, None)

    # -- accounting -------------------------------------------------------
    def versions(self) -> List[int]:
        with self._lock:
            return list(self._order)

    def occupancy(self) -> int:
        with self._lock:
            return len(self._order)

    def refs_out(self) -> int:
        """Snapshots handed out and not yet released (ring + zombies)."""
        with self._lock:
            live = sum(s.refs for s in self._by_version.values())
            return live + sum(s.refs for s in self._zombies.values())

    def snapshot(self) -> Dict[str, object]:
        """Occupancy document for ``/health``'s ``serving`` section."""
        with self._lock:
            versions = list(self._order)
            latest = versions[-1] if versions else 0
            refs = (sum(s.refs for s in self._by_version.values())
                    + sum(s.refs for s in self._zombies.values()))
            return {
                "ring": self.ring,
                "occupancy": len(versions),
                "versions": versions,
                "latest": latest,
                "refs_out": refs,
                "zombies": len(self._zombies),
                "puts": self.puts,
                "evictions": self.evictions,
            }
