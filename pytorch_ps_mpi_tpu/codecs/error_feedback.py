"""Error-feedback wrapper (EF-SGD, Karimireddy et al. 2019).

Wraps any codec: the quantization/sparsification residual is accumulated
into per-worker memory and added back before the next encode, restoring
convergence for biased codecs (sign, top-k). The memory is explicit codec
state threaded through the train step — the principled replacement for the
reference's mutable ``code.codes`` side channel (``ps.py:165``).
"""

from __future__ import annotations

import jax.numpy as jnp

from pytorch_ps_mpi_tpu.codecs.base import Codec, register_codec


@register_codec("ef")
class ErrorFeedback(Codec):
    def __init__(self, inner: Codec = None, inner_name: str = None, **inner_kwargs):
        if inner is None:
            from pytorch_ps_mpi_tpu.codecs.base import get_codec
            inner = get_codec(inner_name or "topk", **inner_kwargs)
        self.inner = inner
        self.needs_rng = inner.needs_rng

    def init_state(self, shape, dtype):
        return {"memory": jnp.zeros(shape, dtype), "inner": self.inner.init_state(shape, dtype)}

    def encode(self, grad, state=(), rng=None):
        corrected = grad + state["memory"]
        payload, inner_state = self.inner.encode(corrected, state["inner"], rng)
        transmitted = self.inner.decode(payload, grad.shape, grad.dtype)
        new_state = {"memory": corrected - transmitted, "inner": inner_state}
        return payload, new_state

    def decode(self, payload, shape, dtype):
        return self.inner.decode(payload, shape, dtype)

    def decode_sum(self, payloads, shape, dtype):
        return self.inner.decode_sum(payloads, shape, dtype)

    # -- aggregation delegates to the inner codec: EF state lives on the
    # -- worker (encode side); the receive-side algebra is the inner's
    @property
    def supports_aggregate(self):
        return self.inner.supports_aggregate

    @property
    def agg_exact(self):
        return self.inner.agg_exact

    def can_aggregate(self, shape, dtype):
        return self.inner.can_aggregate(shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        return self.inner.aggregate(payloads, shape, dtype)

    def agg_decode(self, agg_payload, meta, shape, dtype):
        return self.inner.agg_decode(agg_payload, meta, shape, dtype)

    def agg_init(self, shape, dtype):
        return self.inner.agg_init(shape, dtype)

    def agg_fold(self, acc, payload):
        return self.inner.agg_fold(acc, payload)

    def agg_finalize(self, acc, shape, dtype):
        return self.inner.agg_finalize(acc, shape, dtype)

    def payload_bits(self, shape, dtype):
        return self.inner.payload_bits(shape, dtype)

    def fidelity_probe(self, grad, state=(), rng=None):
        """Probe the INNER codec on the error-corrected gradient (what
        actually rides the wire: grad + memory) and additionally export
        the residual-memory norm — EF's correctness hinges on that
        residual staying bounded (Karimireddy et al. 2019, Thm. 2), so
        it is the one extra number worth a time series. Read-only, like
        the base probe: the memory is consulted, never updated."""
        import jax
        import numpy as np

        if not jax.tree.leaves(state):
            state = self.init_state(grad.shape, grad.dtype)
        corrected = grad + state["memory"]
        out = self.inner.fidelity_probe(corrected, state["inner"], rng)
        mem = np.asarray(state["memory"], np.float32)
        out["ef_residual_norm"] = float(np.linalg.norm(mem.reshape(-1)))
        return out
