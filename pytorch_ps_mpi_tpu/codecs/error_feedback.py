"""Error-feedback wrapper (EF-SGD, Karimireddy et al. 2019).

Wraps any codec: the quantization/sparsification residual is accumulated
into per-worker memory and added back before the next encode, restoring
convergence for biased codecs (sign, top-k). The memory is explicit codec
state threaded through the train step — the principled replacement for the
reference's mutable ``code.codes`` side channel (``ps.py:165``).

Two EF placements exist since the hierarchical-aggregation tree
(``parallel.tree``):

- :class:`ErrorFeedback` — the classic WORKER-side wrapper: residual
  memory per worker, corrected at the encode site, threaded as codec
  state through the jitted step.
- :class:`HopErrorFeedback` — the per-HOP form a tree LEADER runs on the
  host: the leader folds its group's compressed payloads (one decode
  never happens per push), and when it re-encodes the folded aggregate
  for the upstream hop, the re-encode's residual is accumulated in
  leader-local memory and added back into the NEXT round's aggregate.
  Each hop's error is therefore bounded by its own EF recursion
  (Karimireddy et al.'s Thm. 2 applies per hop), and the hops COMPOSE:
  worker-side EF bounds the worker→leader encode error, hop EF bounds
  the leader→root re-encode error, so end-to-end fidelity degrades
  additively in the number of hops rather than multiplicatively. The
  caveat (documented in docs/OPERATIONS.md): hop residual memory lives
  on the leader, so a leader crash loses at most one round's residual —
  the group's fallback pushes are NOT corrected for the dead leader's
  unflushed residual.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import Codec, register_codec


@register_codec("ef")
class ErrorFeedback(Codec):
    def __init__(self, inner: Codec = None, inner_name: str = None, **inner_kwargs):
        if inner is None:
            from pytorch_ps_mpi_tpu.codecs.base import get_codec
            inner = get_codec(inner_name or "topk", **inner_kwargs)
        self.inner = inner
        self.needs_rng = inner.needs_rng

    def init_state(self, shape, dtype):
        return {"memory": jnp.zeros(shape, dtype), "inner": self.inner.init_state(shape, dtype)}

    def encode(self, grad, state=(), rng=None):
        corrected = grad + state["memory"]
        payload, inner_state = self.inner.encode(corrected, state["inner"], rng)
        transmitted = self.inner.decode(payload, grad.shape, grad.dtype)
        new_state = {"memory": corrected - transmitted, "inner": inner_state}
        return payload, new_state

    def decode(self, payload, shape, dtype):
        return self.inner.decode(payload, shape, dtype)

    def decode_sum(self, payloads, shape, dtype):
        return self.inner.decode_sum(payloads, shape, dtype)

    # -- aggregation delegates to the inner codec: EF state lives on the
    # -- worker (encode side); the receive-side algebra is the inner's
    @property
    def supports_aggregate(self):
        return self.inner.supports_aggregate

    @property
    def agg_exact(self):
        return self.inner.agg_exact

    def can_aggregate(self, shape, dtype):
        return self.inner.can_aggregate(shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        return self.inner.aggregate(payloads, shape, dtype)

    def agg_decode(self, agg_payload, meta, shape, dtype):
        return self.inner.agg_decode(agg_payload, meta, shape, dtype)

    def agg_init(self, shape, dtype):
        return self.inner.agg_init(shape, dtype)

    def agg_fold(self, acc, payload):
        return self.inner.agg_fold(acc, payload)

    def agg_finalize(self, acc, shape, dtype):
        return self.inner.agg_finalize(acc, shape, dtype)

    def payload_bits(self, shape, dtype):
        return self.inner.payload_bits(shape, dtype)

    def fidelity_probe(self, grad, state=(), rng=None):
        """Probe the INNER codec on the error-corrected gradient (what
        actually rides the wire: grad + memory) and additionally export
        the residual-memory norm — EF's correctness hinges on that
        residual staying bounded (Karimireddy et al. 2019, Thm. 2), so
        it is the one extra number worth a time series. Read-only, like
        the base probe: the memory is consulted, never updated."""
        import jax
        import numpy as np

        if not jax.tree.leaves(state):
            state = self.init_state(grad.shape, grad.dtype)
        corrected = grad + state["memory"]
        out = self.inner.fidelity_probe(corrected, state["inner"], rng)
        mem = np.asarray(state["memory"], np.float32)
        out["ef_residual_norm"] = float(np.linalg.norm(mem.reshape(-1)))
        return out


class HopErrorFeedback:
    """Per-hop error feedback for an aggregation-tree leader's re-encode.

    The leader's hop is ``finalize (group aggregate) → encode → push
    upstream``; the encode is lossy for compressing codecs, and without
    correction the loss would compound hop over hop. This class keeps
    the hop's residual in LEADER-local host memory, keyed to the wire's
    template leaves: every round the residual is added back into the
    aggregate before encoding, and the new residual is measured against
    the decode of the EXACT payload that ships (bit-for-bit what the
    parent will see) — the EF recursion, applied at the hop instead of
    the worker. Host numpy throughout: no jit dispatch beyond the wire's
    own jitted encode/decode, and the decode-back is the one extra
    decode a correction-by-definition requires (it never counts against
    the leader's ``decodes_done``, which tracks PER-PUSH ingest decodes
    — the tree's "zero decodes at leaders" invariant).

    ``enabled=False`` turns the whole thing into a plain ``encode`` (no
    decode-back, no residual) — ``cfg["hop_ef"]`` plumbs it.
    """

    def __init__(self, wire, enabled: bool = True):
        self.wire = wire
        self.enabled = bool(enabled)
        self._residual = None      # per-leaf flat f32 arrays
        self.residual_norm = 0.0   # ||residual|| after the last hop
        self.last_rel_error = 0.0  # hop rel-L2 error BEFORE correction ref
        self.rounds = 0

    def encode(self, grad_tree):
        """``grad + residual`` → payload bytes (the wire's ping-pong
        buffer — ship or seal before the next-next encode). Updates the
        residual from the shipped payload's decode when enabled."""
        import jax

        leaves = [np.asarray(x, np.float32)
                  for x in self.wire.treedef.flatten_up_to(grad_tree)]
        if self.enabled and self._residual is not None:
            leaves = [x + r for x, r in zip(leaves, self._residual)]
        corrected = jax.tree.unflatten(self.wire.treedef, leaves)
        payload = self.wire.encode_to_bytes(corrected)
        if self.enabled:
            sent = self.wire.treedef.flatten_up_to(
                self.wire.decode_from_bytes(payload))
            self._residual = [
                c - np.asarray(t, np.float32)
                for c, t in zip(leaves, sent)
            ]
            res_sq = sum(float(np.vdot(r, r)) for r in self._residual)
            cor_sq = sum(float(np.vdot(c, c)) for c in leaves)
            self.residual_norm = res_sq ** 0.5
            self.last_rel_error = (res_sq ** 0.5) / max(cor_sq ** 0.5, 1e-30)
        self.rounds += 1
        return payload

    def probe(self) -> dict:
        """The hop's fidelity numbers for lineage hop rows / metrics."""
        return {
            "hop_ef": self.enabled,
            "rounds": self.rounds,
            "ef_residual_norm": round(self.residual_norm, 6),
            "hop_rel_error": round(self.last_rel_error, 6),
        }
