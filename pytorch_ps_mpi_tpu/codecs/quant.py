"""Quantization codecs: deterministic int8 and stochastic QSGD.

The on-device replacement for the reference's host-side blosc byte
compression (``mpi_comms.py:18-30``): instead of entropy-coding pickled
bytes on the CPU (which an ICI link outruns by orders of magnitude), the
gradient itself is narrowed to 8 or fewer bits per element before the
collective. The int8 path has a fused Pallas kernel on TPU
(``ops/quant_pallas.py``); this module is the portable jnp reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    check_nonfinite_mode,
    dense_agg_finalize,
    guard_nonfinite,
    register_codec,
    scalefold_agg_init,
)


@jax.jit
def _fused_scale_fold(acc, q, scale):
    """acc + scale * q in ONE fused pass (int8 payload in, f32 out)."""
    return acc + q.astype(jnp.float32) * scale


class _ScaleFoldedInt8(Codec):
    """Shared exact integer-domain aggregation for codecs whose decode is
    ``scale × q`` over an int8 payload (int8's absmax scale, QSGD's
    norm/levels). The batch form contracts the [world, n] int8 payload
    against the per-frame scale vector in ONE widened-accumulator einsum
    — never materializing the [world, n] f32 dequantized intermediate
    (at ResNet scale × 8 workers that is ~1.4 GB of HBM traffic just to
    feed a sum) — and ``decode_sum`` routes through it, so the two paths
    are one code path (bit-exact by construction). The streaming form
    folds scale_w × q_w into an f32 accumulator per push: the jitted
    fused kernel above the ``base.FOLD_JIT_MIN`` crossover (one SIMD
    dequant-multiply-add pass), pure numpy below it (no dispatch cost).
    Subclasses provide the scale in both shapes."""

    supports_aggregate = True

    def _batch_scales(self, payloads) -> jax.Array:
        """Per-frame scale vector, [world] f32."""
        raise NotImplementedError

    def _frame_scale(self, payload) -> np.float32:
        """One frame's scale scalar (numpy, host-side)."""
        raise NotImplementedError

    def decode_sum(self, payloads, shape, dtype):
        agg, meta = self.aggregate(payloads, shape, dtype)
        return self.agg_decode(agg, meta, shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        q = payloads["q"]                     # [world, n] int8
        acc = jnp.einsum("wn,w->n", q, self._batch_scales(payloads),
                         preferred_element_type=jnp.float32)
        return {"acc": acc}, {"frames": int(q.shape[0])}

    def agg_decode(self, agg_payload, meta, shape, dtype):
        return agg_payload["acc"].astype(dtype).reshape(shape)

    def agg_init(self, shape, dtype):
        return scalefold_agg_init(shape)

    def agg_fold(self, acc, payload):
        scale = self._frame_scale(payload)
        lib = acc.get("lib")
        if lib is not None:
            # native fast path: ONE fused dequant-multiply-add pass in
            # C++ over the int8 payload view — no temp, no dispatch
            from pytorch_ps_mpi_tpu.utils import native as _native

            _native.fold_scaled_i8(
                lib, acc["acc"],
                np.ascontiguousarray(payload["q"], np.int8).reshape(-1),
                scale)
        elif acc.get("jit"):
            acc["acc"] = _fused_scale_fold(
                acc["acc"], payload["q"].reshape(-1), scale)
        else:
            np.multiply(payload["q"].reshape(-1), scale, out=acc["tmp"])
            acc["acc"] += acc["tmp"]
        acc["frames"] += 1

    def agg_finalize(self, acc, shape, dtype):
        return dense_agg_finalize(acc, shape, dtype)

    def payload_bits(self, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        return n * 8 + 32


@register_codec("int8")
class Int8Codec(_ScaleFoldedInt8):
    """Per-tensor symmetric int8: q = round(g / scale), scale = max|g|/127.

    ``use_pallas`` defaults to False: measured under Mosaic on a v5e
    (``benchmarks/codec_bench.py``, 8M elems), XLA's fused abs-max +
    quantize beats the two-pass SMEM Pallas kernel 6× (0.16 ms vs
    0.96 ms enc+dec) — the kernel's extra HBM pass for the absmax loses
    to XLA's fusion. The kernel stays available for layout experiments.
    """

    # shape-agnostic + stateless: bucketed aggregation quantizes with a
    # per-BUCKET absmax scale instead of per-tensor (coarser scale group)
    bucketable = True

    def __init__(self, use_pallas: bool = False,
                 nonfinite: str = "propagate"):
        self.use_pallas = use_pallas
        # one Inf element drives the absmax scale to Inf (every other
        # element quantizes to 0); a NaN scale poisons the whole decode —
        # guard per codecs/base.guard_nonfinite
        self.nonfinite = check_nonfinite_mode(nonfinite)

    def encode(self, grad, state=(), rng=None):
        flat = guard_nonfinite(grad.reshape(-1), self.nonfinite, "Int8Codec")
        if self.use_pallas:
            from pytorch_ps_mpi_tpu.ops.quant_pallas import quantize_int8
            q, scale = quantize_int8(flat)
        else:
            scale = jnp.maximum(jnp.max(jnp.abs(flat)) / 127.0, 1e-12)
            q = jnp.clip(jnp.round(flat / scale), -127, 127).astype(jnp.int8)
        return {"q": q, "scale": scale.astype(jnp.float32)}, state

    def decode(self, payload, shape, dtype):
        return (payload["q"].astype(dtype) * payload["scale"].astype(dtype)).reshape(shape)

    def _batch_scales(self, payloads):
        return payloads["scale"].astype(jnp.float32)

    def _frame_scale(self, payload):
        return np.float32(payload["scale"])


@register_codec("qsgd")
class QSGDCodec(_ScaleFoldedInt8):
    """QSGD (Alistarh et al. 2017): stochastic uniform quantization to
    ``levels`` buckets of the normalized magnitude; unbiased."""

    needs_rng = True
    # per-bucket norm instead of per-tensor under bucketing; still unbiased
    bucketable = True

    def __init__(self, levels: int = 16, nonfinite: str = "propagate"):
        # levels must fit the int8 payload: encode stores q in [-levels,
        # levels], so levels > 127 would silently overflow int8.
        if not 1 <= levels <= 127:
            raise ValueError(f"levels must be in [1, 127], got {levels}")
        self.levels = int(levels)
        # a non-finite element makes the L2 norm NaN/Inf, turning every
        # quantized magnitude into garbage (NaN probabilities round the
        # stochastic rounding to 0) — guard per codecs/base.guard_nonfinite
        self.nonfinite = check_nonfinite_mode(nonfinite)

    def encode(self, grad, state=(), rng=None):
        assert rng is not None, "QSGDCodec needs a PRNG key"
        flat = guard_nonfinite(grad.reshape(-1), self.nonfinite, "QSGDCodec")
        norm = jnp.maximum(jnp.linalg.norm(flat), 1e-12)
        scaled = jnp.abs(flat) / norm * self.levels          # in [0, levels]
        lower = jnp.floor(scaled)
        prob_up = scaled - lower
        up = jax.random.uniform(rng, flat.shape) < prob_up
        q = (lower + up.astype(flat.dtype)).astype(jnp.int8)  # levels ≤ 127
        signs = jnp.signbit(flat)
        return {
            "q": jnp.where(signs, -q, q).astype(jnp.int8),
            "norm": norm.astype(jnp.float32),
        }, state

    def decode(self, payload, shape, dtype):
        g = payload["q"].astype(dtype) * (payload["norm"].astype(dtype) / self.levels)
        return g.reshape(shape)

    def _batch_scales(self, payloads):
        return payloads["norm"].astype(jnp.float32) / self.levels

    def _frame_scale(self, payload):
        return np.float32(payload["norm"]) / np.float32(self.levels)
