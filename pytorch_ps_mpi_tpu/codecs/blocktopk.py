"""Blockwise top-k sparsification: selection tiled for the TPU.

Global top-k over a 132M-element flat gradient is the sparse-codec cost
problem (VERDICT r3 item 2: ``lax.approx_max_k`` measured 107 ms at 132M
on v5e — 7x the whole BERT train step it was meant to accelerate). The
global selection is the expensive part, not the gather: it sorts/scans
the full vector with cross-chip-of-the-array data movement.

Blockwise selection removes it. The flat gradient is viewed as
``[n_blocks, block_size]`` (lane-aligned ``block_size``, default 1024)
and each block keeps its own top ``round(block_size * fraction)``
entries — an embarrassingly parallel batched ``lax.top_k`` over rows,
mapping onto the VPU with zero cross-block traffic. The wire format is
identical to :class:`~.topk.TopKCodec` (values[k] + int32 global
indices[k]), so transports, EF wrapping and ``decode_sum`` fusion are
unchanged.

Selection quality: block-local top-k equals global top-k when large
entries are spread across blocks (the common case for gradient noise;
dense layers' gradients have no privileged memory order), and degrades
gracefully when they cluster — every block still ships its local
maxima, which is exactly the "each worker's own largest coordinates"
error-feedback literature tolerates (PAPERS.md: Stich et al. 2018 — EF
absorbs ANY contraction-factor selection, block-local included; pair
with ``ef`` for convergence-critical runs). The reference's external
``codings`` hook (SURVEY §2.2) put no constraint on selection semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import Codec, register_codec
from pytorch_ps_mpi_tpu.codecs.topk import TopKCodec


@register_codec("blocktopk")
class BlockTopKCodec(TopKCodec):
    def __init__(self, fraction: float = 0.01, block_size: int = 1024,
                 approx: bool = False):
        """``fraction`` of each block survives (>= 1 entry per block).
        ``block_size`` should stay a multiple of the 128-lane register
        width; 1024 = one row of 8 sublanes. ``approx=True`` uses the
        TPU's hardware ``approx_max_k`` per block instead of exact
        ``top_k`` (only worth it for large per-block k)."""
        super().__init__(fraction=fraction, approx=approx)
        if block_size <= 0 or block_size % 128:
            raise ValueError("block_size must be a positive multiple of 128")
        self.block_size = int(block_size)

    def _block_k(self) -> int:
        return max(1, int(round(self.block_size * self.fraction)))

    def _n_blocks(self, n: int) -> int:
        """Block count for an n-element gradient; 1 == the single-block
        plain-top-k fallback regime. The ONE place the fallback
        threshold and ceil-div rule live (four call sites)."""
        return 1 if n <= self.block_size else -(-n // self.block_size)

    def _k_for(self, shape) -> int:
        """Total payload length: per-block k x number of blocks (the
        wire-size contract ``payload_bits`` inherits). Tensors no larger
        than one block take plain top-k's fraction-of-n (matching the
        ``encode`` fallback)."""
        n = int(np.prod(shape)) if shape else 1
        nb = self._n_blocks(n)
        if nb == 1:
            return super()._k_for(shape)
        # NOT capped at n: a ragged tail block still emits block_k pairs
        # (pad-slot picks carry out-of-range indices, dropped at scatter),
        # and the wire carries every one of them
        return nb * self._block_k()

    def encode(self, grad, state=(), rng=None):
        flat = grad.reshape(-1)
        n = flat.shape[0]
        nb = self._n_blocks(n)
        if nb == 1:
            return super().encode(grad, state, rng)  # plain top-k
        pad = nb * self.block_size - n
        # padding must never win selection, and if a short final block
        # still selects a padded slot its global index lands >= n and is
        # dropped at scatter time (mode='drop' in decode/decode_sum)
        blocks = jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)]
        ).reshape(nb, self.block_size)
        kb = self._block_k()
        if self.approx:
            _, local = jax.lax.approx_max_k(jnp.abs(blocks), kb)
        else:
            _, local = jax.lax.top_k(jnp.abs(blocks), kb)
        glob = (jnp.arange(nb, dtype=jnp.int32)[:, None] * self.block_size
                + local.astype(jnp.int32))
        values = jnp.take_along_axis(blocks, local, axis=1)
        return {
            "values": values.reshape(-1),
            "indices": glob.reshape(-1),
        }, state
    # decode/decode_sum are inherited: TopKCodec scatters with
    # mode='drop', which discards this codec's >= n pad-slot indices and
    # is a no-op for plain top-k's always-in-range ones


@register_codec("blocktopk8")
class BlockTopK8Codec(BlockTopKCodec):
    """Compressed-sparse: blockwise top-k survivors with int8-quantized
    values (per-block symmetric scale). The two compression axes the
    reference's codings research explored separately — sparsification
    and quantization — composed: at fraction 1% the wire drops from
    top-k's 64 bits/survivor (f32 value + int32 index) to 40
    (int8 value + int32 index), ~1.6x less wire for one extra
    VPU-elementwise pass; selection cost is unchanged (same per-block
    ``top_k``). Survivors within a block share magnitude order (they ARE
    the block's largest), so a per-block scale loses little precision.
    Pair with ``ef`` to absorb the combined bias, as with any lossy
    codec."""

    def encode(self, grad, state=(), rng=None):
        payload, state = super().encode(grad, state, rng)
        v = payload["values"]  # [k_total] f32 (single-block: plain top-k)
        kb = v.shape[0] if self._n_blocks(grad.size) == 1 else self._block_k()
        blocks = v.reshape(-1, kb)
        scale = jnp.maximum(
            jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0, 1e-12
        )
        q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
        return {
            "values": q.reshape(-1),
            "scale": scale.astype(jnp.float32),
            "indices": payload["indices"],
        }, state

    @staticmethod
    def _dequant(payload, dtype):
        """int8 [.., k_total] x scale [.., nb, 1] -> float [.., k_total]
        (leading worker axis preserved for decode_sum's stacked form)."""
        q = payload["values"]
        nb = payload["scale"].shape[-2]
        blocks = q.reshape(q.shape[:-1] + (nb, -1)).astype(jnp.float32)
        return (blocks * payload["scale"]).reshape(q.shape).astype(dtype)

    def decode(self, payload, shape, dtype):
        return super().decode(
            {"values": self._dequant(payload, dtype),
             "indices": payload["indices"]},
            shape, dtype,
        )

    def decode_sum(self, payloads, shape, dtype):
        # via aggregate (which dequantizes): decode_sum(raw int8 payload)
        # and the compressed-domain path are one code path
        agg, meta = self.aggregate(payloads, shape, dtype)
        return self.agg_decode(agg, meta, shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        # dequantize per rank (payload-sized), then the inherited sparse
        # index-merge — identical values/order to decode_sum (bit-exact)
        return super().aggregate(
            {"values": self._dequant(payloads, dtype),
             "indices": payloads["indices"]},
            shape, dtype,
        )

    def agg_fold(self, acc, payload):
        # dequant of the int8 survivors (per-block scale), then the
        # sparse fold. Native fast path: wc_fold_sparse_q8 fuses the
        # dequantize-multiply and the scatter-add into one C++ pass over
        # the payload; otherwise numpy dequant + shared concat fold.
        from pytorch_ps_mpi_tpu.codecs.base import sparse_agg_fold
        from pytorch_ps_mpi_tpu.utils import native as _native

        q = np.asarray(payload["values"])
        scale = np.asarray(payload["scale"], np.float32)
        lib = acc.get("lib")
        if lib is not None:
            # retained copy feeds both the C++ call and the pooled
            # buffer's re-zero record (see base.py sparse pool)
            idx = np.array(payload["indices"], np.int32,
                           copy=True).reshape(-1)
            _native.fold_sparse_q8(
                lib, acc["acc"],
                np.ascontiguousarray(q, np.int8).reshape(-1),
                np.ascontiguousarray(scale).reshape(-1), idx,
                acc_ptr=acc["ptr"])
            acc["touched"].append(idx)
            acc["frames"] += 1
            return
        val = (q.reshape(scale.shape[0], -1).astype(np.float32)
               * scale).reshape(-1)
        sparse_agg_fold(acc, val, payload["indices"])

    def payload_bits(self, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        return self._k_for(shape) * (8 + 32) + self._n_blocks(n) * 32
