"""Blockwise top-k sparsification: selection tiled for the TPU.

Global top-k over a 132M-element flat gradient is the sparse-codec cost
problem (VERDICT r3 item 2: ``lax.approx_max_k`` measured 107 ms at 132M
on v5e — 7x the whole BERT train step it was meant to accelerate). The
global selection is the expensive part, not the gather: it sorts/scans
the full vector with cross-chip-of-the-array data movement.

Blockwise selection removes it. The flat gradient is viewed as
``[n_blocks, block_size]`` (lane-aligned ``block_size``, default 1024)
and each block keeps its own top ``round(block_size * fraction)``
entries — an embarrassingly parallel batched ``lax.top_k`` over rows,
mapping onto the VPU with zero cross-block traffic. The wire format is
identical to :class:`~.topk.TopKCodec` (values[k] + int32 global
indices[k]), so transports, EF wrapping and ``decode_sum`` fusion are
unchanged.

Selection quality: block-local top-k equals global top-k when large
entries are spread across blocks (the common case for gradient noise;
dense layers' gradients have no privileged memory order), and degrades
gracefully when they cluster — every block still ships its local
maxima, which is exactly the "each worker's own largest coordinates"
error-feedback literature tolerates (PAPERS.md: Stich et al. 2018 — EF
absorbs ANY contraction-factor selection, block-local included; pair
with ``ef`` for convergence-critical runs). The reference's external
``codings`` hook (SURVEY §2.2) put no constraint on selection semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import Codec, register_codec
from pytorch_ps_mpi_tpu.codecs.topk import TopKCodec


@register_codec("blocktopk")
class BlockTopKCodec(TopKCodec):
    def __init__(self, fraction: float = 0.01, block_size: int = 1024,
                 approx: bool = False):
        """``fraction`` of each block survives (>= 1 entry per block).
        ``block_size`` should stay a multiple of the 128-lane register
        width; 1024 = one row of 8 sublanes. ``approx=True`` uses the
        TPU's hardware ``approx_max_k`` per block instead of exact
        ``top_k`` (only worth it for large per-block k)."""
        super().__init__(fraction=fraction, approx=approx)
        if block_size <= 0 or block_size % 128:
            raise ValueError("block_size must be a positive multiple of 128")
        self.block_size = int(block_size)

    def _block_k(self) -> int:
        return max(1, int(round(self.block_size * self.fraction)))

    def _k_for(self, shape) -> int:
        """Total payload length: per-block k x number of blocks (the
        wire-size contract ``payload_bits`` inherits). Tensors no larger
        than one block take plain top-k's fraction-of-n (matching the
        ``encode`` fallback)."""
        n = int(np.prod(shape)) if shape else 1
        if n <= self.block_size:
            return super()._k_for(shape)
        nb = -(-n // self.block_size)
        # NOT capped at n: a ragged tail block still emits block_k pairs
        # (pad-slot picks carry out-of-range indices, dropped at scatter),
        # and the wire carries every one of them
        return nb * self._block_k()

    def encode(self, grad, state=(), rng=None):
        flat = grad.reshape(-1)
        n = flat.shape[0]
        if n <= self.block_size:
            return super().encode(grad, state, rng)  # one block: plain top-k
        nb = -(-n // self.block_size)
        pad = nb * self.block_size - n
        # padding must never win selection, and if a short final block
        # still selects a padded slot its global index lands >= n and is
        # dropped at scatter time (mode='drop' in decode/decode_sum)
        blocks = jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)]
        ).reshape(nb, self.block_size)
        kb = self._block_k()
        if self.approx:
            _, local = jax.lax.approx_max_k(jnp.abs(blocks), kb)
        else:
            _, local = jax.lax.top_k(jnp.abs(blocks), kb)
        glob = (jnp.arange(nb, dtype=jnp.int32)[:, None] * self.block_size
                + local.astype(jnp.int32))
        values = jnp.take_along_axis(blocks, local, axis=1)
        return {
            "values": values.reshape(-1),
            "indices": glob.reshape(-1),
        }, state
    # decode/decode_sum are inherited: TopKCodec scatters with
    # mode='drop', which discards this codec's >= n pad-slot indices and
    # is a no-op for plain top-k's always-in-range ones
