"""TernGrad codec: stochastic ternary gradients, 2 bits per element.

Wen et al. 2017 (arXiv:1705.07878): each coordinate becomes
``s·sign(g)·b`` with ``b ~ Bernoulli(|g|/s)`` and ``s = max|g|`` — an
unbiased estimator (``E[decode] = g``), the midpoint of the compression
curve between int8 (4x) and sign (32x). One more point on the research
surface the reference's external ``codings`` hook existed to explore
(SURVEY §2.2).

Wire format: ternary digits {0,1,2} (= value -1,0,+1) packed 4 per byte
base-4, plus a float32 scale — a true 16x wire reduction on float32
gradients, all on-device (no host compressor, SURVEY §2.4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    check_nonfinite_mode,
    dense_agg_finalize,
    guard_nonfinite,
    register_codec,
    scalefold_agg_init,
)

_WEIGHTS = (1, 4, 16, 64)  # base-4 digit weights, 4 ternary digits per byte


def _packed_len(n: int) -> int:
    return (n + 3) // 4


@partial(jax.jit, static_argnames=("n",))
def _fused_tern_fold(acc, packed, scale, n):
    """acc + scale · unpack(packed) in one fused pass."""
    digits = (packed[:, None]
              // jnp.asarray(_WEIGHTS, jnp.uint8)[None, :]) % 4
    tern = digits.reshape(-1)[:n].astype(jnp.int8) - 1
    return acc + tern.astype(jnp.float32) * scale


@register_codec("terngrad")
class TernGradCodec(Codec):
    needs_rng = True
    # per-bucket max|g| scale instead of per-tensor under bucketing;
    # unbiasedness is preserved (scale is shared, Bernoulli stays exact)
    bucketable = True
    # exact ternary-count algebra: the batch form contracts the unpacked
    # {-1,0,+1} digits against the per-frame scale vector in one widened-
    # accumulator einsum (decode_sum routes through it); the streaming
    # form folds scale × ternary per push into an f32 accumulator —
    # integer unpack, one fused multiply-add, no per-push jitted decode
    supports_aggregate = True

    def __init__(self, nonfinite: str = "propagate",
                 scan_block: int = 1 << 20, scan_threshold: int = 0,
                 use_pallas: bool = False):
        """``scan_block``/``scan_threshold``: gradients with at least
        ``scan_threshold`` elements (default ``4 * scan_block``) encode
        through a ``lax.scan`` over ``scan_block``-element chunks so XLA
        never materializes a full-size f32 intermediate — the fix for
        the 505 MB HLO temp the whole-tensor form allocated on a
        BERT-base gradient (BENCH_TPU_WATCH: the uniform draw + keep
        probability both went [132M] f32). Per-chunk PRNG keys derive
        from the round key by fold-in, so the stream differs from the
        whole-tensor form — irrelevant for an unbiased stochastic codec
        — while wire format and size are unchanged.

        ``use_pallas=True`` routes sizes divisible by 512 through the
        fused ternarize+pack kernel (``ops/tern_pallas.tern_pack``):
        compare → digit → base-4 pack in ONE VMEM pass over the
        gradient and a tile of raw random bits, so the f32 uniform
        draw, keep mask, and digit tensor never hit HBM. NOTE: the
        Pallas bit layout groups by sublane (digit s of packed byte
        [r, lane] holds element r*512 + s*128 + lane) while the jnp
        path packs 4 consecutive elements per byte — payloads are only
        self-consistent within one codec configuration, and the native
        C++ wire fold (flat layout) declines Pallas-layout units (the
        numpy fold handles both layouts)."""
        # a NaN/Inf element drives the max|g| scale non-finite AND makes
        # its keep-probability NaN (uniform < NaN is False, so the digit
        # silently collapses to 0) — guard per codecs/base.guard_nonfinite
        self.nonfinite = check_nonfinite_mode(nonfinite)
        if scan_block <= 0 or scan_block % 4:
            raise ValueError("scan_block must be a positive multiple of 4")
        self.scan_block = int(scan_block)
        self.scan_threshold = (int(scan_threshold) if scan_threshold > 0
                               else 4 * self.scan_block)
        self.use_pallas = bool(use_pallas)

    def _pallas_ok(self, n: int) -> bool:
        # 512 = one packed Pallas row (4 sublanes × 128 lanes). Above
        # the scan threshold the chunks must divide into rows too: with
        # scan_block % 512 == 0 every full chunk AND the ragged tail
        # inherit n's divisibility (tail ≡ n mod scan_block), and the
        # per-chunk packs concatenate into exactly the whole-tensor
        # Pallas layout (chunks are whole numbers of packed rows)
        if not (self.use_pallas and n > 0 and n % 512 == 0):
            return False
        return n < self.scan_threshold or self.scan_block % 512 == 0

    def _digits(self, g, scale, rng):
        """g (any shape) → ternary digits {0,1,2} (uint8, same shape)."""
        keep = jax.random.uniform(rng, g.shape) < (jnp.abs(g) / scale)
        # ternary digit: 0 -> -1, 1 -> 0, 2 -> +1
        return jnp.where(keep, jnp.where(g >= 0, 2, 0), 1).astype(jnp.uint8)

    def encode(self, grad, state=(), rng=None):
        assert rng is not None, "TernGradCodec needs a PRNG key"
        g = guard_nonfinite(grad.astype(jnp.float32), self.nonfinite,
                            "TernGradCodec")
        n = int(np.prod(g.shape)) if g.shape else 1
        weights = jnp.asarray(_WEIGHTS, jnp.uint8)

        def pack_digits(d):
            return (d.reshape(-1, 4) * weights).sum(axis=1).astype(jnp.uint8)

        pallas = self._pallas_ok(n)
        if pallas:
            from pytorch_ps_mpi_tpu.ops.tern_pallas import tern_pack
        if n >= self.scan_threshold:
            # chunked encode: scan over scan_block-element slices — the
            # absmax pass AND the Bernoulli/pack pass both run one chunk
            # at a time, so peak temp is a chunk's intermediates (XLA
            # reuses the loop-body buffers), never an n-sized f32 tensor
            # (the whole-tensor form materializes abs|g| + the uniform
            # draw: 505 MB of HLO temps on a BERT-base gradient,
            # BENCH_TPU_WATCH). A ragged tail (< scan_block elements)
            # encodes outside the scan with chunk-sized temps; its digit
            # offset stays 4-aligned because scan_block is.
            blk = self.scan_block
            nb_full = n // blk
            tail_n = n - nb_full * blk
            flat = g.reshape(-1)
            idxs = jnp.arange(nb_full, dtype=jnp.int32)

            def chunk(i):
                # dynamic_slice, not a pre-reshaped xs array: the scan
                # reads blk elements straight out of the input buffer,
                # so no n-sized copy exists even at ragged sizes
                return jax.lax.dynamic_slice(flat, (i * blk,), (blk,))

            def mx_body(m, i):
                return jnp.maximum(m, jnp.max(jnp.abs(chunk(i)))), None

            scale, _ = jax.lax.scan(mx_body, jnp.float32(1e-12), idxs)
            tail = flat[nb_full * blk:] if tail_n else None
            if tail_n:
                scale = jnp.maximum(scale, jnp.max(jnp.abs(tail)))

            def body(_, i):
                key = jax.random.fold_in(rng, i)
                c = chunk(i)
                if pallas:
                    # fused compare/digit/pack: per-chunk raw bits are
                    # the only full-chunk temp (u32, reused across scan
                    # iterations) — the uniform f32 / keep / digit
                    # tensors never exist
                    bits = jax.random.bits(key, (blk,), jnp.uint32)
                    return 0, tern_pack(c, bits, scale)
                return 0, pack_digits(self._digits(c, scale, key))

            _, packed = jax.lax.scan(body, 0, idxs)
            parts = [packed.reshape(-1)]
            if tail_n:
                key = jax.random.fold_in(rng, nb_full)
                if pallas:
                    # tail_n ≡ n mod 512 == 0 (see _pallas_ok), so the
                    # tail packs with the same fused kernel and its
                    # bytes continue the global sublane layout exactly
                    bits = jax.random.bits(key, (tail_n,), jnp.uint32)
                    parts.append(tern_pack(tail, bits, scale))
                else:
                    d = self._digits(tail, scale, key)
                    pad = _packed_len(tail_n) * 4 - tail_n
                    parts.append(pack_digits(
                        jnp.pad(d, (0, pad), constant_values=1)))
            packed = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            return {"packed": packed,
                    "scale": scale.astype(jnp.float32)}, state
        scale = jnp.maximum(jnp.max(jnp.abs(g)), 1e-12)
        if pallas:
            bits = jax.random.bits(rng, (n,), jnp.uint32)
            return {"packed": tern_pack(g.reshape(-1), bits, scale),
                    "scale": scale.astype(jnp.float32)}, state
        # draw the Bernoulli randoms in the gradient's NATIVE shape and
        # flatten only the resulting uint8 digits: fusing a 132M-element
        # threefry with a reshape-derived probability tensor crashes the
        # TPU compile helper (observed on v5e; 1-D and native-shape forms
        # compile fine)
        digit = self._digits(g, scale, rng)
        pad = _packed_len(n) * 4 - n
        packed = pack_digits(
            jnp.pad(digit.reshape(-1), (0, pad), constant_values=1))
        return {"packed": packed, "scale": scale.astype(jnp.float32)}, state

    def _unpack(self, packed, n):
        if self._pallas_ok(n):
            # sublane-grouped layout: byte [r, lane] holds digits of
            # elements r*512 + s*128 + lane — the [rows, 4, 128] digit
            # cube flattens back to element order
            digits = (packed.reshape(-1, 128)[:, None, :]
                      // jnp.asarray(_WEIGHTS, jnp.uint8)[None, :, None]) % 4
        else:
            digits = (packed[:, None]
                      // jnp.asarray(_WEIGHTS, jnp.uint8)[None, :]) % 4
        return digits.reshape(-1)[:n].astype(jnp.int8) - 1  # {-1, 0, +1}

    def decode(self, payload, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        if self._pallas_ok(n):
            # fused dequantizing unpack (digits and the ±scale values
            # never exist separately)
            from pytorch_ps_mpi_tpu.ops.tern_pallas import tern_unpack

            g = tern_unpack(payload["packed"], payload["scale"])
            return g.astype(dtype).reshape(shape)
        tern = self._unpack(payload["packed"], n)
        return (tern.astype(dtype) * payload["scale"].astype(dtype)).reshape(shape)

    def decode_sum(self, payloads, shape, dtype):
        # Sum of per-rank scaled ternaries without materializing [world, n]
        # floats — routed through the exact ternary-count aggregation.
        agg, meta = self.aggregate(payloads, shape, dtype)
        return self.agg_decode(agg, meta, shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        # ternary-count contraction: the [world, n] int8 digit matrix
        # meets the [world] scale vector inside one widened-accumulator
        # einsum — the integer payloads never become a float stack
        n = int(np.prod(shape)) if shape else 1
        tern = jax.vmap(lambda p: self._unpack(p, n))(payloads["packed"])
        acc = jnp.einsum("wn,w->n", tern,
                         payloads["scale"].astype(jnp.float32),
                         preferred_element_type=jnp.float32)
        return {"acc": acc}, {"frames": int(tern.shape[0])}

    def agg_decode(self, agg_payload, meta, shape, dtype):
        return agg_payload["acc"].astype(dtype).reshape(shape)

    def agg_init(self, shape, dtype):
        return scalefold_agg_init(shape)

    def agg_fold(self, acc, payload):
        # base-4 unpack (integer ops), then one per-frame scale-folded
        # multiply-add into the f32 accumulator; the native fast path
        # fuses unpack + MA into one C++ pass, large units otherwise run
        # the jitted fused kernel, small ones pure numpy
        packed = payload["packed"].reshape(-1)
        if self._pallas_ok(acc["n"]):
            # sublane-grouped Pallas layout: the native kernel and the
            # jitted fused fold both assume the flat base-4 grouping —
            # layout-aware numpy unpack + multiply-add instead (still
            # exact; only the fast paths decline)
            p = np.ascontiguousarray(packed, np.uint8).reshape(-1, 128)
            digits = (p[:, None, :]
                      // np.asarray(_WEIGHTS, np.uint8)[None, :, None]) % 4
            tern = digits.reshape(-1)[: acc["n"]].astype(np.int8) - 1
            acc["acc"] = acc["acc"] + (tern.astype(np.float32)
                                       * np.float32(payload["scale"]))
            acc["frames"] += 1
            return
        lib = acc.get("lib")
        if lib is not None:
            from pytorch_ps_mpi_tpu.utils import native as _native

            _native.fold_tern(
                lib, acc["acc"], np.ascontiguousarray(packed, np.uint8),
                np.float32(payload["scale"]))
            acc["frames"] += 1
            return
        if acc.get("jit"):
            acc["acc"] = _fused_tern_fold(
                acc["acc"], packed, np.float32(payload["scale"]),
                acc["n"])
        else:
            digits = (packed[:, None] //
                      np.asarray(_WEIGHTS, np.uint8)[None, :]) % 4
            tern = digits.reshape(-1)[: acc["n"]].astype(np.int8) - 1
            np.multiply(tern, np.float32(payload["scale"]), out=acc["tmp"])
            acc["acc"] += acc["tmp"]
        acc["frames"] += 1

    def agg_finalize(self, acc, shape, dtype):
        return dense_agg_finalize(acc, shape, dtype)

    def payload_bits(self, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        return _packed_len(n) * 8 + 32
