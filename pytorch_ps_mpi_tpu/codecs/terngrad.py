"""TernGrad codec: stochastic ternary gradients, 2 bits per element.

Wen et al. 2017 (arXiv:1705.07878): each coordinate becomes
``s·sign(g)·b`` with ``b ~ Bernoulli(|g|/s)`` and ``s = max|g|`` — an
unbiased estimator (``E[decode] = g``), the midpoint of the compression
curve between int8 (4x) and sign (32x). One more point on the research
surface the reference's external ``codings`` hook existed to explore
(SURVEY §2.2).

Wire format: ternary digits {0,1,2} (= value -1,0,+1) packed 4 per byte
base-4, plus a float32 scale — a true 16x wire reduction on float32
gradients, all on-device (no host compressor, SURVEY §2.4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    check_nonfinite_mode,
    guard_nonfinite,
    register_codec,
)

_WEIGHTS = (1, 4, 16, 64)  # base-4 digit weights, 4 ternary digits per byte


def _packed_len(n: int) -> int:
    return (n + 3) // 4


@register_codec("terngrad")
class TernGradCodec(Codec):
    needs_rng = True
    # per-bucket max|g| scale instead of per-tensor under bucketing;
    # unbiasedness is preserved (scale is shared, Bernoulli stays exact)
    bucketable = True

    def __init__(self, nonfinite: str = "propagate"):
        # a NaN/Inf element drives the max|g| scale non-finite AND makes
        # its keep-probability NaN (uniform < NaN is False, so the digit
        # silently collapses to 0) — guard per codecs/base.guard_nonfinite
        self.nonfinite = check_nonfinite_mode(nonfinite)

    def encode(self, grad, state=(), rng=None):
        assert rng is not None, "TernGradCodec needs a PRNG key"
        g = guard_nonfinite(grad.astype(jnp.float32), self.nonfinite,
                            "TernGradCodec")
        n = int(np.prod(g.shape)) if g.shape else 1
        scale = jnp.maximum(jnp.max(jnp.abs(g)), 1e-12)
        # draw the Bernoulli randoms in the gradient's NATIVE shape and
        # flatten only the resulting uint8 digits: fusing a 132M-element
        # threefry with a reshape-derived probability tensor crashes the
        # TPU compile helper (observed on v5e; 1-D and native-shape forms
        # compile fine)
        keep = jax.random.uniform(rng, g.shape) < (jnp.abs(g) / scale)
        # ternary digit: 0 -> -1, 1 -> 0, 2 -> +1
        digit = jnp.where(keep, jnp.where(g >= 0, 2, 0), 1).astype(jnp.uint8)
        flat = digit.reshape(-1)
        pad = _packed_len(n) * 4 - n
        flat = jnp.pad(flat, (0, pad), constant_values=1).reshape(-1, 4)
        weights = jnp.asarray(_WEIGHTS, jnp.uint8)
        packed = (flat * weights).sum(axis=1).astype(jnp.uint8)
        return {"packed": packed, "scale": scale.astype(jnp.float32)}, state

    def _unpack(self, packed, n):
        digits = (packed[:, None] // jnp.asarray(_WEIGHTS, jnp.uint8)[None, :]) % 4
        return digits.reshape(-1)[:n].astype(jnp.int8) - 1  # {-1, 0, +1}

    def decode(self, payload, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        tern = self._unpack(payload["packed"], n)
        return (tern.astype(dtype) * payload["scale"].astype(dtype)).reshape(shape)

    def decode_sum(self, payloads, shape, dtype):
        # Sum of per-rank scaled ternaries without materializing [world, n]
        # floats: unpack to int8, weight each rank by its scale.
        n = int(np.prod(shape)) if shape else 1
        tern = jax.vmap(lambda p: self._unpack(p, n))(payloads["packed"])
        summed = (tern.astype(dtype) * payloads["scale"][:, None].astype(dtype)).sum(0)
        return summed.reshape(shape)

    def payload_bits(self, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        return _packed_len(n) * 8 + 32
