"""Random-k sparsification codec (unbiased: kept entries are rescaled so
``E[decode] = grad``).

Companion to top-k in the reference's codings research surface (SURVEY
§2.2). Needs per-worker randomness: the train step threads a PRNG key
folded with the worker's axis index so ranks sample different coordinates.

Sampling is stratified: the flat gradient is split into k equal buckets
and one uniform index is drawn per bucket — O(k) work and collision-free,
where drawing k of n indices without replacement costs a full O(n log n)
permutation. Kept entries are scaled by their bucket's length, which makes
the estimator exactly unbiased per coordinate (inclusion probability is
1/len(bucket)) and lowers variance vs. plain without-replacement sampling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    register_codec,
    sparse_agg_finalize,
    sparse_agg_fold,
    sparse_agg_init,
)


@register_codec("randomk")
class RandomKCodec(Codec):
    needs_rng = True
    # exact sparse index-merge (see TopKCodec): concat + one scatter-add,
    # never densified; per-worker strata may overlap across ranks and the
    # scatter-add sums collisions exactly as decode_sum does
    supports_aggregate = True

    @property
    def bucketable(self):
        # Only the FRACTION form is bucket-safe: k scales with the unit's
        # size, so keeping fraction·n coordinates of each bucket equals
        # keeping fraction·n of each leaf (stratum boundaries move, the
        # estimator stays exactly unbiased per coordinate, total kept
        # count is unchanged). An ABSOLUTE k is per-UNIT by definition —
        # bucketing would silently shrink the kept set by ~leaves/buckets
        # (an unconfigured compression increase), so that form keeps the
        # per-leaf path.
        return self.fraction > 0

    def __init__(self, k: int = 0, fraction: float = 0.0, unbiased: bool = True):
        if (k <= 0) == (fraction <= 0.0):
            raise ValueError("give exactly one of k>0 or 0<fraction<=1")
        self.k = int(k)
        self.fraction = float(fraction)
        self.unbiased = unbiased

    def _k_for(self, shape) -> int:
        n = int(np.prod(shape)) if shape else 1
        k = self.k if self.k > 0 else max(1, int(round(n * self.fraction)))
        return min(k, n)

    def encode(self, grad, state=(), rng=None):
        assert rng is not None, "RandomKCodec needs a PRNG key"
        flat = grad.reshape(-1)
        n = flat.shape[0]
        k = self._k_for(grad.shape)
        # n and k are static: exact bucket bounds on host (int arithmetic)
        bounds = ((np.arange(k + 1, dtype=np.int64) * n) // k).astype(np.int32)
        starts = jnp.asarray(bounds[:-1])
        lens = jnp.asarray(bounds[1:] - bounds[:-1])
        u = jax.random.uniform(rng, (k,))
        indices = starts + jnp.floor(u * lens).astype(jnp.int32)
        values = jnp.take(flat, indices)
        if self.unbiased:
            values = values * lens.astype(flat.dtype)
        return {"values": values, "indices": indices}, state

    def decode(self, payload, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        flat = jnp.zeros((n,), dtype)
        flat = flat.at[payload["indices"]].set(payload["values"].astype(dtype))
        return flat.reshape(shape)

    def decode_sum(self, payloads, shape, dtype):
        agg, meta = self.aggregate(payloads, shape, dtype)
        return self.agg_decode(agg, meta, shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        idx = payloads["indices"]
        return {
            "values": payloads["values"].reshape(-1),
            "indices": idx.reshape(-1),
        }, {"frames": int(idx.shape[0])}

    def agg_decode(self, agg_payload, meta, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        flat = jnp.zeros((n,), dtype)
        val = agg_payload["values"].astype(dtype)
        return flat.at[agg_payload["indices"]].add(val).reshape(shape)

    # streaming form: shared sparse concat accumulator (O(k) per fold)
    def agg_init(self, shape, dtype):
        return sparse_agg_init(shape)

    def agg_fold(self, acc, payload):
        sparse_agg_fold(acc, payload["values"], payload["indices"])

    def agg_finalize(self, acc, shape, dtype):
        return sparse_agg_finalize(acc, shape, dtype)

    def payload_bits(self, shape, dtype):
        k = self._k_for(shape)
        return k * (jnp.dtype(dtype).itemsize * 8 + 32)
