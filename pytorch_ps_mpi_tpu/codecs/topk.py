"""Top-k sparsification codec.

The BASELINE "top-k gradient compression" slot (BASELINE.json config #4;
the reference reached it through the external ``codings`` hook, SURVEY
§2.2). Keeps the k largest-magnitude entries of the flattened gradient.

Static shapes: k is fixed at trace time, so the payload (values[k],
indices[k]) is dense and needs NO size exchange — the compile-time analog
of the reference's two-phase ``prepare``/``Iallgatherv`` ragged protocol
(``mpi_comms.py:144-174``). For the genuinely variable-length payload
class (data-dependent survivor counts + a load-bearing length sidecar),
see :class:`~pytorch_ps_mpi_tpu.codecs.threshold.ThresholdCodec`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    register_codec,
    sparse_agg_finalize,
    sparse_agg_fold,
    sparse_agg_init,
)


@register_codec("topk")
class TopKCodec(Codec):
    # exact sparse index-merge algebra (SparCML): aggregation is concat
    # of (values, indices) pairs — never densified — and ONE scatter-add
    # decodes the sum; the streaming accumulator is the concat list
    # itself, so server-side per-push cost is O(k), not O(n)
    supports_aggregate = True

    def __init__(self, k: int = 0, fraction: float = 0.0, approx: bool = False,
                 pallas: bool = False):
        """``approx=True`` selects ``lax.approx_max_k`` — the TPU's
        hardware-accelerated approximate top-k (recall ~0.95) — instead of
        the exact sort-based ``lax.top_k``, which is far cheaper on
        multi-million-element gradients. Sparsification is already lossy,
        so approximate selection costs little accuracy.

        ``pallas=True`` keeps selection EXACT but replaces the full-sort
        ``lax.top_k`` with the per-block threshold-refine kernel
        (``ops/topk_pallas.exact_topk``: Pallas count passes find the
        exact k-th |g|, chunked compaction extracts the survivors) —
        same value multiset, ties broken in index order instead of sort
        order. Small tensors fall back to ``lax.top_k`` internally."""
        if (k <= 0) == (fraction <= 0.0):
            raise ValueError("give exactly one of k>0 or 0<fraction<=1")
        if approx and pallas:
            raise ValueError("approx and pallas are alternative selection "
                             "strategies; pick one")
        self.k = int(k)
        self.fraction = float(fraction)
        self.approx = bool(approx)
        self.pallas = bool(pallas)

    def _k_for(self, shape) -> int:
        n = int(np.prod(shape)) if shape else 1
        k = self.k if self.k > 0 else max(1, int(round(n * self.fraction)))
        return min(k, n)

    def encode(self, grad, state=(), rng=None):
        flat = grad.reshape(-1)
        k = self._k_for(grad.shape)
        if self.pallas:
            from pytorch_ps_mpi_tpu.ops.topk_pallas import exact_topk

            values, indices = exact_topk(flat, k)
            return {"values": values, "indices": indices}, state
        if self.approx:
            _, indices = jax.lax.approx_max_k(jnp.abs(flat), k)
        else:
            _, indices = jax.lax.top_k(jnp.abs(flat), k)
        payload = {
            "values": jnp.take(flat, indices),
            "indices": indices.astype(jnp.int32),
        }
        return payload, state

    def decode(self, payload, shape, dtype):
        # mode='drop': a no-op for this codec's always-in-range indices,
        # load-bearing for BlockTopKCodec's >= n pad-slot indices (the
        # default would CLAMP them onto element n-1 and corrupt it)
        n = int(np.prod(shape)) if shape else 1
        flat = jnp.zeros((n,), dtype)
        flat = flat.at[payload["indices"]].set(
            payload["values"].astype(dtype), mode="drop"
        )
        return flat.reshape(shape)

    def decode_sum(self, payloads, shape, dtype):
        # Fused scatter-add across all ranks' payloads: one segment-sum
        # instead of the reference's per-rank decode loop (ps.py:161-176).
        agg, meta = self.aggregate(payloads, shape, dtype)
        return self.agg_decode(agg, meta, shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        # SparCML index-merge: the aggregated payload is the ranks'
        # (values, indices) pairs concatenated in rank order — the
        # reshape(-1) of the stacked batch — sized world×k, never n
        idx = payloads["indices"]
        return {
            "values": payloads["values"].reshape(-1),
            "indices": idx.reshape(-1),
        }, {"frames": int(idx.shape[0])}

    def agg_decode(self, agg_payload, meta, shape, dtype):
        # mode='drop' as in decode: load-bearing for BlockTopKCodec's
        # >= n pad-slot indices
        n = int(np.prod(shape)) if shape else 1
        flat = jnp.zeros((n,), dtype)
        val = agg_payload["values"].astype(dtype)
        return flat.at[agg_payload["indices"]].add(
            val, mode="drop").reshape(shape)

    # streaming form: the concat list IS the accumulator (O(k) per fold,
    # one numpy scatter-add at finalize) — shared sparse helpers
    def agg_init(self, shape, dtype):
        return sparse_agg_init(shape)

    def agg_fold(self, acc, payload):
        sparse_agg_fold(acc, payload["values"], payload["indices"])

    def agg_finalize(self, acc, shape, dtype):
        return sparse_agg_finalize(acc, shape, dtype)

    def payload_bits(self, shape, dtype):
        k = self._k_for(shape)
        return k * (jnp.dtype(dtype).itemsize * 8 + 32)
