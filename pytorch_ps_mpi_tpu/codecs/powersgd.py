"""PowerSGD: low-rank gradient compression (Vogels et al. 2019,
arXiv:1905.13727 — see PAPERS.md).

Each ≥2-D gradient, viewed as a matrix M [n, m], is approximated as
P @ Qᵀ with rank r ≪ min(n, m): one power-iteration step against the
warm-started Q from the previous round, orthonormalized via QR. Error
feedback is built in (the residual is carried in codec state and added
back next round), as the algorithm requires for convergence.
Vectors/scalars (ndim < 2) ride uncompressed.

TWO protocols live here, matching the paper's own split:

- **All-reducible (the headline, paper §2/Alg. 1)** — the fused
  in-collective form ``fused_allreduce`` used by ``MPI_PS``'s on-mesh
  step: every worker shares ONE warm Q, so ``P = psum(M_w @ Q)`` →
  QR → ``Q = psum(M_wᵀ @ P̂)`` yields the rank-r approximation of the
  *summed* gradient in two rank-sized psums. Wire cost per worker is
  ``~2·(W-1)/W·r·(n+m)`` — **independent of world size** — where the
  gather form ships ``(W-1)·r·(n+m)``. Per-worker error feedback keeps
  exactly what the protocol transmitted on this worker's behalf:
  ``e_w ← M_w − P̂ P̂ᵀ M_w`` (VERDICT r4 weak #3).
- **Per-worker factors (``encode``/``decode_sum``)** — each worker ships
  its own ``(P_w, Q_w)`` and the receiver sums W separate rank-r
  approximations. This is NOT the paper's all-reduced algorithm, but it
  needs no collective inside the codec, which is exactly what the
  async/DCN wires require (host PS, shm/TCP fleets): there IS no
  synchronous collective to ride, payloads arrive one worker at a time.

MXU note: encode/decode are three tall-skinny matmuls per tensor —
exactly the shape XLA tiles onto the systolic array; the QR is r×r-sized
and negligible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pytorch_ps_mpi_tpu.codecs.base import Codec, register_codec


def _matrix_shape(shape):
    """Matrix view [n, m] of a tensor: first dim x rest — SKIPPING
    leading singleton dims. The model-parallel shard convention carries
    a leading [1] local-shard axis ([1, d, f/tp] TP leaves); without the
    skip that axis becomes n=1, the rank clips to 1, r*(n+m) >= n*m,
    and PowerSGD silently refuses to compress every TP leaf."""
    i = 0
    while i < len(shape) - 1 and shape[i] == 1:
        i += 1
    n = shape[i]
    m = int(np.prod(shape[i + 1:]))
    return n, m


@register_codec("powersgd")
class PowerSGDCodec(Codec):
    supports_fused_allreduce = True
    # exact factor-domain aggregation: W rank-r payloads concatenate into
    # ONE rank-W·r factor pair ([n, Wr] and [m, Wr]) whose single
    # reconstruct equals Σ_w P_w Q_wᵀ — the factors are summed/stacked in
    # the compressed domain and the O(n·m) reconstruct happens once per
    # round instead of once per worker (the all-reduced shared-Q protocol
    # remains the true factor-SUM form, fused_allreduce)
    supports_aggregate = True

    def __init__(self, rank: int = 2, min_compression_elems: int = 1024):
        """``rank``: approximation rank r. Tensors with fewer than
        ``min_compression_elems`` elements (or ndim < 2) are sent raw —
        compressing tiny biases costs more wire than it saves."""
        self.rank = int(rank)
        self.min_elems = int(min_compression_elems)

    def _compresses(self, shape) -> bool:
        if len(shape) < 2:
            return False
        n, m = _matrix_shape(shape)
        r = min(self.rank, n, m)
        return n * m >= self.min_elems and r * (n + m) < n * m

    def init_state(self, shape, dtype):
        if not self._compresses(shape):
            return ()
        n, m = _matrix_shape(shape)
        r = min(self.rank, n, m)
        # deterministic warm-start Q, identical on every worker
        key = jax.random.key(np.int64(hash((n, m, r))) % (2 ** 31))
        q = jax.random.normal(key, (m, r), dtype)
        return {"Q": q, "memory": jnp.zeros(shape, dtype)}

    def encode(self, grad, state=(), rng=None):
        if not self._compresses(grad.shape):
            return {"raw": grad}, state
        n, m = _matrix_shape(grad.shape)
        corrected = grad + state["memory"]
        M = corrected.reshape(n, m)
        P = M @ state["Q"]                       # [n, r] power iteration
        P, _ = jnp.linalg.qr(P)                  # orthonormalize columns
        Q = M.T @ P                              # [m, r]
        decoded = (P @ Q.T).reshape(grad.shape)
        new_state = {"Q": Q, "memory": corrected - decoded}
        return {"P": P, "Q": Q}, new_state

    def fused_allreduce(self, grad, state, axis_name, comm_dtype=None):
        """Vogels et al.'s all-reduced protocol (module docstring):
        returns ``(summed_decoded, new_state)`` — the rank-r
        approximation of the cross-worker gradient SUM, via two
        rank-sized psums over ``axis_name``. Runs inside shard_map.

        ``comm_dtype`` narrows the UNCOMPRESSED leaves' psum wire (the
        always-on bf16 doctrine); the low-rank factors keep their own
        dtype — they feed a QR whose orthonormality the error-feedback
        analysis leans on, and at r(n+m) elements they are already the
        cheap part of the wire."""
        if not self._compresses(grad.shape):
            if comm_dtype is not None:
                return lax.psum(
                    grad.astype(comm_dtype), axis_name
                ).astype(grad.dtype), state
            return lax.psum(grad, axis_name), state
        n, m = _matrix_shape(grad.shape)
        corrected = grad + state["memory"]
        M = corrected.reshape(n, m)
        # psum #1: P = M @ Q summed across workers (Q is shared/warm,
        # identical everywhere, so this IS (Σ M_w) @ Q)
        P = lax.psum(M @ state["Q"], axis_name)
        P, _ = jnp.linalg.qr(P)          # deterministic: same P̂ everywhere
        Qw = M.T @ P                     # this worker's factor
        # psum #2: Q = (Σ M_w)ᵀ @ P̂
        Q = lax.psum(Qw, axis_name)
        summed = (P @ Q.T).reshape(grad.shape)
        # error feedback keeps what was NOT transmitted on this worker's
        # behalf: its share of the decode is P̂ Q_wᵀ = P̂ P̂ᵀ M_w, and
        # Σ_w P̂ Q_wᵀ == the summed decode, so the global residual is
        # exactly the sum of these local memories
        new_state = {"Q": Q, "memory": corrected - (P @ Qw.T).reshape(grad.shape)}
        return summed, new_state

    def fused_wire_bits(self, shape, dtype, comm_dtype=None) -> int:
        """Per-worker wire bits of one two-psum round (both rank-sized
        ring reductions; world-size-independent). Uncompressed leaves
        ride a plain psum at ``comm_dtype`` when set."""
        bits = jnp.dtype(dtype).itemsize * 8
        if not self._compresses(shape):
            n = int(np.prod(shape)) if shape else 1
            wire_bits = (jnp.dtype(comm_dtype).itemsize * 8
                         if comm_dtype is not None else bits)
            return n * wire_bits  # rides a plain psum
        n, m = _matrix_shape(shape)
        r = min(self.rank, n, m)
        return r * (n + m) * bits

    def decode(self, payload, shape, dtype):
        if "raw" in payload:
            return payload["raw"].astype(dtype)
        return (payload["P"] @ payload["Q"].T).reshape(shape).astype(dtype)

    def decode_sum(self, payloads, shape, dtype):
        # Σ_w P_w Q_wᵀ through the factor-concat aggregation (one
        # [n, Wr] @ [Wr, m] contraction — same reduction the old
        # "wnr,wmr->nm" einsum performed, single source of truth now)
        agg, meta = self.aggregate(payloads, shape, dtype)
        return self.agg_decode(agg, meta, shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        if "raw" in payloads:
            return ({"raw": payloads["raw"].sum(axis=0)},
                    {"frames": int(payloads["raw"].shape[0])})
        w, n, r = payloads["P"].shape
        m = payloads["Q"].shape[1]
        # [w, n, r] -> [n, w*r]: stack the per-worker factors side by
        # side; the concatenated pair IS the aggregated payload (rank
        # W·r), sized by the factors, never by the decoded matrix
        p_cat = jnp.transpose(payloads["P"], (1, 0, 2)).reshape(n, w * r)
        q_cat = jnp.transpose(payloads["Q"], (1, 0, 2)).reshape(m, w * r)
        return {"P": p_cat, "Q": q_cat}, {"frames": int(w)}

    def agg_decode(self, agg_payload, meta, shape, dtype):
        if "raw" in agg_payload:
            return agg_payload["raw"].astype(dtype)
        out = agg_payload["P"] @ agg_payload["Q"].T
        return out.reshape(shape).astype(dtype)

    def payload_bits(self, shape, dtype):
        bits = jnp.dtype(dtype).itemsize * 8
        if not self._compresses(shape):
            n = int(np.prod(shape)) if shape else 1
            return n * bits
        n, m = _matrix_shape(shape)
        r = min(self.rank, n, m)
        return r * (n + m) * bits
