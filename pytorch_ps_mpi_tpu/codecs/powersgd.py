"""PowerSGD: low-rank gradient compression (Vogels et al. 2019,
arXiv:1905.13727 — see PAPERS.md).

Each ≥2-D gradient, viewed as a matrix M [n, m], is approximated as
P @ Qᵀ with rank r ≪ min(n, m): one power-iteration step against the
warm-started Q from the previous round, orthonormalized via QR. The wire
carries (P [n,r], Q [m,r]) — r·(n+m) numbers instead of n·m. Error
feedback is built in (the residual M − PQᵀ is carried in codec state and
added back next round), as the algorithm requires for convergence.
Vectors/scalars (ndim < 2) ride uncompressed.

MXU note: encode/decode are three tall-skinny matmuls per tensor —
exactly the shape XLA tiles onto the systolic array; the QR is r×r-sized
and negligible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import Codec, register_codec


def _matrix_shape(shape):
    n = shape[0]
    m = int(np.prod(shape[1:]))
    return n, m


@register_codec("powersgd")
class PowerSGDCodec(Codec):
    def __init__(self, rank: int = 2, min_compression_elems: int = 1024):
        """``rank``: approximation rank r. Tensors with fewer than
        ``min_compression_elems`` elements (or ndim < 2) are sent raw —
        compressing tiny biases costs more wire than it saves."""
        self.rank = int(rank)
        self.min_elems = int(min_compression_elems)

    def _compresses(self, shape) -> bool:
        if len(shape) < 2:
            return False
        n, m = _matrix_shape(shape)
        r = min(self.rank, n, m)
        return n * m >= self.min_elems and r * (n + m) < n * m

    def init_state(self, shape, dtype):
        if not self._compresses(shape):
            return ()
        n, m = _matrix_shape(shape)
        r = min(self.rank, n, m)
        # deterministic warm-start Q, identical on every worker
        key = jax.random.key(np.int64(hash((n, m, r))) % (2 ** 31))
        q = jax.random.normal(key, (m, r), dtype)
        return {"Q": q, "memory": jnp.zeros(shape, dtype)}

    def encode(self, grad, state=(), rng=None):
        if not self._compresses(grad.shape):
            return {"raw": grad}, state
        n, m = _matrix_shape(grad.shape)
        corrected = grad + state["memory"]
        M = corrected.reshape(n, m)
        P = M @ state["Q"]                       # [n, r] power iteration
        P, _ = jnp.linalg.qr(P)                  # orthonormalize columns
        Q = M.T @ P                              # [m, r]
        decoded = (P @ Q.T).reshape(grad.shape)
        new_state = {"Q": Q, "memory": corrected - decoded}
        return {"P": P, "Q": Q}, new_state

    def decode(self, payload, shape, dtype):
        if "raw" in payload:
            return payload["raw"].astype(dtype)
        return (payload["P"] @ payload["Q"].T).reshape(shape).astype(dtype)

    def decode_sum(self, payloads, shape, dtype):
        if "raw" in payloads:
            return payloads["raw"].sum(axis=0).astype(dtype)
        # Σ_w P_w Q_wᵀ in one batched contraction
        out = jnp.einsum("wnr,wmr->nm", payloads["P"], payloads["Q"])
        return out.reshape(shape).astype(dtype)

    def payload_bits(self, shape, dtype):
        bits = jnp.dtype(dtype).itemsize * 8
        if not self._compresses(shape):
            n = int(np.prod(shape)) if shape else 1
            return n * bits
        n, m = _matrix_shape(shape)
        r = min(self.rank, n, m)
        return r * (n + m) * bits
