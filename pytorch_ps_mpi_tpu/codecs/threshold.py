"""Threshold sparsification — the genuinely RAGGED codec.

Keeps every entry with ``|g| > tau * mean|g|`` (Strom-2015-style relative
threshold). Unlike top-k, the number of surviving entries is
**data-dependent**: it varies per worker, per parameter, and per step. This
is the payload class the reference's whole two-phase variable-length
protocol existed for (``mpi_comms.py:144-174``: exchange byte counts
first, then ``Iallgatherv`` the ragged payloads), and its TPU-native wire
convention is the one the reference's ``max_bytes`` high-water padding
approximated (``mpi_comms.py:82-85``):

- the payload buffer has a **static cap** (``max_fraction`` of the tensor),
  so it can ride ``lax.all_gather`` under jit;
- the slots past each worker's true count hold *garbage* (whatever
  ``flat[0]`` gather produced) — they are NOT zeroed on the send side;
- an int32 ``length`` sidecar rides along, and the **receive side masks**
  ``arange(cap) < length`` before the scatter-add. Consumers that ignore
  the sidecar get corrupt sums — the sidecar is load-bearing, exactly like
  the reference's count exchange (and unlike its 32-byte ``0x29`` sentinel,
  which could collide with payload bytes, SURVEY §2.3).

Overflow (more survivors than the cap) drops the tail entries in index
order — the high-water buffer is the contract, as in the reference. Wrap
in :class:`~pytorch_ps_mpi_tpu.codecs.error_feedback.ErrorFeedback`
(``get_codec('ef', inner_name='threshold', ...)``) to accumulate both
sub-threshold and overflow residuals into later steps.

With ``target_fraction`` set, ``tau`` becomes adaptive codec state: a
multiplicative controller nudges it so the mean kept fraction tracks the
target (kept > target → raise the bar, and vice versa).

Performance note (measured on TPU v5 lite, ``benchmarks/codec_bench.py``):
the ``nonzero(size=cap)`` compaction lowers to an n-sized scatter, which
TPUs execute serially — 67-72 ms at 8M elems, 1.6 s at 132M, orders
slower than the dense codecs (sign/int8 at ~1 ms or below at 8M). The
default TPU path therefore compacts with one ``lax.sort`` instead
(``compaction='sort'``: bitonic, vectorized; see ``__init__``), keeping
the scatter path for CPUs where it wins. Even so, for on-chip
compression where raggedness is NOT the point, prefer ``topk-approx``
or ``sign``/``terngrad``; use this codec where the ragged protocol
itself is (DCN wires with real byte budgets).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    register_codec,
    sparse_agg_finalize,
    sparse_agg_fold,
    sparse_agg_init,
)


@register_codec("threshold")
class ThresholdCodec(Codec):
    # exact sparse index-merge, with each rank's garbage tail masked by
    # ITS OWN length sidecar before the concat — the ragged protocol's
    # receive half applied in the compressed domain
    supports_aggregate = True

    def __init__(
        self,
        tau: float = 2.0,
        max_fraction: float = 0.25,
        target_fraction: float = 0.0,
        eta: float = 0.25,
        compaction: str | None = None,
        chunk: int = 1 << 16,
    ):
        """Args:
          tau: initial threshold in units of the gradient's mean |g|.
          max_fraction: static payload cap as a fraction of the tensor —
            the compile-time high-water mark (reference ``max_bytes``).
          target_fraction: if >0, adapt tau so the kept fraction tracks
            this value (tau becomes codec state).
          eta: controller gain for the tau adaptation.
          compaction: ``'sort'`` compacts survivor indices with a
            sort — a bitonic network the TPU runs vectorized;
            ``'scatter'`` uses ``jnp.nonzero(size=cap)``, which lowers to
            an n-sized scatter TPUs execute serially but CPUs run cheaply
            (measured: scatter 3.4× faster than sort on the host CPU at
            1M elems, while on TPU the n-scatter is the 72 ms outlier of
            the codec table). Default ``None`` picks by the ambient
            backend: sort on TPU, scatter elsewhere. Both produce
            identical decoded gradients; only the garbage tail beyond
            ``length`` differs (and decode masks it either way).
          chunk: sort-path tensors with at least ``4 * chunk`` elements
            compact CHUNKED: one vectorized per-chunk sort over
            ``[n_chunks, chunk]`` (a bitonic network of depth log²(chunk)
            instead of log²(n) — the fix for the superlinear 619 ms
            BERT-flat-grad encode, BENCH_TPU_WATCH) followed by a
            sequential cursor merge of the per-chunk survivor prefixes
            (``dynamic_update_slice`` per chunk; each write is a full
            static-size chunk and the next chunk's write overlap-
            overwrites the garbage tail, so the merged prefix is exactly
            the global survivors in index order). Identical decoded
            payloads to the unchunked sort — only the garbage tail past
            ``length`` differs. 0 disables chunking.
        """
        if not 0.0 < max_fraction <= 1.0:
            raise ValueError(f"max_fraction must be in (0, 1], got {max_fraction}")
        if target_fraction and target_fraction > max_fraction:
            raise ValueError("target_fraction must be <= max_fraction")
        if compaction is None:
            compaction = "sort" if jax.default_backend() == "tpu" else "scatter"
        if compaction not in ("sort", "scatter"):
            raise ValueError(f"compaction must be 'sort' or 'scatter', "
                             f"got {compaction!r}")
        if chunk and (chunk < 1024 or chunk & (chunk - 1)):
            raise ValueError(f"chunk must be 0 or a power of two >= 1024, "
                             f"got {chunk}")
        self.tau = float(tau)
        self.max_fraction = float(max_fraction)
        self.target_fraction = float(target_fraction)
        self.eta = float(eta)
        self.compaction = compaction
        self.chunk = int(chunk)

    def _cap(self, shape) -> int:
        n = int(np.prod(shape)) if shape else 1
        return max(1, int(round(n * self.max_fraction)))

    def init_state(self, shape, dtype):
        return {"tau": jnp.float32(self.tau)}

    def encode(self, grad, state=None, rng=None):
        state = state if state else {"tau": jnp.float32(self.tau)}
        flat = grad.reshape(-1)
        n = flat.shape[0]
        cap = self._cap(grad.shape)
        tau = state["tau"]
        thr = tau * jnp.mean(jnp.abs(flat))
        mask = jnp.abs(flat) > thr
        kept = jnp.sum(mask)  # true survivor count — data-dependent
        # static-size compaction: indices of the first `cap` survivors in
        # index order; slots past min(kept, cap) hold garbage by design
        # (see module doc) — decode masks them by `length` either way.
        if (self.compaction == "sort" and self.chunk
                and n >= 4 * self.chunk):
            idx = self._chunked_compact(mask, n, cap)
        elif self.compaction == "sort" and 2 * n < 2**31:
            # survivors keep their index as the sort key, non-survivors
            # get index+n: one ascending sort puts survivor indices
            # first IN INDEX ORDER. The sort is bitonic — vectorized on
            # TPU, unlike nonzero's serial n-sized scatter. The 2n < 2^31
            # guard keeps the biased keys inside int32 (beyond it, pos+n
            # would wrap negative and sort garbage BEFORE survivors —
            # silently wrong decode); such tensors take the scatter path
            # (large tensors normally hit the chunked branch above,
            # whose local keys never approach the int32 bound).
            pos = jnp.arange(n, dtype=jnp.int32)
            keys = jnp.where(mask, pos, pos + n)
            idx = jax.lax.sort(keys)[:cap]
            idx = jnp.where(idx >= n, idx - n, idx)  # unbias garbage tail
        else:
            (idx,) = jnp.nonzero(mask, size=cap, fill_value=0)
        payload = {
            "values": jnp.take(flat, idx),
            "indices": idx.astype(jnp.int32),
            "length": jnp.minimum(kept, cap).astype(jnp.int32),
        }
        if self.target_fraction > 0.0:
            target = self.target_fraction * n
            ratio = kept.astype(jnp.float32) / target
            new_tau = jnp.clip(tau * ratio**self.eta, 1e-4, 1e4)
        else:
            new_tau = tau
        return payload, {"tau": new_tau}

    def _chunked_compact(self, mask, n: int, cap: int):
        """Chunked data-dependent compaction: the first ``cap`` survivor
        indices of ``mask`` in GLOBAL index order, without an n-sized
        sort. Per-chunk biased-key sorts run as ONE vectorized
        ``lax.sort`` over ``[n_chunks, chunk]`` (bitonic depth
        log²(chunk), not log²(n)); a sequential ``fori_loop`` then
        merges each chunk's survivor prefix at a running cursor with a
        full-chunk ``dynamic_update_slice`` — the next chunk's write
        lands AT its predecessor's survivor count, overwriting the
        garbage tail, so out[:kept_total] is exactly the concatenation
        of survivor prefixes = the global survivors in index order.
        Bit-identical payload semantics to the unchunked sort path for
        every slot decode ever reads (the masked ``length`` prefix)."""
        C = self.chunk
        nc = -(-n // C)
        pad = nc * C - n
        m2 = (jnp.concatenate([mask, jnp.zeros((pad,), mask.dtype)])
              if pad else mask).reshape(nc, C)
        pos = jnp.arange(C, dtype=jnp.int32)[None, :]
        keys = jnp.where(m2, pos, pos + C)  # local keys: always < 2^31
        skeys = jax.lax.sort(keys, dimension=-1)
        counts = m2.sum(axis=1, dtype=jnp.int32)  # survivors per chunk
        take = min(C, cap)  # a chunk's rank >= cap entries can never
        # land inside the global first-cap prefix, so a static
        # take-per-chunk write loses nothing
        out0 = jnp.zeros((cap + take,), jnp.int32)

        def body(c, state):
            out, cursor = state
            glob = skeys[c, :take]
            glob = jnp.where(glob >= C, glob - C, glob) + c * C
            # clamp only the WRITE position: past cap the write lands in
            # the slack region (sliced off below); the cursor itself
            # keeps the true running survivor count
            out = jax.lax.dynamic_update_slice(
                out, glob, (jnp.minimum(cursor, cap),))
            return out, cursor + counts[c]

        out, _ = jax.lax.fori_loop(0, nc, body, (out0, jnp.int32(0)))
        return out[:cap]

    def _masked_values(self, payload, dtype):
        cap = payload["values"].shape[-1]
        valid = jnp.arange(cap) < payload["length"][..., None]
        return jnp.where(valid, payload["values"], 0).astype(dtype)

    def decode(self, payload, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        vals = self._masked_values(payload, dtype)
        flat = jnp.zeros((n,), dtype)
        return flat.at[payload["indices"]].add(vals).reshape(shape)

    def decode_sum(self, payloads, shape, dtype):
        # Masked fused scatter-add over all workers: each worker's garbage
        # tail is zeroed by ITS OWN length before the sum — the receive
        # half of the ragged protocol.
        agg, meta = self.aggregate(payloads, shape, dtype)
        return self.agg_decode(agg, meta, shape, dtype)

    def aggregate(self, payloads, shape, dtype):
        idx = payloads["indices"]
        return {
            "values": self._masked_values(payloads, dtype).reshape(-1),
            "indices": idx.reshape(-1),
        }, {"frames": int(idx.shape[0])}

    def agg_decode(self, agg_payload, meta, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        return jnp.zeros((n,), dtype).at[agg_payload["indices"]].add(
            agg_payload["values"].astype(dtype)).reshape(shape)

    # streaming form: each frame contributes only its length-prefix
    # (survivors live at the front in index order; the tail is garbage
    # by the wire contract) — O(length) per fold
    def agg_init(self, shape, dtype):
        return sparse_agg_init(shape)

    def agg_fold(self, acc, payload):
        k = int(payload["length"])
        sparse_agg_fold(acc, np.asarray(payload["values"]).reshape(-1)[:k],
                        np.asarray(payload["indices"]).reshape(-1)[:k])

    def agg_finalize(self, acc, shape, dtype):
        return sparse_agg_finalize(acc, shape, dtype)

    def payload_bits(self, shape, dtype):
        # static wire size (the cap); true occupancy varies per step
        cap = self._cap(shape)
        return cap * (jnp.dtype(dtype).itemsize * 8 + 32) + 32
