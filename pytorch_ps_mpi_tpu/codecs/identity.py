"""Identity codec: no compression, gradients ride the wire as-is.

The default when the reference is constructed without a ``code`` (its
``codings`` default was an identity-style passthrough). Signals
``supports_psum`` so the train step can lower aggregation to a single
fused ``lax.psum`` instead of all_gather + decode + sum.
"""

from __future__ import annotations

import jax

from pytorch_ps_mpi_tpu.codecs.base import Codec, register_codec


@register_codec("identity")
class IdentityCodec(Codec):
    supports_psum = True
    bucketable = True  # trivially shape-agnostic and stateless

    def encode(self, grad, state=(), rng=None):
        return grad, state

    def decode(self, payload, shape, dtype):
        return payload.astype(dtype).reshape(shape)

    def decode_sum(self, payloads, shape, dtype):
        return payloads.sum(axis=0).astype(dtype).reshape(shape)
