"""Identity codec: no compression, gradients ride the wire as-is.

The default when the reference is constructed without a ``code`` (its
``codings`` default was an identity-style passthrough). Signals
``supports_psum`` so the train step can lower aggregation to a single
fused ``lax.psum`` instead of all_gather + decode + sum.
"""

from __future__ import annotations

import jax

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    dense_agg_finalize,
    dense_agg_init,
    register_codec,
)


@register_codec("identity")
class IdentityCodec(Codec):
    supports_psum = True
    bucketable = True  # trivially shape-agnostic and stateless
    # aggregation IS the sum — trivially exact; the streaming form keeps
    # one running f32 accumulator per unit (no per-push tree rebuild)
    supports_aggregate = True

    def encode(self, grad, state=(), rng=None):
        return grad, state

    def decode(self, payload, shape, dtype):
        return payload.astype(dtype).reshape(shape)

    def decode_sum(self, payloads, shape, dtype):
        return payloads.sum(axis=0).astype(dtype).reshape(shape)

    def aggregate(self, payloads, shape, dtype):
        return (payloads.sum(axis=0),
                {"frames": int(payloads.shape[0])})

    def agg_decode(self, agg_payload, meta, shape, dtype):
        return agg_payload.astype(dtype).reshape(shape)

    def agg_init(self, shape, dtype):
        from pytorch_ps_mpi_tpu.utils import native as _native

        acc = dense_agg_init(shape)
        # bind once per round, not per push (fold_lib reads the env var
        # and probes symbols — hot-path money)
        acc["lib"] = _native.fold_lib()
        return acc

    def agg_fold(self, acc, payload):
        import numpy as np

        from pytorch_ps_mpi_tpu.utils import native as _native

        x = np.asarray(payload).reshape(-1)
        lib = acc.get("lib") if x.dtype == np.float32 else None
        if lib is not None and x.flags.c_contiguous:
            _native.fold_dense_f32(lib, acc["acc"], x)
        else:
            acc["acc"] += x
        acc["frames"] += 1

    def agg_finalize(self, acc, shape, dtype):
        return dense_agg_finalize(acc, shape, dtype)
