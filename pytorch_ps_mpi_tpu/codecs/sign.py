"""SignSGD codec: 1 bit per element + a mean-|g| scale.

The most aggressive point on the compression curve the reference's codings
hook was built to explore (SURVEY §2.2). Payload packs 8 signs per byte —
a true 32× wire reduction, not just a narrower dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    check_nonfinite_mode,
    guard_nonfinite,
    register_codec,
)


def _packed_len(n: int) -> int:
    return (n + 7) // 8


@register_codec("sign")
class SignCodec(Codec):
    """``use_pallas=True`` routes sizes divisible by 1024 through the
    fused VMEM pack/unpack kernels (``ops/sign_pallas.py``). NOTE: the
    Pallas bit layout groups by sublane (bit s of packed byte [r, lane]
    holds element r*1024 + s*128 + lane) while the jnp path groups 8
    consecutive elements per byte — payloads are only self-consistent
    within one codec configuration, which is all the aggregation pipeline
    needs (every worker runs the same codec)."""

    # shape-agnostic + stateless: under flat-bucket aggregation the scale
    # becomes a per-BUCKET mean|g| instead of per-tensor (same estimator
    # family, coarser normalization group — documented semantics change)
    bucketable = True
    # APPROXIMATE vote-count algebra: per-element votes accumulate in a
    # widened integer counter (pure integer domain, no decode per push)
    # and the decode applies the MEAN of the per-frame scales — exact
    # when all frames share a scale, otherwise sign-vote ≈ sum-of-signs
    # with a measured rel-error (fidelity_bench --aggregate). agg_exact
    # is False, so the SPMD training path (ps.decode_sum_payloads) never
    # substitutes it for the exact decode_sum; only the host wire ships
    # it, behind the fidelity contract.
    supports_aggregate = True
    agg_exact = False

    def __init__(self, use_pallas: bool = True, nonfinite: str = "propagate"):
        self.use_pallas = use_pallas
        # non-finite input guard: a single NaN makes the mean|g| scale
        # NaN, which decodes EVERY element to NaN — "zero" sanitizes,
        # "raise" fails fast on eager encodes (codecs/base.guard_nonfinite)
        self.nonfinite = check_nonfinite_mode(nonfinite)

    def _pallas_ok(self, n: int) -> bool:
        return self.use_pallas and n > 0 and n % 1024 == 0

    def encode(self, grad, state=(), rng=None):
        flat = guard_nonfinite(grad.reshape(-1), self.nonfinite, "SignCodec")
        n = flat.shape[0]
        if self._pallas_ok(n):
            # fused encode: packed bits + the |g| sum for the scale in
            # ONE pass over the gradient (ops/sign_pallas.encode_signs)
            # — half the memory traffic of scale-reduce-then-pack. The
            # blockwise-sequential sum may differ from jnp.mean in the
            # last ulps (same config-scoped semantics as the Pallas bit
            # layout).
            from pytorch_ps_mpi_tpu.ops.sign_pallas import encode_signs

            packed, abs_sum = encode_signs(flat.astype(jnp.float32))
            scale = abs_sum / n
        else:
            scale = jnp.mean(jnp.abs(flat))
            bits = (flat >= 0).astype(jnp.uint8)
            pad = _packed_len(n) * 8 - n
            bits = jnp.pad(bits, (0, pad)).reshape(-1, 8)
            weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
            packed = (bits * weights).sum(axis=1).astype(jnp.uint8)
        return {"packed": packed, "scale": scale.astype(jnp.float32)}, state

    def _unpack(self, packed, n):
        weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
        bits = (packed[:, None] & weights[None, :]) > 0
        return bits.reshape(-1)[:n]

    def decode(self, payload, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        if self._pallas_ok(n):
            from pytorch_ps_mpi_tpu.ops.sign_pallas import unpack_signs

            signs = unpack_signs(payload["packed"])
            g = (signs * payload["scale"]).astype(dtype)
            return g.reshape(shape)
        signs = self._unpack(payload["packed"], n)
        g = jnp.where(signs, payload["scale"], -payload["scale"]).astype(dtype)
        return g.reshape(shape)

    def can_aggregate(self, shape, dtype) -> bool:
        # the Pallas bit layout (sublane-grouped) has no host-side
        # unpack; those units fall back to decode_sum automatically
        n = int(np.prod(shape)) if shape else 1
        return not self._pallas_ok(n)

    def aggregate(self, payloads, shape, dtype):
        """Vote-count aggregation: per-element positive-sign votes in an
        int32 counter plus the summed scale. Σ_w s_w·(2b_w − 1) is
        approximated by s̄·(2·votes − W); the per-frame decode collapses
        to ONE at agg_decode time."""
        n = int(np.prod(shape)) if shape else 1
        bits = jax.vmap(lambda p: self._unpack(p, n))(payloads["packed"])
        votes = bits.astype(jnp.int32).sum(axis=0)
        scale_sum = payloads["scale"].astype(jnp.float32).sum()
        return ({"votes": votes, "scale_sum": scale_sum},
                {"frames": int(payloads["packed"].shape[0])})

    def agg_decode(self, agg_payload, meta, shape, dtype):
        w = meta["frames"]
        mean_scale = agg_payload["scale_sum"] / w
        out = (2 * agg_payload["votes"] - w).astype(dtype) * mean_scale
        return out.astype(dtype).reshape(shape)

    def agg_init(self, shape, dtype):
        from pytorch_ps_mpi_tpu.utils import native as _native

        n = int(np.prod(shape)) if shape else 1
        # bind the native library once per round — the env-var read +
        # symbol probe in fold_lib() is per-push money on the serve
        # loop's hot path (same discipline as scalefold/sparse_agg_init)
        return {"frames": 0, "votes": np.zeros(n, np.int32),
                "scale_sum": 0.0, "n": n, "lib": _native.fold_lib()}

    def agg_fold(self, acc, payload):
        # pure integer accumulate — the widened-counter vote domain.
        # Native fast path: one C++ bit-unpack + vote-count pass
        # (wc_fold_sign, bitorder 'little' like np.unpackbits and the
        # jnp pack weights [1, 2, 4, ...]); integer domain, so native
        # and numpy are identical by construction.
        from pytorch_ps_mpi_tpu.utils import native as _native

        lib = acc.get("lib")
        packed = np.ascontiguousarray(payload["packed"], np.uint8).reshape(-1)
        if lib is not None:
            _native.fold_sign(lib, acc["votes"], packed)
        else:
            acc["votes"] += np.unpackbits(packed, count=acc["n"],
                                          bitorder="little")
        acc["scale_sum"] += float(payload["scale"])
        acc["frames"] += 1

    def agg_finalize(self, acc, shape, dtype):
        w = acc["frames"]
        mean_scale = np.float32(acc["scale_sum"] / w)
        out = (2 * acc["votes"] - w).astype(np.float32) * mean_scale
        return out.astype(dtype).reshape(shape)

    def payload_bits(self, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        return _packed_len(n) * 8 + 32
