"""Gradient codecs: the pluggable compression surface.

Re-designs the reference's import-by-convention ``codings`` hook
(``ps.py:18``, interface inferred at ``ps.py:94,165-167``) as a real plugin
registry. A codec turns a gradient array into a static-shape payload pytree
before the collective and back after it — replacing the reference's
host-side pickle+blosc wire compression (``mpi_comms.py:18-30,186-193``)
with on-device sparsification/quantization, which is what actually saves
ICI bandwidth (byte-level entropy coding is pointless when the interconnect
outruns any host CPU compressor — SURVEY §2.4).
"""

from pytorch_ps_mpi_tpu.codecs.base import Codec, get_codec, register_codec
from pytorch_ps_mpi_tpu.codecs.identity import IdentityCodec
from pytorch_ps_mpi_tpu.codecs.cast import Bf16Codec, F16Codec
from pytorch_ps_mpi_tpu.codecs.topk import TopKCodec
from pytorch_ps_mpi_tpu.codecs.blocktopk import BlockTopK8Codec, BlockTopKCodec
from pytorch_ps_mpi_tpu.codecs.threshold import ThresholdCodec
from pytorch_ps_mpi_tpu.codecs.randomk import RandomKCodec
from pytorch_ps_mpi_tpu.codecs.quant import Int8Codec, QSGDCodec
from pytorch_ps_mpi_tpu.codecs.sign import SignCodec
from pytorch_ps_mpi_tpu.codecs.terngrad import TernGradCodec
from pytorch_ps_mpi_tpu.codecs.powersgd import PowerSGDCodec
from pytorch_ps_mpi_tpu.codecs.error_feedback import ErrorFeedback

__all__ = [
    "Codec",
    "get_codec",
    "register_codec",
    "IdentityCodec",
    "Bf16Codec",
    "F16Codec",
    "TopKCodec",
    "BlockTopKCodec",
    "BlockTopK8Codec",
    "ThresholdCodec",
    "RandomKCodec",
    "Int8Codec",
    "QSGDCodec",
    "SignCodec",
    "TernGradCodec",
    "PowerSGDCodec",
    "ErrorFeedback",
]
