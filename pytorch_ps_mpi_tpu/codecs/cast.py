"""Cast codec: gradients ride the wire in a narrower float dtype.

The cheapest compression there is — one cast each way, 2x fewer wire
bytes with bf16 (the TPU's native matmul width, so the information loss
matches what the MXU already computes in) — and the natural DEFAULT for
DCN wires where bandwidth is the bottleneck but sparsification is
unwanted. Complements ``MPI_PS(comm_dtype=...)``, which narrows the
in-XLA collective: this narrows the HOST wire of the async PS paths
(``CodecWire`` payload bytes over shm/TCP/sharded), where the reference
shipped full pickled float64/float32 buffers (``mpi_comms.py:74``).

``supports_psum`` holds via the codec's ``wire_dtype``: the fused psum
path (``ps.aggregate``) narrows the collective to ``wire_dtype`` and
casts back — the cast IS this codec's encode, so the fast path applies
it to the wire rather than skipping it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.codecs.base import (
    Codec,
    dense_agg_finalize,
    dense_agg_init,
    register_codec,
)


@register_codec("bf16")
class Bf16Codec(Codec):
    supports_psum = True
    # a cast is elementwise: casting one flat bucket == casting each leaf
    # (bit-exact), so bucketed aggregation is lossless relative to per-leaf
    bucketable = True
    # exact: aggregation is the same cast-up-then-sum decode_sum runs;
    # the streaming accumulator is one f32 array per unit
    supports_aggregate = True

    wire_dtype = jnp.bfloat16

    def encode(self, grad, state=(), rng=None):
        return grad.astype(self.wire_dtype), state

    def decode(self, payload, shape, dtype):
        return payload.astype(dtype).reshape(shape)

    def decode_sum(self, payloads, shape, dtype):
        # cast up BEFORE the sum: world-many bf16 addends would lose
        # low bits pairwise; f32 accumulation matches psum's behavior
        return payloads.astype(dtype).sum(axis=0).reshape(shape)

    def aggregate(self, payloads, shape, dtype):
        # same cast-up-before-sum as decode_sum (bit-exact)
        return (payloads.astype(dtype).sum(axis=0),
                {"frames": int(payloads.shape[0])})

    def agg_decode(self, agg_payload, meta, shape, dtype):
        return agg_payload.astype(dtype).reshape(shape)

    def agg_init(self, shape, dtype):
        from pytorch_ps_mpi_tpu.utils import native as _native

        acc = dense_agg_init(shape)
        # bind once per round, not per push (fold_lib reads the env var
        # and probes symbols — hot-path money); f16 has no fused kernel
        acc["lib"] = (_native.fold_lib()
                      if self.wire_dtype == jnp.bfloat16 else None)
        return acc

    def agg_fold(self, acc, payload):
        # cast up per frame (ml_dtypes handles the bf16/f16 view), then
        # accumulate in f32 — the streaming mirror of decode_sum's
        # cast-before-sum rule. bf16 payloads have a native fused
        # cast-up + add (wc_fold_dense_bf16: a bf16 is the top 16 bits
        # of the equal-valued f32, so the cast is exact and the numpy
        # astype temp never exists); f16 keeps the numpy path.
        from pytorch_ps_mpi_tpu.utils import native as _native

        x = np.asarray(payload).reshape(-1)
        lib = acc.get("lib")
        if lib is not None and x.flags.c_contiguous:
            _native.fold_dense_bf16(lib, acc["acc"], x.view(np.uint16))
        else:
            acc["acc"] += x.astype(np.float32)
        acc["frames"] += 1

    def agg_finalize(self, acc, shape, dtype):
        return dense_agg_finalize(acc, shape, dtype)

    def payload_bits(self, shape, dtype):
        n = int(np.prod(shape)) if shape else 1
        return n * jnp.dtype(self.wire_dtype).itemsize * 8


@register_codec("f16")
class F16Codec(Bf16Codec):
    """IEEE half: more mantissa, less range than bf16 — for wires whose
    consumers prefer fp16 (e.g. non-TPU peers on the DCN).

    Range handling: magnitudes above f16's max finite (65504) would
    overflow to inf on the wire and corrupt the server-side update, so
    ``encode`` clips to ±65504 first — in f32, because casting the bound
    to a coarser grad dtype first (bf16 rounds 65504 → 65536) would
    defeat it. bf16, sharing f32's exponent range, needs no such clip.
    Exploding gradients large enough to hit the clip should be paired
    with gradient clipping anyway (``clip_norm``).

    ``supports_psum`` is disabled (unlike bf16): the fused psum fast path
    narrows the collective with a bare ``astype`` and would bypass this
    clip, overflowing on-chip exactly as the wire would. f16 is a host-
    wire codec by purpose (DCN peers that prefer IEEE half); on-chip
    collectives should narrow with bf16/``comm_dtype`` instead, so f16
    takes the encode/decode all-gather path where the clip always runs."""

    supports_psum = False
    wire_dtype = jnp.float16

    def encode(self, grad, state=(), rng=None):
        m = float(jnp.finfo(jnp.float16).max)
        clipped = jnp.clip(grad.astype(jnp.float32), -m, m)
        return clipped.astype(self.wire_dtype), state
