"""Codec interface + registry.

Contract (the TPU-native version of the reference's inferred hook interface,
SURVEY §2.2):

- ``encode(grad, state, rng) -> (payload, new_state)`` — called per
  parameter on each worker's *local* gradient, before the collective
  (reference ``code.encode``, ``ps.py:94``, ran in the autograd-hook thread
  pool). Payload is a pytree of arrays with **static shapes** so it can ride
  ``lax.all_gather`` under jit — the analog of the reference's fixed
  ``max_bytes`` padding for ragged messages (``mpi_comms.py:82-85``).
- ``decode(payload, shape, dtype) -> grad`` — inverse, called per
  (parameter × rank) on the receive side (reference ``code.decode``,
  ``ps.py:166``).
- ``decode_sum(payloads, shape, dtype) -> grad`` — decode a stacked
  ``[world, ...]`` payload batch and sum over ranks in one shot (the
  reference's ``sum(grads)`` loop, ``ps.py:176``); the default is a
  ``lax.scan`` fold (peak memory = ONE decoded tensor + the accumulator,
  never a ``[world, ...]`` decoded stack) and codecs override it when a
  fused form exists (e.g. top-k scatter-add).
- ``aggregate(payloads, shape, dtype) -> (agg_payload, meta)`` /
  ``agg_decode(agg_payload, meta, shape, dtype) -> grad`` — homomorphic
  aggregation (THC / SparCML, PAPERS.md): sum a stacked payload batch in
  the COMPRESSED domain, then decode ONCE. ``agg_payload`` is sized by
  the payloads, never by a ``[world, decoded]`` stack; codecs without an
  exact or probe-certified algebra leave ``supports_aggregate`` False
  and every consumer falls back to ``decode_sum`` automatically.
- ``agg_init(shape, dtype)`` / ``agg_fold(acc, payload)`` /
  ``agg_finalize(acc, shape, dtype)`` — the STREAMING (host-side, numpy)
  form of the same algebra, used by the async serve loop's
  ``CodecWire`` aggregator: each arriving push folds into a compressed
  accumulator and the one decode happens at publish time
  (``decodes_per_publish == 1``). The hierarchical tree
  (``parallel.tree``) runs the SAME streaming algebra at every
  intermediate hop: a leader folds its group's payloads without any
  per-push decode, finalizes once per upstream round, and re-encodes
  the aggregate for the next hop behind per-hop error feedback
  (``codecs.error_feedback.HopErrorFeedback``), so the fold algebra is
  the tree's one aggregation primitive and its SUM semantics must hold
  recursively — a folded-then-re-encoded payload is a valid input to
  the parent's fold. Codecs whose payload statistics are per-input
  (sign's mean|g|, int8's absmax) keep working because the re-encode
  recomputes them on the aggregate; nothing mid-tree ever assumes a
  payload came from a single worker.
- ``init_state(shape, dtype)`` — per-leaf codec state (e.g. error-feedback
  memory); ``()`` for stateless codecs. Explicit state threading replaces
  the reference's mutable ``code.codes`` side channel (``ps.py:165``).
- ``payload_bits(shape, dtype)`` — wire size in bits, for the
  ``msg_bytes``/``packaged_bytes`` metrics (reference ``ps.py:135-136``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

#: accepted values of the lossy codecs' ``nonfinite=`` constructor kwarg
NONFINITE_MODES = ("propagate", "zero", "raise")


def check_nonfinite_mode(mode: str) -> str:
    """Constructor-time validation of the ``nonfinite=`` kwarg — a typo
    must fail where the config was written, not at the first encode on
    a worker mid-startup."""
    if mode not in NONFINITE_MODES:
        raise ValueError(
            f"nonfinite must be one of {NONFINITE_MODES}, got {mode!r}"
        )
    return mode


def guard_nonfinite(flat: jax.Array, mode: str, codec_name: str) -> jax.Array:
    """The non-finite input guard the lossy codecs share.

    ``mode`` is the codec's ``nonfinite=`` kwarg:

    - ``"propagate"`` — legacy behavior: NaN/Inf flow into the payload
      statistics (sign's mean|g|, terngrad's max|g|, qsgd's norm all go
      NaN and poison every decoded element) undetected.
    - ``"zero"`` — sanitize: non-finite elements become 0 before any
      statistic or quantization, so one bad element can no longer wipe
      the whole payload. jit-safe (a ``where``, fused for free).
    - ``"raise"`` — eager (concrete-array) encodes raise
      ``FloatingPointError`` on any non-finite input — the fail-fast
      debugging mode. Under tracing a data-dependent raise is
      impossible, so traced encodes degrade to the ``"zero"`` sanitize
      (the payload stays finite either way); pair with the serve loop's
      NumericsMonitor for the online detection story.
    """
    if mode == "propagate":
        return flat
    if mode not in NONFINITE_MODES:
        raise ValueError(
            f"nonfinite must be one of {NONFINITE_MODES}, got {mode!r}"
        )
    if mode == "raise" and not isinstance(flat, jax.core.Tracer):
        bad = int(jnp.sum(~jnp.isfinite(flat)))
        if bad:
            raise FloatingPointError(
                f"{codec_name}.encode: {bad} non-finite gradient "
                "element(s) in input (nonfinite='raise')"
            )
        return flat
    return jnp.where(jnp.isfinite(flat), flat, jnp.zeros_like(flat))


class Codec:
    """Base codec: subclasses override encode/decode (+ optionally
    decode_sum, init_state, payload_bits)."""

    #: identity-like codecs set this so the train step can use a single
    #: fused psum instead of all_gather + decode + sum.
    supports_psum: bool = False
    #: codecs that consume randomness (random-k, QSGD) set this so the
    #: train step threads a per-worker PRNG key in.
    needs_rng: bool = False
    #: shape-agnostic AND stateless codecs set this so flat-bucket
    #: aggregation (``bucketing.BucketPlan``) may encode one dtype-uniform
    #: ~MB-scale bucket instead of hundreds of per-leaf fragments.
    #: Contract: ``init_state`` returns ``()`` (per-bucket state has no
    #: home — bucket boundaries are a transport detail, not a training
    #: one) and ``encode``/``decode``/``decode_sum`` treat the input as an
    #: opaque flat array (any per-input statistic — sign's mean|g|, int8's
    #: absmax — is then computed per bucket instead of per tensor, a
    #: documented semantics change for those lossy codecs). Per-tensor
    #: codecs (PowerSGD's 2-D factorization, top-k's per-tensor selection,
    #: stateful error feedback) leave this False and keep the per-leaf
    #: path even when bucketing is on.
    bucketable: bool = False
    #: codecs whose aggregation IS a collective protocol (PowerSGD's
    #: two-psum shared-Q form) set this and implement
    #: ``fused_allreduce(grad, state, axis_name, comm_dtype=None) ->
    #: (summed, new_state)`` (+ ``fused_wire_bits(shape, dtype,
    #: comm_dtype=None)`` for metrics): the fused on-mesh step then runs
    #: it in place of encode → all_gather → decode_sum, threading the
    #: optimizer's ``comm_dtype`` so uncompressed leaves still ride a
    #: narrowed wire. ``encode``/``decode_sum`` remain the payload form
    #: for wires with no synchronous collective (async/DCN/host PS).
    supports_fused_allreduce: bool = False
    #: codecs whose payload algebra allows compressed-domain aggregation
    #: set this and implement ``aggregate``/``agg_decode`` (+ optionally
    #: the streaming ``agg_init``/``agg_fold``/``agg_finalize`` overrides
    #: when an O(payload) accumulator exists). False means every consumer
    #: (ps.aggregate, the CodecWire serve-loop aggregator) falls back to
    #: decode_sum — the always-correct path.
    supports_aggregate: bool = False
    #: True when ``aggregate`` is bit-identical to ``decode_sum`` (the
    #: integer/sparse algebras); False for probe-certified approximations
    #: (sign's vote-count algebra), which the SPMD training path never
    #: uses implicitly and the host wire ships behind the measured
    #: fidelity contract in ``benchmarks/fidelity_bench.py --aggregate``.
    agg_exact: bool = True

    def init_state(self, shape: Tuple[int, ...], dtype) -> PyTree:
        return ()

    def encode(self, grad: jax.Array, state: PyTree = (),
               rng: Optional[jax.Array] = None) -> Tuple[PyTree, PyTree]:
        raise NotImplementedError

    def decode(self, payload: PyTree, shape: Tuple[int, ...], dtype) -> jax.Array:
        raise NotImplementedError

    def decode_sum(self, payloads: PyTree, shape: Tuple[int, ...], dtype) -> jax.Array:
        """Decode a [world, ...]-stacked payload pytree, summed over ranks.

        Default: a ``lax.scan`` fold — one rank decoded per step into a
        running accumulator, so peak memory is ONE decoded tensor plus
        the accumulator instead of the ``[world, ...]`` decoded stack the
        old vmap-then-sum form materialized (at BERT scale × 8 workers
        that stack was a ~4 GB cliff). Order note: the fold accumulates
        ranks sequentially (bit-exact to the left-fold definition,
        ``tests/test_agg.py``); XLA's axis-0 reduce used a tree order,
        so the two forms agree to 1 ulp per element, not bitwise, for
        world > 2."""
        def body(acc, p):
            return acc + self.decode(p, shape, dtype).astype(acc.dtype), None

        summed, _ = jax.lax.scan(body, jnp.zeros(shape, dtype), payloads)
        return summed

    # -- homomorphic aggregation (THC / SparCML; see module docstring) ----
    def can_aggregate(self, shape: Tuple[int, ...], dtype) -> bool:
        """Per-unit refinement of ``supports_aggregate``: a codec may
        support the algebra in general but not for a particular wire
        unit (sign's Pallas bit layout has no host-side unpack). The
        CodecWire aggregator checks every unit and falls back to
        decode_sum wholesale when any says no."""
        return self.supports_aggregate

    def aggregate(self, payloads: PyTree, shape: Tuple[int, ...], dtype
                  ) -> Tuple[PyTree, Dict[str, Any]]:
        """Compressed-domain sum of a [world, ...]-stacked payload batch:
        returns ``(agg_payload, meta)`` where ``agg_payload`` is sized by
        the payloads (sparse index-merge, widened integer counts, summed
        low-rank factors) and one :meth:`agg_decode` call yields the
        summed gradient. jnp ops only — runs under jit/shard_map."""
        raise NotImplementedError(
            f"{type(self).__name__} has no compressed-domain aggregation "
            "algebra (supports_aggregate=False); use decode_sum"
        )

    def agg_decode(self, agg_payload: PyTree, meta: Dict[str, Any],
                   shape: Tuple[int, ...], dtype) -> jax.Array:
        """The ONE decode of an aggregated payload → summed gradient."""
        raise NotImplementedError

    # -- streaming form (host-side numpy; the serve-loop accumulator) -----
    def agg_init(self, shape: Tuple[int, ...], dtype) -> Dict[str, Any]:
        """Fresh streaming accumulator for one wire unit. The default
        keeps the raw payloads (payload-sized memory — for sparse codecs
        this IS the index-merge accumulator) and defers the algebra to
        :meth:`aggregate` at finalize; codecs with an O(1)-frames
        accumulator (int8's scale-folded sum, sign's vote counts)
        override all three methods."""
        return {"frames": 0, "payloads": []}

    def agg_fold(self, acc: Dict[str, Any], payload: PyTree) -> None:
        """Fold ONE worker's payload (numpy array views into the receive
        buffer — anything retained must be copied) into ``acc``."""
        acc["payloads"].append(jax.tree.map(np.copy, payload))
        acc["frames"] += 1

    def agg_finalize(self, acc: Dict[str, Any], shape: Tuple[int, ...],
                     dtype):
        """One decode of the accumulated state → summed gradient (numpy
        or jax array, ``shape``-shaped)."""
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *acc["payloads"])
        agg, meta = self.aggregate(stacked, shape, dtype)
        return self.agg_decode(agg, meta, shape, dtype)

    def payload_bits(self, shape: Tuple[int, ...], dtype) -> int:
        """Encoded wire size in bits per gradient (for metrics)."""
        payload, _ = jax.eval_shape(
            lambda: self.encode(jnp.zeros(shape, dtype), self.init_state(shape, dtype),
                                jax.random.key(0) if self.needs_rng else None)
        )
        return sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize * 8
            for leaf in jax.tree.leaves(payload)
        )

    def fidelity_probe(self, grad: jax.Array, state: PyTree = (),
                       rng: Optional[jax.Array] = None) -> Dict[str, float]:
        """Decode-after-encode fidelity of THIS codec on a real gradient:
        what the wire actually does to the values it carries, measured
        online instead of assumed from the paper. Returns relative L2
        reconstruction error, cosine similarity, and achieved
        bits-per-parameter — the three numbers the compression-utility
        literature gates wins on. Read-only: codec state is consulted
        (error feedback probes through its residual memory) but NEVER
        updated, so a probe is safe mid-training at any cadence.

        ``state`` defaults to a fresh ``init_state``; stochastic codecs
        need ``rng`` (a default key is used when omitted). Identity-like
        codecs report ~0 error / ~1 cosine — the sanity anchor the
        numerics smoke asserts."""
        grad = jnp.asarray(grad)
        if not jax.tree.leaves(state):
            state = self.init_state(grad.shape, grad.dtype)
        if rng is None and self.needs_rng:
            rng = jax.random.key(0)
        payload, _ = self.encode(grad, state, rng)
        rec = self.decode(payload, grad.shape, grad.dtype)
        g = grad.astype(jnp.float32).reshape(-1)
        r = rec.astype(jnp.float32).reshape(-1)
        gn = jnp.linalg.norm(g)
        rel = jnp.linalg.norm(r - g) / jnp.maximum(gn, 1e-30)
        cos = jnp.dot(r, g) / jnp.maximum(jnp.linalg.norm(r) * gn, 1e-30)
        n = int(np.prod(grad.shape)) if grad.shape else 1
        return {
            "rel_error": float(rel),
            "cosine": float(cos),
            "bits_per_param": self.payload_bits(grad.shape, grad.dtype) / n,
            "grad_norm": float(gn),
        }


# -- shared streaming accumulator for the sparse index-merge family --------
# (top-k / block-top-k / random-k / threshold): the accumulator IS the
# concatenated (values, indices) list — O(payload) per fold, and the one
# finalize scatter-adds world×k entries into the dense gradient. Pure
# numpy: the serve loop's per-push cost carries no jit dispatch.
#
# With the native fast path (utils/native.fold_lib, PS_NO_NATIVE off),
# the accumulator is the dense f32 gradient itself and each fold is ONE
# C++ scatter-add pass over the payload (wc_fold_sparse) — same O(k) per
# push, no per-push array copies, no finalize concat. Accumulation order
# (push order, then element order) matches np.add.at over the concat
# exactly, so the two paths are bit-identical.
#
# The dense buffers are POOLED across rounds: allocating + first-touch
# faulting a fresh zeros(n) costs ~3 ms at 8M elements — it would
# dominate the whole round and make the "per-push cost is O(payload)"
# claim false. Instead each round remembers which entries its folds
# touched, releases the buffer at finalize (or aggregator GC), and the
# next round scatter-zeroes ONLY those entries on reuse — O(world × k)
# per round, flat in model size, and bit-identical to a fresh zeros
# buffer. A buffer is handed out again only once the pool holds at
# least two (FIFO), so a finalize's returned view stays valid until a
# LATER agg_begin — the serve loop derives the averaged gradient from
# it immediately, well inside that window.

_SPARSE_POOL: Dict[int, Any] = {}
_SPARSE_POOL_LOCK = threading.Lock()
_SPARSE_POOL_MIN_READY = 2   # buffers that must sit in the pool before reuse
_SPARSE_POOL_MAX = 4         # kept per size; beyond this they drop to the GC


def _sparse_pool_take(n: int):
    """A recycled dense buffer plus the index arrays its last round's
    folds touched (the caller re-zeroes exactly those entries), or None
    (pool cold — caller allocates a fresh zeros)."""
    with _SPARSE_POOL_LOCK:
        q = _SPARSE_POOL.get(n)
        if not q or len(q) < _SPARSE_POOL_MIN_READY:
            return None
        return q.pop(0)


def _sparse_pool_give(n: int, buf: np.ndarray, touched) -> None:
    with _SPARSE_POOL_LOCK:
        q = _SPARSE_POOL.setdefault(n, [])
        if len(q) < _SPARSE_POOL_MAX:
            q.append((buf, list(touched)))


def sparse_agg_release(acc: Dict[str, Any]) -> None:
    """Return a native sparse accumulator's dense buffer to the pool
    (idempotent). Called at finalize and from ``WireAggregator`` GC so
    abandoned rounds don't leak pool capacity."""
    # "touched" marks a NATIVE SPARSE acc — scale-fold/dense accs also
    # carry "acc"+"lib" but their buffers hold arbitrary sums that a
    # touched-entry zero pass could never clean, so they must never pool
    if acc.get("lib") is not None and "touched" in acc:
        buf = acc.pop("acc", None)
        if buf is not None:
            _sparse_pool_give(acc["n"], buf, acc.pop("touched"))


def sparse_agg_init(shape=None) -> Dict[str, Any]:
    from pytorch_ps_mpi_tpu.utils import native as _native

    lib = _native.fold_lib() if shape is not None else None
    if lib is not None:
        n = int(np.prod(shape)) if shape else 1
        taken = _sparse_pool_take(n)
        if taken is None:
            buf = np.zeros(n, np.float32)
            ptr = _native._f32(buf)
        else:
            buf, dirty = taken
            ptr = _native._f32(buf)
            for idx in dirty:  # O(touched) recycle, not O(n)
                if idx.size:
                    _native.zero_sparse(lib, buf, idx, acc_ptr=ptr)
        return {"frames": 0, "acc": buf, "n": n, "lib": lib,
                "touched": [], "ptr": ptr}
    return {"frames": 0, "values": [], "indices": []}


def sparse_agg_fold(acc: Dict[str, Any], values, indices) -> None:
    lib = acc.get("lib")
    if lib is not None:
        from pytorch_ps_mpi_tpu.utils import native as _native

        # the index copy is retained in `touched` (payload buffers are
        # transport-owned views) — it is both the C++ argument and the
        # record of which entries to re-zero when the buffer recycles
        idx = np.array(indices, np.int32, copy=True).reshape(-1)
        _native.fold_sparse(
            lib, acc["acc"],
            np.ascontiguousarray(values, np.float32).reshape(-1), idx,
            acc_ptr=acc["ptr"])
        acc["touched"].append(idx)
        acc["frames"] += 1
        return
    acc["values"].append(np.array(values, np.float32,
                                  copy=True).reshape(-1))
    acc["indices"].append(np.array(indices, copy=True).reshape(-1))
    acc["frames"] += 1


def sparse_agg_finalize(acc: Dict[str, Any], shape, dtype) -> np.ndarray:
    if acc.get("lib") is not None and "touched" in acc:
        out = acc["acc"].astype(dtype, copy=False).reshape(shape)
        # release to the pool NOW (not at GC): `out` may be a view of
        # the buffer, valid until a later agg_begin re-issues it — see
        # the pool contract above
        sparse_agg_release(acc)
        return out
    n = int(np.prod(shape)) if shape else 1
    idx = np.concatenate(acc["indices"]).astype(np.int64)
    val = np.concatenate(acc["values"])
    keep = (idx >= 0) & (idx < n)  # mode='drop' for block pad-slot picks
    flat = np.zeros(n, np.float32)
    np.add.at(flat, idx[keep], val[keep])
    return flat.astype(dtype, copy=False).reshape(shape)


# -- shared streaming accumulator for the dense cast-up family -------------
# (identity / bf16 / f16): ONE running f32 array per unit; each fold
# casts its frame up and adds in place, mirroring decode_sum's
# cast-before-sum rule. The per-frame cast stays with the codec.

def dense_agg_init(shape) -> Dict[str, Any]:
    n = int(np.prod(shape)) if shape else 1
    return {"frames": 0, "acc": np.zeros(n, np.float32)}


def dense_agg_finalize(acc: Dict[str, Any], shape, dtype) -> np.ndarray:
    # np.asarray: the accumulator may be a jax array (scale-fold jit path)
    return np.asarray(acc["acc"]).astype(dtype, copy=False).reshape(shape)


# -- shared streaming accumulator for the scale-folded integer family ------
# (int8 / qsgd / terngrad: decode is scale × integer payload). ONE f32
# accumulator per unit with a three-way fold path, picked at init:
# native (utils/native.fold_lib — one C++ SIMD dequant-multiply-add pass
# per push, no jit dispatch, bit-exact to the numpy form) when the fast
# path is armed; else the codec's jitted fused kernel at or above the
# crossover (numpy's multiply-into-temp + add pays ~3x the memory
# traffic there); else pure numpy, where a jit dispatch would dominate.
# The per-codec fused kernel stays with the codec; finalize is
# dense_agg_finalize.

FOLD_JIT_MIN = 1 << 16


def scalefold_agg_init(shape) -> Dict[str, Any]:
    from pytorch_ps_mpi_tpu.utils import native as _native

    n = int(np.prod(shape)) if shape else 1
    lib = _native.fold_lib()
    if lib is not None:
        return {"frames": 0, "acc": np.zeros(n, np.float32), "n": n,
                "lib": lib}
    if n >= FOLD_JIT_MIN:
        return {"frames": 0, "acc": jnp.zeros(n, jnp.float32), "n": n,
                "jit": True}
    return {"frames": 0, "acc": np.zeros(n, np.float32),
            "tmp": np.empty(n, np.float32), "n": n}


_REGISTRY: Dict[str, Type[Codec]] = {}


def register_codec(name: str) -> Callable[[Type[Codec]], Type[Codec]]:
    def deco(cls: Type[Codec]) -> Type[Codec]:
        _REGISTRY[name] = cls
        return cls
    return deco


def get_codec(name: str, **kwargs) -> Codec:
    """Instantiate a registered codec by name (e.g. ``get_codec('topk',
    fraction=0.01)``)."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown codec {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)
