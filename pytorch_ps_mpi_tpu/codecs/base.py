"""Codec interface + registry.

Contract (the TPU-native version of the reference's inferred hook interface,
SURVEY §2.2):

- ``encode(grad, state, rng) -> (payload, new_state)`` — called per
  parameter on each worker's *local* gradient, before the collective
  (reference ``code.encode``, ``ps.py:94``, ran in the autograd-hook thread
  pool). Payload is a pytree of arrays with **static shapes** so it can ride
  ``lax.all_gather`` under jit — the analog of the reference's fixed
  ``max_bytes`` padding for ragged messages (``mpi_comms.py:82-85``).
- ``decode(payload, shape, dtype) -> grad`` — inverse, called per
  (parameter × rank) on the receive side (reference ``code.decode``,
  ``ps.py:166``).
- ``decode_sum(payloads, shape, dtype) -> grad`` — decode a stacked
  ``[world, ...]`` payload batch and sum over ranks in one shot (the
  reference's ``sum(grads)`` loop, ``ps.py:176``); the default is
  vmap(decode).sum(0) and codecs override it when a fused form exists
  (e.g. top-k scatter-add).
- ``init_state(shape, dtype)`` — per-leaf codec state (e.g. error-feedback
  memory); ``()`` for stateless codecs. Explicit state threading replaces
  the reference's mutable ``code.codes`` side channel (``ps.py:165``).
- ``payload_bits(shape, dtype)`` — wire size in bits, for the
  ``msg_bytes``/``packaged_bytes`` metrics (reference ``ps.py:135-136``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

#: accepted values of the lossy codecs' ``nonfinite=`` constructor kwarg
NONFINITE_MODES = ("propagate", "zero", "raise")


def check_nonfinite_mode(mode: str) -> str:
    """Constructor-time validation of the ``nonfinite=`` kwarg — a typo
    must fail where the config was written, not at the first encode on
    a worker mid-startup."""
    if mode not in NONFINITE_MODES:
        raise ValueError(
            f"nonfinite must be one of {NONFINITE_MODES}, got {mode!r}"
        )
    return mode


def guard_nonfinite(flat: jax.Array, mode: str, codec_name: str) -> jax.Array:
    """The non-finite input guard the lossy codecs share.

    ``mode`` is the codec's ``nonfinite=`` kwarg:

    - ``"propagate"`` — legacy behavior: NaN/Inf flow into the payload
      statistics (sign's mean|g|, terngrad's max|g|, qsgd's norm all go
      NaN and poison every decoded element) undetected.
    - ``"zero"`` — sanitize: non-finite elements become 0 before any
      statistic or quantization, so one bad element can no longer wipe
      the whole payload. jit-safe (a ``where``, fused for free).
    - ``"raise"`` — eager (concrete-array) encodes raise
      ``FloatingPointError`` on any non-finite input — the fail-fast
      debugging mode. Under tracing a data-dependent raise is
      impossible, so traced encodes degrade to the ``"zero"`` sanitize
      (the payload stays finite either way); pair with the serve loop's
      NumericsMonitor for the online detection story.
    """
    if mode == "propagate":
        return flat
    if mode not in NONFINITE_MODES:
        raise ValueError(
            f"nonfinite must be one of {NONFINITE_MODES}, got {mode!r}"
        )
    if mode == "raise" and not isinstance(flat, jax.core.Tracer):
        bad = int(jnp.sum(~jnp.isfinite(flat)))
        if bad:
            raise FloatingPointError(
                f"{codec_name}.encode: {bad} non-finite gradient "
                "element(s) in input (nonfinite='raise')"
            )
        return flat
    return jnp.where(jnp.isfinite(flat), flat, jnp.zeros_like(flat))


class Codec:
    """Base codec: subclasses override encode/decode (+ optionally
    decode_sum, init_state, payload_bits)."""

    #: identity-like codecs set this so the train step can use a single
    #: fused psum instead of all_gather + decode + sum.
    supports_psum: bool = False
    #: codecs that consume randomness (random-k, QSGD) set this so the
    #: train step threads a per-worker PRNG key in.
    needs_rng: bool = False
    #: shape-agnostic AND stateless codecs set this so flat-bucket
    #: aggregation (``bucketing.BucketPlan``) may encode one dtype-uniform
    #: ~MB-scale bucket instead of hundreds of per-leaf fragments.
    #: Contract: ``init_state`` returns ``()`` (per-bucket state has no
    #: home — bucket boundaries are a transport detail, not a training
    #: one) and ``encode``/``decode``/``decode_sum`` treat the input as an
    #: opaque flat array (any per-input statistic — sign's mean|g|, int8's
    #: absmax — is then computed per bucket instead of per tensor, a
    #: documented semantics change for those lossy codecs). Per-tensor
    #: codecs (PowerSGD's 2-D factorization, top-k's per-tensor selection,
    #: stateful error feedback) leave this False and keep the per-leaf
    #: path even when bucketing is on.
    bucketable: bool = False
    #: codecs whose aggregation IS a collective protocol (PowerSGD's
    #: two-psum shared-Q form) set this and implement
    #: ``fused_allreduce(grad, state, axis_name, comm_dtype=None) ->
    #: (summed, new_state)`` (+ ``fused_wire_bits(shape, dtype,
    #: comm_dtype=None)`` for metrics): the fused on-mesh step then runs
    #: it in place of encode → all_gather → decode_sum, threading the
    #: optimizer's ``comm_dtype`` so uncompressed leaves still ride a
    #: narrowed wire. ``encode``/``decode_sum`` remain the payload form
    #: for wires with no synchronous collective (async/DCN/host PS).
    supports_fused_allreduce: bool = False

    def init_state(self, shape: Tuple[int, ...], dtype) -> PyTree:
        return ()

    def encode(self, grad: jax.Array, state: PyTree = (),
               rng: Optional[jax.Array] = None) -> Tuple[PyTree, PyTree]:
        raise NotImplementedError

    def decode(self, payload: PyTree, shape: Tuple[int, ...], dtype) -> jax.Array:
        raise NotImplementedError

    def decode_sum(self, payloads: PyTree, shape: Tuple[int, ...], dtype) -> jax.Array:
        """Decode a [world, ...]-stacked payload pytree, summed over ranks."""
        decoded = jax.vmap(lambda p: self.decode(p, shape, dtype))(payloads)
        return decoded.sum(axis=0)

    def payload_bits(self, shape: Tuple[int, ...], dtype) -> int:
        """Encoded wire size in bits per gradient (for metrics)."""
        payload, _ = jax.eval_shape(
            lambda: self.encode(jnp.zeros(shape, dtype), self.init_state(shape, dtype),
                                jax.random.key(0) if self.needs_rng else None)
        )
        return sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize * 8
            for leaf in jax.tree.leaves(payload)
        )

    def fidelity_probe(self, grad: jax.Array, state: PyTree = (),
                       rng: Optional[jax.Array] = None) -> Dict[str, float]:
        """Decode-after-encode fidelity of THIS codec on a real gradient:
        what the wire actually does to the values it carries, measured
        online instead of assumed from the paper. Returns relative L2
        reconstruction error, cosine similarity, and achieved
        bits-per-parameter — the three numbers the compression-utility
        literature gates wins on. Read-only: codec state is consulted
        (error feedback probes through its residual memory) but NEVER
        updated, so a probe is safe mid-training at any cadence.

        ``state`` defaults to a fresh ``init_state``; stochastic codecs
        need ``rng`` (a default key is used when omitted). Identity-like
        codecs report ~0 error / ~1 cosine — the sanity anchor the
        numerics smoke asserts."""
        grad = jnp.asarray(grad)
        if not jax.tree.leaves(state):
            state = self.init_state(grad.shape, grad.dtype)
        if rng is None and self.needs_rng:
            rng = jax.random.key(0)
        payload, _ = self.encode(grad, state, rng)
        rec = self.decode(payload, grad.shape, grad.dtype)
        g = grad.astype(jnp.float32).reshape(-1)
        r = rec.astype(jnp.float32).reshape(-1)
        gn = jnp.linalg.norm(g)
        rel = jnp.linalg.norm(r - g) / jnp.maximum(gn, 1e-30)
        cos = jnp.dot(r, g) / jnp.maximum(jnp.linalg.norm(r) * gn, 1e-30)
        n = int(np.prod(grad.shape)) if grad.shape else 1
        return {
            "rel_error": float(rel),
            "cosine": float(cos),
            "bits_per_param": self.payload_bits(grad.shape, grad.dtype) / n,
            "grad_norm": float(gn),
        }


_REGISTRY: Dict[str, Type[Codec]] = {}


def register_codec(name: str) -> Callable[[Type[Codec]], Type[Codec]]:
    def deco(cls: Type[Codec]) -> Type[Codec]:
        _REGISTRY[name] = cls
        return cls
    return deco


def get_codec(name: str, **kwargs) -> Codec:
    """Instantiate a registered codec by name (e.g. ``get_codec('topk',
    fraction=0.01)``)."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown codec {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)
