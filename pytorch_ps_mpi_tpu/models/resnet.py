"""ResNet-18/50 — BASELINE configs #2/#3/#4.

TPU-first choices: NHWC layout (XLA's native conv layout on TPU),
GroupNorm by default instead of BatchNorm so the gradient path is
stateless under ``jax.grad`` (no mutable batch_stats to sync across
replicas — the cross-replica BN sync problem simply doesn't arise; GN is
also batch-size independent, which matters once the global batch is
sharded over many chips). ``norm='batch'`` is available for parity
experiments and returns mutable state the caller threads through.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class AdaptiveGroupNorm(nn.Module):
    """GroupNorm with ``gcd(32, channels)`` groups so scaled-down test
    models (few filters) normalize correctly too."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        import math

        groups = math.gcd(32, x.shape[-1])
        return nn.GroupNorm(num_groups=groups, dtype=self.dtype)(x)


class ResNetBlock(nn.Module):
    """Basic 3x3 block (ResNet-18/34)."""

    filters: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding=1, use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding=1, use_bias=False)(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), self.strides, use_bias=False, name="shortcut"
            )(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1-3x3-1x1 bottleneck (ResNet-50/101/152)."""

    filters: int
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, padding=1, use_bias=False)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), self.strides, use_bias=False, name="shortcut"
            )(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Any
    num_classes: int = 1000
    num_filters: int = 64
    norm: str = "group"          # 'group' (stateless) or 'batch'
    small_inputs: bool = False   # CIFAR stem: 3x3 conv, no maxpool
    dtype: Any = jnp.float32
    # With norm='batch', set to the mesh data axis ('data') to get TRUE
    # SyncBatchNorm: batch statistics are psum-averaged across replicas
    # inside the forward pass (flax BatchNorm axis_name), so distributed
    # normalization matches a single device seeing the global batch —
    # torch DDP's SyncBatchNorm semantics. Requires running inside
    # shard_map/pmap with that axis bound (MPI_PS's loss_fn path is).
    # None = per-device BN (each replica normalizes with its local batch).
    bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.norm == "group":
            norm = functools.partial(AdaptiveGroupNorm, dtype=self.dtype)
        else:
            norm = functools.partial(
                nn.BatchNorm, use_running_average=not train, dtype=self.dtype,
                axis_name=self.bn_axis,
            )
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = nn.Conv(self.num_filters, (3, 3), padding=1, use_bias=False)(x)
        else:
            x = nn.Conv(self.num_filters, (7, 7), (2, 2), padding=3, use_bias=False)(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i, norm=norm, strides=strides
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
