"""2-layer MLP — BASELINE config #1 (MLP / MNIST, sync SGD smoke test)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (128, 10)

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=jnp.float32)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x
