"""Switch-style MoE masked-LM encoder — the expert-parallel model family.

No reference analog (the reference ships no models at all — SURVEY: "no
models, no training loop"); this pairs with ``parallel/ep.py`` the way
``models/bert.py`` pairs with ``parallel/ring.py``: the dense encoder
stack with every other FFN replaced by a top-k mixture-of-experts layer
(``top_k=1``: Fedus et al. 2021, Switch Transformer, arXiv:2101.03961;
``top_k=2``: the classic GShard gate — public techniques).

Two execution modes, same parameters:

- ``expert_axis=None`` (default): dense routing — every token gathers its
  expert's weights (fine single-device; this is also the test oracle).
- ``expert_axis='expert'``: call ``apply`` inside ``shard_map`` with that
  mesh axis bound; the MoE layers dispatch through
  ``parallel/ep.moe_apply`` (capacity buffers + all_to_all). Expert
  weights are stacked on a leading ``[E]`` axis either way — shard them
  ``P(expert_axis)`` host-side (see :func:`moe_param_spec`).

Load balancing: set ``aux_loss_weight`` and apply with
``mutable=["aux_loss"]`` — each MoE layer sows its weighted
Switch/GShard balance loss (``parallel/ep.load_balance_loss``); add the
collection's sum to the objective, or the router collapses onto a few
experts and the capacity buffers drop the rest.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.models.bert import BertConfig, SelfAttention
from pytorch_ps_mpi_tpu.parallel.ep import load_balance_loss, moe_apply


@dataclasses.dataclass(frozen=True)
class SwitchConfig:
    vocab_size: int = 1024
    hidden_size: int = 64
    num_layers: int = 2
    num_heads: int = 4
    intermediate_size: int = 128
    max_position: int = 128
    n_experts: int = 8
    capacity: int = 64          # per (expert, source device) — ep.py note
    top_k: int = 1              # 1 = Switch; 2 = classic GShard gate
    # weight of the Switch/GShard load-balancing auxiliary loss each MoE
    # layer SOWS into the "aux_loss" collection: apply with
    # mutable=["aux_loss"] and add the collection's SUM to the objective
    # as-is — the sown values already carry this weight. 0 disables.
    aux_loss_weight: float = 0.0
    expert_axis: Optional[str] = None
    dtype: Any = jnp.float32

    def bert_cfg(self) -> BertConfig:
        return BertConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            num_layers=self.num_layers, num_heads=self.num_heads,
            intermediate_size=self.intermediate_size,
            max_position=self.max_position, dtype=self.dtype,
        )


class MoEFFN(nn.Module):
    """Top-k routed FFN over n_experts expert MLPs (cfg.top_k)."""

    cfg: SwitchConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        d, f, e = c.hidden_size, c.intermediate_size, c.n_experts
        # inside shard_map the expert-stacked leaves arrive SLICED to the
        # local e/axis_size experts; declare the local shape so flax's
        # parameter shape check matches (init is done in dense mode —
        # expert_axis=None — so the stored params are the full [E] stack)
        e_param = e
        if c.expert_axis is not None:
            e_param = e // jax.lax.axis_size(c.expert_axis)
        params = {
            "wr": self.param(
                "wr", nn.initializers.normal(0.02), (d, e), jnp.float32
            ),
            "w1": self.param(
                "w1", nn.initializers.normal(0.1), (e_param, d, f), jnp.float32
            ),
            "w2": self.param(
                "w2", nn.initializers.normal(0.1), (e_param, f, d), jnp.float32
            ),
        }
        b, l, _ = x.shape
        tok = x.reshape(b * l, d)
        if c.aux_loss_weight:
            aux = load_balance_loss(tok, params["wr"], top_k=c.top_k,
                                    expert_axis=c.expert_axis)
            self.sow("aux_loss", "load_balance", c.aux_loss_weight * aux)
        if c.expert_axis is not None:
            out = moe_apply(tok, params, c.expert_axis,
                            capacity=c.capacity, top_k=c.top_k)
        else:
            from pytorch_ps_mpi_tpu.parallel.ep import moe_dense_oracle

            out = moe_dense_oracle(tok, params, top_k=c.top_k)
        return out.reshape(b, l, d)


class SwitchEncoderLayer(nn.Module):
    cfg: SwitchConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        y = SelfAttention(c.bert_cfg())(nn.LayerNorm(dtype=c.dtype)(x))
        x = x + y
        y = MoEFFN(c)(nn.LayerNorm(dtype=c.dtype)(x))
        return x + y


class SwitchMLM(nn.Module):
    """Token-in, vocab-logits-out MoE masked-LM (pre-norm, every layer's
    FFN is a Switch MoE)."""

    cfg: SwitchConfig

    @nn.compact
    def __call__(self, tokens, position_offset: int = 0):
        c = self.cfg
        tok = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                       name="tok_emb")(tokens)
        positions = position_offset + jnp.arange(tokens.shape[-1])
        pos = nn.Embed(c.max_position, c.hidden_size, dtype=c.dtype,
                       name="pos_emb")(positions)
        x = tok + pos[None]
        for i in range(c.num_layers):
            x = SwitchEncoderLayer(c, name=f"layer_{i}")(x)
        x = nn.LayerNorm(dtype=c.dtype)(x)
        logits = nn.Dense(c.vocab_size, dtype=c.dtype, name="mlm_head")(x)
        return logits.astype(jnp.float32)


def moe_param_spec(params: Any, expert_axis: str):
    """PartitionSpec pytree for SwitchMLM parameters: expert-stacked
    leaves (``w1``/``w2`` under any ``MoEFFN``) sharded over
    ``expert_axis``; everything else replicated."""
    from jax.sharding import PartitionSpec as P

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree.structure(params)
    specs = []
    for path, _ in flat:
        keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        sharded = any(k in ("w1", "w2") for k in keys)
        specs.append(P(expert_axis) if sharded else P())
    return jax.tree.unflatten(treedef, specs)
