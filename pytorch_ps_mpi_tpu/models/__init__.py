"""Model zoo for the BASELINE configs.

The reference ships no models (SURVEY "What the reference is NOT") — its
train scripts lived in a sibling research repo — but the BASELINE configs
(BASELINE.json) name the families the framework must drive: a 2-layer MLP
(MNIST), ResNet-18/50 (CIFAR-10 / ImageNet), and BERT-base MLM. All are
flax modules designed TPU-first: stateless norms in the grad path,
bfloat16-friendly, static shapes, ring-attention option for long context.
"""

from pytorch_ps_mpi_tpu.models.mlp import MLP
from pytorch_ps_mpi_tpu.models.resnet import ResNet, ResNet18, ResNet50
from pytorch_ps_mpi_tpu.models.bert import BertConfig, BertMLM, stack_layer_params
from pytorch_ps_mpi_tpu.models.moe import SwitchConfig, SwitchMLM
from pytorch_ps_mpi_tpu.models.gpt import GPTLM, causal_lm_loss, gpt_config, gpt_tiny

__all__ = ["MLP", "ResNet", "ResNet18", "ResNet50", "BertConfig", "BertMLM",
           "SwitchConfig", "SwitchMLM", "GPTLM", "causal_lm_loss",
           "gpt_config", "gpt_tiny"]
