"""Decoder-only causal LM (GPT family) — the autoregressive complement
to the BERT encoder, sharing its MXU-shaped transformer blocks.

The reference repo carries no models at all (they lived in a sibling
research repo, SURVEY §2.1); this family exists because a PS framework's
stress cases differ by objective: the MLM stack stresses flat-gradient
bandwidth, while a causal LM exercises the CAUSAL paths of both
sequence-parallel designs (ring attention's skip-early-blocks schedule
and Ulysses' masked local attention) inside a real model rather than a
kernel test. ``attention='ring'`` with ``causal=True`` is the canonical
long-context training shape: each device holds a sequence shard and the
ring skips the blocks the mask would zero anyway.

Weight tying (lm head = token embedding, Press & Wolf 2017) is on by
default, as in GPT-2.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.models.bert import (
    BertConfig,
    encoder_stack,
    target_log_likelihood,
)


def gpt_config(**kw) -> BertConfig:
    """A ``BertConfig`` with causal masking on — the one knob that turns
    the encoder stack into a decoder stack."""
    kw.setdefault("causal", True)
    return BertConfig(**kw)


def gpt_tiny(**kw) -> BertConfig:
    kw.setdefault("causal", True)
    return BertConfig.tiny(**kw)


class GPTLM(nn.Module):
    """Token-in, next-token-logits-out decoder (pre-norm, tied head).

    ``cfg.causal`` must be True — a non-causal config would silently
    train a bidirectional model on a next-token objective (trivially
    cheatable), so it is rejected loudly.
    """

    cfg: BertConfig
    tie_embeddings: bool = True

    @nn.compact
    def __call__(self, tokens, position_offset: int = 0):
        c = self.cfg
        if not c.causal:
            raise ValueError("GPTLM requires cfg.causal=True")
        tok_emb = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                           name="tok_emb")
        x = tok_emb(tokens)
        positions = position_offset + jnp.arange(tokens.shape[-1])
        pos = nn.Embed(c.max_position, c.hidden_size, dtype=c.dtype,
                       name="pos_emb")(positions)
        x = x + pos[None]
        x = encoder_stack(c, x)
        x = nn.LayerNorm(dtype=c.dtype)(x)
        if self.tie_embeddings:
            logits = x @ tok_emb.embedding.T.astype(c.dtype)
        else:
            logits = nn.Dense(c.vocab_size, dtype=c.dtype, name="lm_head")(x)
        return logits.astype(jnp.float32) if c.f32_logits else logits


def causal_lm_loss(logits, tokens, mask=None):
    """Next-token cross-entropy: position t predicts token t+1. ``mask``
    (optional, [b, l]) marks VALID input positions; the loss at the last
    position (no target) is always dropped. f32 accumulation at any
    logits dtype (``bert.target_log_likelihood``)."""
    ll = target_log_likelihood(logits[:, :-1], tokens[:, 1:])
    if mask is None:
        return -ll.mean()
    m = mask[:, 1:].astype(jnp.float32)
    return -(ll * m).sum() / jnp.maximum(m.sum(), 1.0)
