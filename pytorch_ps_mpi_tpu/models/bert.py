"""BERT-style encoder with an MLM head — BASELINE config #5 (large flat
gradient vector: the ~110M-param embedding+encoder stack stresses
aggregation bandwidth the way the config intends).

TPU-first: attention and MLPs are einsum/matmul shaped for the MXU,
bfloat16 compute with float32 params supported via ``dtype``, and
long-context runs under sequence parallelism — set
``attention='ring'`` and call ``apply`` inside ``shard_map`` with the
sequence sharded over ``seq_axis`` (``parallel/ring.py``); position
embeddings take a per-shard ``position_offset``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.parallel.ring import ring_attention
from pytorch_ps_mpi_tpu.parallel.ulysses import ulysses_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    dtype: Any = jnp.float32
    attention: str = "full"       # 'full', 'ring', or 'ulysses'
    seq_axis: str = "seq"         # mesh axis for ring/ulysses attention
    causal: bool = False          # decoder-only masking (GPT family)
    remat: bool = False           # rematerialize each layer's activations
    # in the backward pass (jax.checkpoint): activation memory drops from
    # O(layers) to O(1) layers' worth for ~1/3 extra FLOPs — the standard
    # HBM-for-FLOPs trade for long sequences / deep stacks on TPU
    scan_layers: bool = False     # lax.scan over a stacked layer body:
    # ONE layer's HLO instead of num_layers unrolled copies, cutting
    # compile time ~proportionally (the binding constraint on tunneled
    # remote_compile windows) at identical math. Param layout changes
    # (stacked [L, ...] leaves under 'layers'), so it is opt-in;
    # stack_layer_params converts a loop-layout checkpoint.
    f32_logits: bool = True       # False keeps the [B, S, V] logits in
    # the compute dtype: at GPT-2 scale the f32 materialization is
    # 1.65 GB at b8 s1024 of pure HBM traffic, and the loss functions
    # compute their reductions in f32 regardless (fused elementwise
    # upcast — no full-size f32 array). Opt-in lever, A/B'd per window
    # like remat/scan_layers.

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        defaults = dict(
            vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position=128,
        )
        defaults.update(kw)
        return BertConfig(**defaults)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        head_dim = c.hidden_size // c.num_heads
        qkv = nn.DenseGeneral(
            (3, c.num_heads, head_dim), axis=-1, dtype=c.dtype, name="qkv"
        )(x)                                   # [b, l, 3, h, d]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if c.attention == "ring":
            out = ring_attention(q, k, v, c.seq_axis, causal=c.causal)
        elif c.attention == "ulysses":
            out = ulysses_attention(q, k, v, c.seq_axis, causal=c.causal)
        elif c.attention in ("full", "flash", "einsum"):
            # 'flash': always the Pallas kernel (interpret mode off-TPU —
            # for tests). 'full': whichever path measured faster on TPU —
            # the kernel for long sequences (when shapes tile and Mosaic
            # lowers it), the dense einsum below FLASH_MIN_SEQ where
            # XLA's batched MXU matmuls win. 'einsum': force the dense
            # path (the flash-vs-einsum A/B in benchmarks/bert_bench.py).
            from pytorch_ps_mpi_tpu.ops.attention_pallas import (
                flash_attention,
                flash_auto_ok,
                flash_supported,
            )

            l = q.shape[1]
            if c.attention == "flash" and not flash_supported(l, l, dtype=c.dtype):
                # the explicit mode must fail loudly, not silently hand
                # an f32 dense fallback to a 'flash'-labeled A/B
                raise ValueError(
                    f"attention='flash' cannot tile seq={l} (needs a "
                    "power-of-two block >= 8 dividing it); use 'full' "
                    "for automatic fallback"
                )
            # 'full' prefers the path that measured faster: the gate
            # includes a FLASH_MIN_SEQ floor because XLA's fused dense
            # attention wins short sequences on the MXU (TPU v5e,
            # BERT-base b16 s128: einsum 14.75 ms/step vs flash 15.18;
            # benchmarks/flash_tune.py measures the crossover)
            use_kernel = c.attention == "flash" or (
                c.attention == "full" and flash_auto_ok(l, l, head_dim, c.dtype)
            )
            if use_kernel:
                out = flash_attention(q, k, v, causal=c.causal)
            else:
                s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / head_dim ** 0.5
                if c.causal:
                    mask = jnp.tril(jnp.ones((l, l), bool))
                    s = jnp.where(mask[None, None], s,
                                  jnp.asarray(-1e30, s.dtype))
                p = jax.nn.softmax(s, axis=-1)
                out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        else:
            # a typo'd mode must not silently run shard-local dense
            # attention (valid shapes, quietly wrong model under SP)
            raise ValueError(
                f"unknown attention={c.attention!r}: expected 'full', "
                "'flash', 'einsum', 'ring', or 'ulysses'"
            )
        return nn.DenseGeneral(
            c.hidden_size, axis=(-2, -1), dtype=c.dtype, name="out"
        )(out)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        y = SelfAttention(c)(nn.LayerNorm(dtype=c.dtype)(x))
        x = x + y
        y = nn.LayerNorm(dtype=c.dtype)(x)
        y = nn.Dense(c.intermediate_size, dtype=c.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(c.hidden_size, dtype=c.dtype)(y)
        return x + y


class _ScanBody(nn.Module):
    """Carry-style wrapper ``(x, None) -> (x, None)`` so ``nn.scan``
    can drive :class:`EncoderLayer` (whose call is plain ``x -> x``)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, _):
        return EncoderLayer(self.cfg)(x), None


def encoder_stack(c: BertConfig, x):
    """The shared L-layer trunk: unrolled named layers (``layer_{i}``)
    by default, or ONE scanned body with stacked ``[L, ...]`` params
    under ``layers`` when ``c.scan_layers`` — same math, one layer's
    HLO to compile instead of L copies."""
    if c.scan_layers:
        body = nn.remat(_ScanBody, prevent_cse=False) if c.remat else _ScanBody
        stack = nn.scan(
            body,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            length=c.num_layers,
        )
        x, _ = stack(c, name="layers")(x, None)
        return x
    layer_cls = nn.remat(EncoderLayer) if c.remat else EncoderLayer
    for i in range(c.num_layers):
        x = layer_cls(c, name=f"layer_{i}")(x)
    return x


def stack_layer_params(params, num_layers: int):
    """Convert loop-layout params (``layer_{i}`` subtrees) to the
    ``scan_layers`` layout (one ``layers/EncoderLayer_0`` subtree with a
    stacked leading axis) — the checkpoint-migration shim and the
    numerics-equality test's bridge."""
    stacked = jax.tree.map(
        lambda *leaves: jnp.stack(leaves),
        *[params[f"layer_{i}"] for i in range(num_layers)],
    )
    rest = {k: v for k, v in params.items()
            if not k.startswith("layer_")}
    rest["layers"] = {"EncoderLayer_0": stacked}
    return rest


class BertMLM(nn.Module):
    """Token-in, vocab-logits-out masked-LM model (pre-norm encoder)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, position_offset: int = 0):
        c = self.cfg
        tok = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype, name="tok_emb")(
            tokens
        )
        positions = position_offset + jnp.arange(tokens.shape[-1])
        pos = nn.Embed(c.max_position, c.hidden_size, dtype=c.dtype, name="pos_emb")(
            positions
        )
        x = tok + pos[None]
        x = encoder_stack(c, x)
        x = nn.LayerNorm(dtype=c.dtype)(x)
        logits = nn.Dense(c.vocab_size, dtype=c.dtype, name="mlm_head")(x)
        return logits.astype(jnp.float32) if c.f32_logits else logits


def target_log_likelihood(logits, targets):
    """Per-position ``log p(target)`` with f32-internal reductions for
    ANY logits dtype, WITHOUT materializing an f32 ``[..., V]`` array:
    the elementwise upcast feeds straight into the exp-sum reduction,
    which XLA fuses into one pass over the (possibly bf16) logits —
    that fusion is the entire point of ``f32_logits=False``. For f32
    inputs this is log_softmax+gather to within reassociation."""
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1)).astype(jnp.float32)
    z = jnp.exp(logits.astype(jnp.float32) - m[..., None])
    lse = m + jnp.log(jnp.sum(z, axis=-1))
    tgt = jnp.take_along_axis(
        logits, targets[..., None], axis=-1
    )[..., 0].astype(jnp.float32)
    return tgt - lse


def mlm_loss(logits, targets, mask):
    """Cross-entropy over masked positions only (f32 accumulation at
    any logits dtype — see :func:`target_log_likelihood`)."""
    ll = target_log_likelihood(logits, targets)
    mask = mask.astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
