"""Comms layer: typed collective wrappers over a device mesh.

The TPU-native replacement for the reference's ``mpi_comms.py``. Every MPI
collective the reference uses maps to an XLA collective over ICI:

=====================================  =======================================
reference (mpi4py, host bytes)          here (XLA, on-device arrays)
=====================================  =======================================
``Iallgatherv`` of pickled grads        ``lax.all_gather`` (``all_gather_tree``)
(``mpi_comms.py:162``)
``Iallgather`` of int32 sizes           compile-time static shapes; ragged
(``mpi_comms.py:153``, the "prepare"    payloads use max-size padding + a
phase)                                  true-length sidecar (``ragged_all_gather``)
``Igatherv`` to rank 0                  ``gather_to_leader``
(``mpi_comms.py:88``)
``Ibcast`` from rank 0                  ``broadcast_from_leader``
(``mpi_comms.py:132``)
sum of per-rank grads (``ps.py:176``)   ``lax.psum`` (``allreduce_sum_tree``)
``Request.Wait``                        XLA schedules/overlaps async
(``ps.py:146``)                         collectives; no explicit waits
pickle+blosc wire format                none: gradients stay typed on-device
(``mpi_comms.py:186-193``)              arrays; see ``utils/serialization.py``
                                        for the host-side pytree wire format
=====================================  =======================================

All functions here are pure and meant to be called *inside* ``shard_map``
(or any context where ``axis_name`` is bound). The two-phase size exchange
of the reference (``mpi_comms.py:144-174``) disappears entirely: shapes are
static under XLA, so "send sizes first" is a compile-time property. Only
ragged *encoded* payloads (top-k with data-dependent true length) need the
max-size + length-sidecar convention, mirroring the reference's ``max_bytes``
high-water padding (``mpi_comms.py:82-85``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

PyTree = Any


# ---------------------------------------------------------------------------
# Primitives (call inside shard_map / pmapped code)
# ---------------------------------------------------------------------------

def allreduce_sum(x: jax.Array, axis_name: str) -> jax.Array:
    """Sum ``x`` across the mesh axis. Fuses the reference's allgather +
    host-side ``sum(grads)`` (``ps.py:161,176``) into one ICI collective."""
    return lax.psum(x, axis_name)


def allreduce_sum_tree(tree: PyTree, axis_name: str) -> PyTree:
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


def allreduce_sum_buckets(
    buckets, axis_name, wire_dtype=None
) -> list:
    """One ``psum`` per flat dtype-grouped bucket (``bucketing.BucketPlan``
    output) — the launch-fused form of :func:`allreduce_sum_tree`: a
    BERT-size tree goes from hundreds of per-leaf collectives to a handful
    of ~MB-scale ones. ``wire_dtype`` narrows each bucket on the wire and
    casts back (same contract as ``MPI_PS(comm_dtype=...)``; applied
    unconditionally so numerics match the per-leaf psum path bit for
    bit)."""
    out = []
    for b in buckets:
        if wire_dtype is not None:
            out.append(lax.psum(b.astype(wire_dtype), axis_name).astype(b.dtype))
        else:
            out.append(lax.psum(b, axis_name))
    return out


def all_gather(x: jax.Array, axis_name: str) -> jax.Array:
    """Every rank receives every rank's ``x``, stacked on a new leading
    axis — the reference's ``Iallgatherv`` (``mpi_comms.py:160-163``) minus
    the bytes/size dance."""
    return lax.all_gather(x, axis_name)


def all_gather_tree(tree: PyTree, axis_name: str) -> PyTree:
    return jax.tree.map(lambda x: lax.all_gather(x, axis_name), tree)


def gather_to_leader(x: jax.Array, axis_name: str) -> jax.Array:
    """Rank-0-PS gather (reference ``igather``, ``mpi_comms.py:60-93``).

    Under SPMD every rank materializes the stacked result; semantically the
    leader (axis index 0) is the consumer. XLA's all-gather over ICI is the
    efficient lowering — a true gather would idle the other chips' links.
    """
    return lax.all_gather(x, axis_name)


def broadcast_from_leader(x: jax.Array, axis_name: str) -> jax.Array:
    """Every rank receives the leader's ``x`` (reference ``ibroadcast``,
    ``mpi_comms.py:127-133``). Lowering: mask-then-psum, which XLA turns
    into a broadcast-shaped collective."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == 0, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def broadcast_from_leader_tree(tree: PyTree, axis_name: str) -> PyTree:
    """Tree-mapped :func:`broadcast_from_leader` — the parameter read-back
    of a broadcast-topology PS (reference ``ibroadcast`` of the whole
    param dict, ``mpi_comms.py:127-133``). The optimizer's leader mode now
    uses the sharded ZeRO-1 lowering instead (``ps.leader_shard_update``);
    this remains the comms-layer primitive for replicating any leader-held
    pytree (e.g. initial params in a custom loop)."""
    idx_is_leader = lax.axis_index(axis_name) == 0
    def bcast(x):
        return lax.psum(jnp.where(idx_is_leader, x, jnp.zeros_like(x)), axis_name)
    return jax.tree.map(bcast, tree)


def ragged_all_gather(
    payload: jax.Array, length: jax.Array, axis_name: str
) -> Tuple[jax.Array, jax.Array]:
    """All-gather a variable-length payload.

    The XLA analog of the reference's two-phase ``Iallgather`` protocol
    (sizes first, then ``Iallgatherv``, ``mpi_comms.py:144-174``): here the
    *max* size is static (``payload.shape``), each rank's *true* length
    rides along as an int32 sidecar, and consumers mask beyond it — exactly
    the ``max_bytes`` padding + sentinel-trim idea (``mpi_comms.py:80-104``)
    without the sentinel's collision bug (SURVEY §2.3).

    Returns ``(payloads[world, *payload.shape], lengths[world])``.
    """
    payloads = lax.all_gather(payload, axis_name)
    lengths = lax.all_gather(jnp.asarray(length, jnp.int32), axis_name)
    return payloads, lengths


# -- collective/autodiff pairs for model-parallel regions --------------------
#
# Under ``shard_map(..., check_vma=False)`` the transpose of ``lax.psum``
# is another psum, which scales gradients by the axis size when the
# cotangent is replicated (the failure mode ``parallel/pp.py``'s module
# docstring documents). These two custom-VJP wrappers pin the correct
# local-gradient semantics explicitly — the classic conjugate pair of
# tensor-parallel frameworks (Megatron's f/g, Shoeybi et al. 2019,
# arXiv:1909.08053 §3 — public technique): an all-reduce in one
# direction is an identity in the other. They make model-parallel
# forward functions differentiable inside the optimizer's vma-unchecked
# shard_map, producing per-device LOCAL gradients that ``MPI_PS`` then
# aggregates over the data axis only.

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_fwd_identity_bwd(x: jax.Array, axis_name) -> jax.Array:
    """Forward: ``lax.psum(x, axis_name)``; backward: identity.

    Use at a model-parallel region's OUTPUT reduction (row-parallel
    matmul, pipeline loss replication): the output is replicated across
    the axis, so its replicated cotangent is already each shard's
    correct local cotangent — summing it again would scale gradients by
    the axis size."""
    return lax.psum(x, axis_name)


def _pfib_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _pfib_bwd(axis_name, _res, ct):
    return (ct,)


psum_fwd_identity_bwd.defvjp(_pfib_fwd, _pfib_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def identity_fwd_psum_bwd(x: jax.Array, axis_name) -> jax.Array:
    """Forward: identity; backward: ``lax.psum`` of the cotangent.

    Use at a model-parallel region's INPUT (a replicated activation
    consumed by every shard, e.g. the input of a column-parallel
    matmul): each shard back-propagates only its own contribution, and
    the true input gradient is their sum across the axis."""
    return x


def _ifpb_fwd(x, axis_name):
    return x, None


def _ifpb_bwd(axis_name, _res, ct):
    return (lax.psum(ct, axis_name),)


identity_fwd_psum_bwd.defvjp(_ifpb_fwd, _ifpb_bwd)


def ring_permute(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Send ``x`` to the next rank around the ring (receives from previous).

    The building block for ring collectives / ring attention; rides
    neighbor ICI links. No reference analog (MPI point-to-point was never
    used there) but falls out of the comms layer for free (SURVEY §2.5).
    """
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


# ---------------------------------------------------------------------------
# Host-level entry points: same collectives wrapped in shard_map + jit so a
# user can call them eagerly on sharded arrays (the reference's usage style,
# e.g. test_comms.py round-trips).
# ---------------------------------------------------------------------------

def _shard_mapped(fn: Callable, mesh: Mesh, axis_name: str, out_specs):
    in_spec = P(axis_name)
    return jax.jit(
        jax.shard_map(
            functools.partial(fn, axis_name=axis_name),
            mesh=mesh,
            in_specs=in_spec,
            out_specs=out_specs,
        )
    )


def host_allreduce_sum(x: jax.Array, mesh: Mesh, axis_name: str = "data") -> jax.Array:
    """Sum per-worker slices of ``x`` (stacked on the leading axis)."""
    fn = _shard_mapped(
        lambda v, axis_name: lax.psum(v, axis_name), mesh, axis_name, P()
    )
    return fn(x)


def host_all_gather(x: jax.Array, mesh: Mesh, axis_name: str = "data") -> jax.Array:
    fn = _shard_mapped(
        lambda v, axis_name: lax.all_gather(v, axis_name), mesh, axis_name, P(axis_name)
    )
    return fn(x)


def host_broadcast_from_leader(
    x: jax.Array, mesh: Mesh, axis_name: str = "data"
) -> jax.Array:
    fn = _shard_mapped(
        lambda v, axis_name: broadcast_from_leader(v, axis_name),
        mesh,
        axis_name,
        P(axis_name),
    )
    return fn(x)
