"""Auxiliary subsystems: metrics, host wire format, checkpointing.

The reference's auxiliary surface (SURVEY §5) and the gaps it left:
hand-rolled timing dicts (kept, as ``metrics``), pickle+blosc host wire
format (replaced by a typed pytree pack in ``serialization``), and
checkpoint/resume (absent in the reference; provided here via Orbax).
"""

from pytorch_ps_mpi_tpu.utils.metrics import StepTimer, MetricsAccumulator
from pytorch_ps_mpi_tpu.utils.serialization import (
    pack_arrays_into,
    pack_pytree,
    read_arrays,
    unpack_pytree,
    save_pytree,
    load_pytree,
)

__all__ = [
    "StepTimer",
    "MetricsAccumulator",
    "pack_arrays_into",
    "pack_pytree",
    "read_arrays",
    "unpack_pytree",
    "save_pytree",
    "load_pytree",
]
