"""ctypes bindings + lazy g++ build for the native wire codec.

The reference reached its native compressor through a third-party binding
(python-blosc → c-blosc, ``mpi_comms.py:25,29``); here the native code is
part of the framework (``native/wirecodec.cpp``) and compiled on first use
with the system toolchain. Pure-numpy fallbacks keep every feature working
when no compiler is available.

Wire format of :func:`compress` (little-endian):
  magic ``b'WC02'`` | u8 elem_size | u8 flags (1 = shuffled) | u64 raw_len
  | u32 crc32(raw) | payload (rle0, or stored raw when elem_size == 0)
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import tempfile
import zlib
from typing import Optional

import numpy as np

_MAGIC = b"WC02"
_HDR = struct.Struct("<4sBBQI")

_lib: Optional[ctypes.CDLL] = None
_BUILD_FAILURES: set = set()


class _FoldSpan(ctypes.Structure):
    """ctypes mirror of ``wirecodec.cpp``'s ``FoldSpan`` — one
    (start_ns, end_ns, elems) interval per ``wc_fold_*`` call, captured
    by the armed native span ring for the hop-anatomy plane. Layout is
    size-checked at load against ``wc_abi_fold_span_bytes`` and diffed
    field-for-field by the psanalyze ABI-drift rule."""

    _pack_ = 1
    _fields_ = [
        ("start_ns", ctypes.c_uint64),
        ("end_ns", ctypes.c_uint64),
        ("elems", ctypes.c_uint64),
    ]


assert ctypes.sizeof(_FoldSpan) == 24, "FoldSpan ctypes mirror drifted"

#: ``PS_NATIVE_SANITIZE`` → extra g++ flags. The sanitized builds land
#: in ``native/_build/<mode>/`` so they never clobber the normal cache;
#: ``make native-asan``/``native-ubsan`` (tools/native_sanitize.py) run
#: the parity suite against them with the runtime LD_PRELOADed (the
#: Python binary itself is uninstrumented). ``-ffp-contract=off`` stays:
#: the bit-exact native==numpy fold contract must hold under sanitizers
#: too, or the parity suite would be testing a different kernel.
SANITIZE_FLAGS = {
    "asan": ("-fsanitize=address", "-fno-omit-frame-pointer", "-g", "-O1"),
    "ubsan": ("-fsanitize=undefined", "-fno-sanitize-recover=all",
              "-g", "-O1"),
    "tsan": ("-fsanitize=thread", "-g", "-O1"),
}


def sanitize_mode() -> Optional[str]:
    """The active ``PS_NATIVE_SANITIZE`` mode, or None. Unknown values
    raise at the first build rather than silently producing an
    unsanitized library that a leak-check run would then vouch for."""
    mode = os.environ.get("PS_NATIVE_SANITIZE", "").strip().lower()
    if not mode:
        return None
    if mode not in SANITIZE_FLAGS:
        raise ValueError(
            f"PS_NATIVE_SANITIZE={mode!r}: expected one of "
            f"{sorted(SANITIZE_FLAGS)}")
    return mode


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_and_load(src_name: str, extra_flags=()) -> Optional[ctypes.CDLL]:
    """Compile ``native/<src_name>`` with g++ (cached by mtime under
    ``native/_build``) and dlopen it. Returns None — once, latched — if the
    source is missing or the toolchain fails, so callers fall back to pure
    Python. Shared by every native component (wirecodec, psqueue).

    With ``PS_NATIVE_SANITIZE=asan|ubsan|tsan`` the library is built
    with the matching sanitizer into a mode-specific cache directory."""
    mode = sanitize_mode()
    if (src_name, mode) in _BUILD_FAILURES:
        return None
    src = os.path.join(_repo_root(), "native", src_name)
    stem = os.path.splitext(src_name)[0]
    build_dir = os.path.join(_repo_root(), "native", "_build",
                             *([mode] if mode else []))
    so_path = os.path.join(build_dir, f"lib{stem}.so")
    if mode:
        extra_flags = (*extra_flags, *SANITIZE_FLAGS[mode])
    try:
        if not os.path.exists(src):
            raise FileNotFoundError(src)
        os.makedirs(build_dir, exist_ok=True)
        if (not os.path.exists(so_path)
                or os.path.getmtime(so_path) < os.path.getmtime(src)):
            tmp = tempfile.mktemp(suffix=".so", dir=build_dir)
            # -lrt AFTER the source (link order): shm_open lives in librt
            # on pre-2.34 glibc; newer glibc ships a no-op librt. Linux
            # only — other platforms have no librt and the flag would
            # fail the whole build into the silent fallback
            import sys as _sys

            libs = ["-lrt"] if _sys.platform.startswith("linux") else []
            # -ffp-contract=off: the wc_fold_* kernels must not contract
            # multiply+add into an FMA — the numpy fallback computes them
            # as separate f32 ops and the native==numpy bit-exact parity
            # contract (tests/test_native_fold.py) pins that
            cmd = ["g++", "-O3", "-std=c++17", "-ffp-contract=off",
                   "-shared", "-fPIC", *extra_flags, "-o", tmp, src, *libs]
            # scrubbed env: under `make native-asan` the PYTHON process
            # runs with the ASan runtime LD_PRELOADed and leak-checking
            # armed — inherited into g++ that flags the compiler's own
            # exit-time allocations and fails the build
            env = {k: v for k, v in os.environ.items()
                   if k not in ("LD_PRELOAD", "ASAN_OPTIONS",
                                "LSAN_OPTIONS", "UBSAN_OPTIONS",
                                "TSAN_OPTIONS")}
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120, env=env)
            os.replace(tmp, so_path)
        return ctypes.CDLL(so_path)
    except Exception:
        _BUILD_FAILURES.add((src_name, mode))
        return None


def _build_lib() -> Optional[ctypes.CDLL]:
    lib = build_and_load("wirecodec.cpp")
    if lib is None:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.wc_shuffle.argtypes = [u8p, u8p, ctypes.c_size_t, ctypes.c_size_t]
    lib.wc_unshuffle.argtypes = [u8p, u8p, ctypes.c_size_t, ctypes.c_size_t]
    lib.wc_rle0_max_out.argtypes = [ctypes.c_size_t]
    lib.wc_rle0_max_out.restype = ctypes.c_size_t
    lib.wc_rle0_encode.argtypes = [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
    lib.wc_rle0_encode.restype = ctypes.c_size_t
    lib.wc_rle0_decode.argtypes = [u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
    lib.wc_rle0_decode.restype = ctypes.c_size_t
    # fold kernels (absent from a stale cached .so built before they
    # existed — probe one symbol and leave the rest unbound then; the
    # mtime check above rebuilds on any source change, so this only
    # guards a hand-copied old library)
    try:
        f32p = ctypes.POINTER(ctypes.c_float)
        i8p = ctypes.POINTER(ctypes.c_int8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.wc_fold_scaled_i8.argtypes = [f32p, i8p, ctypes.c_float,
                                          ctypes.c_size_t]
        lib.wc_fold_tern.argtypes = [f32p, u8p, ctypes.c_float,
                                     ctypes.c_size_t]
        lib.wc_fold_sign.argtypes = [i32p, u8p, ctypes.c_size_t]
        lib.wc_fold_sparse.argtypes = [f32p, f32p, i32p, ctypes.c_size_t,
                                       ctypes.c_size_t]
        lib.wc_zero_sparse.argtypes = [f32p, i32p, ctypes.c_size_t,
                                       ctypes.c_size_t]
        lib.wc_fold_sparse_q8.argtypes = [f32p, i8p, f32p, i32p,
                                          ctypes.c_size_t, ctypes.c_size_t,
                                          ctypes.c_size_t]
        lib.wc_fold_dense_f32.argtypes = [f32p, f32p, ctypes.c_size_t]
        lib.wc_fold_dense_bf16.argtypes = [f32p, u16p, ctypes.c_size_t]
        lib._has_folds = True
    except AttributeError:
        lib._has_folds = False
    # fold-span capture ring (hop anatomy) — own probe so a stale .so
    # built with folds but before the ring degrades only the ring
    try:
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.wc_abi_fold_span_bytes.argtypes = []
        lib.wc_abi_fold_span_bytes.restype = ctypes.c_uint32
        lib.wc_fold_spans_arm.argtypes = [ctypes.c_uint32]
        lib.wc_fold_spans_arm.restype = ctypes.c_int
        lib.wc_fold_spans_drain.argtypes = [ctypes.POINTER(_FoldSpan),
                                            ctypes.c_uint32, u64p]
        lib.wc_fold_spans_drain.restype = ctypes.c_uint32
        # load-time ABI twin: the native struct size must equal the
        # ctypes mirror's before ANY drain call is allowed
        if int(lib.wc_abi_fold_span_bytes()) != ctypes.sizeof(_FoldSpan):
            raise RuntimeError(
                "FoldSpan ABI drift: wirecodec.cpp packs "
                f"{int(lib.wc_abi_fold_span_bytes())} bytes, the ctypes "
                f"mirror {ctypes.sizeof(_FoldSpan)}")
        lib._has_fold_spans = True
    except AttributeError:
        lib._has_fold_spans = False
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first call; None if the
    toolchain is unavailable (numpy fallbacks take over)."""
    global _lib
    if _lib is None:
        _lib = _build_lib()
    return _lib


def fold_profile_stats() -> Optional[dict]:
    """The wc_fold_* cycle counters (calls / elements / wall ns) — the
    native half of continuous profiling (telemetry.profiler). Reads the
    ALREADY-loaded library only (never triggers a build: a process that
    armed no folds reports nothing, not zeros); None when unavailable
    or built before the counters existed."""
    lib = _lib
    if lib is None or not getattr(lib, "_has_folds", False):
        return None
    if not hasattr(lib, "wc_profile_stats"):
        return None
    calls = ctypes.c_uint64()
    elems = ctypes.c_uint64()
    ns = ctypes.c_uint64()
    lib.wc_profile_stats(ctypes.byref(calls), ctypes.byref(elems),
                         ctypes.byref(ns))
    return {"fold_calls": int(calls.value),
            "fold_elems": int(elems.value),
            "fold_ns": int(ns.value)}


def fold_spans_arm(capacity: int) -> bool:
    """Arm (capacity > 0) or disarm (0) the native per-fold-call span
    ring the hop-anatomy plane drains. Returns True when the ring is
    live. Honors ``PS_NO_NATIVE`` (the Python fallback times folds
    itself); call only from the fold-running thread."""
    if fast_path_disabled():
        return False
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_fold_spans", False):
        return False
    return int(lib.wc_fold_spans_arm(int(capacity))) == 0


def fold_spans_drain(max_spans: int = 4096
                     ) -> Optional[tuple]:
    """Drain the armed span ring: ``([(start_ns, end_ns, elems), ...],
    dropped_count)`` — oldest first, drop counter reset per drain — or
    None when the ring is unavailable. Reads the ALREADY-loaded library
    only, from the fold-running thread (same affinity discipline as
    ``tps_server_read_stats``)."""
    lib = _lib
    if lib is None or not getattr(lib, "_has_fold_spans", False):
        return None
    buf = (_FoldSpan * int(max_spans))()
    dropped = ctypes.c_uint64()
    n = int(lib.wc_fold_spans_drain(buf, int(max_spans),
                                    ctypes.byref(dropped)))
    spans = [(int(buf[i].start_ns), int(buf[i].end_ns), int(buf[i].elems))
             for i in range(n)]
    return spans, int(dropped.value)


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


# -- filters (native with numpy fallback) -----------------------------------

def shuffle(data: np.ndarray, elem_size: int) -> np.ndarray:
    data = np.ascontiguousarray(data, dtype=np.uint8)
    if elem_size <= 0 or data.size % elem_size != 0:
        raise ValueError(f"size {data.size} not divisible by elem_size {elem_size}")
    n = data.size // elem_size
    lib = get_lib()
    if lib is not None:
        out = np.empty_like(data)
        lib.wc_shuffle(_u8(data), _u8(out), n, elem_size)
        return out
    return data.reshape(n, elem_size).T.reshape(-1).copy()


def unshuffle(data: np.ndarray, elem_size: int) -> np.ndarray:
    data = np.ascontiguousarray(data, dtype=np.uint8)
    if elem_size <= 0 or data.size % elem_size != 0:
        raise ValueError(f"size {data.size} not divisible by elem_size {elem_size}")
    n = data.size // elem_size
    lib = get_lib()
    if lib is not None:
        out = np.empty_like(data)
        lib.wc_unshuffle(_u8(data), _u8(out), n, elem_size)
        return out
    return data.reshape(elem_size, n).T.reshape(-1).copy()


def _rle0_encode_np(src: np.ndarray) -> bytes:
    """Numpy fallback of the C encoder (identical format)."""
    out = bytearray()

    def put_varint(v: int):
        while v >= 0x80:
            out.append((v & 0x7F) | 0x80)
            v >>= 7
        out.append(v)

    n = src.size
    i = 0
    is_zero = src == 0
    while i < n:
        zrun = 0
        while i + zrun < n and is_zero[i + zrun]:
            zrun += 1
        lit_start = i + zrun
        lit = 0
        while lit_start + lit < n:
            if is_zero[lit_start + lit]:
                z = 0
                while lit_start + lit + z < n and is_zero[lit_start + lit + z]:
                    z += 1
                if z >= 2:
                    break
            lit += 1
        put_varint(zrun)
        put_varint(lit)
        out += src[lit_start : lit_start + lit].tobytes()
        i = lit_start + lit
    return bytes(out)


def _rle0_decode_np(src: bytes, raw_len: int) -> np.ndarray:
    out = np.empty(raw_len, np.uint8)
    i = 0
    o = 0
    n = len(src)

    def get_varint(i):
        v = 0
        shift = 0
        while True:
            b = src[i]
            v |= (b & 0x7F) << shift
            i += 1
            if not (b & 0x80):
                return v, i
            shift += 7

    while i < n:
        zrun, i = get_varint(i)
        lit, i = get_varint(i)
        out[o : o + zrun] = 0
        o += zrun
        out[o : o + lit] = np.frombuffer(src, np.uint8, lit, i)
        o += lit
        i += lit
    if o != raw_len:
        raise ValueError(f"corrupt rle0 stream: got {o}, want {raw_len}")
    return out


def rle0_encode(data: np.ndarray) -> bytes:
    data = np.ascontiguousarray(data, dtype=np.uint8)
    lib = get_lib()
    if lib is not None:
        cap = lib.wc_rle0_max_out(data.size)
        out = np.empty(cap, np.uint8)
        size = lib.wc_rle0_encode(_u8(data), data.size, _u8(out), cap)
        if size == 0 and data.size > 0:
            raise RuntimeError("rle0 encode capacity overflow")
        return out[:size].tobytes()
    return _rle0_encode_np(data)


def rle0_decode(data: bytes, raw_len: int) -> np.ndarray:
    lib = get_lib()
    if lib is not None:
        src = np.frombuffer(data, np.uint8)
        out = np.empty(raw_len, np.uint8)
        size = lib.wc_rle0_decode(_u8(src), src.size, _u8(out), raw_len)
        if size != raw_len:
            raise ValueError(f"corrupt rle0 stream: got {size}, want {raw_len}")
        return out
    return _rle0_decode_np(data, raw_len)


# -- public compress/decompress (the reference's blosc surface) --------------

def compress(data: bytes, elem_size: int = 4) -> bytes:
    """Shuffle + RLE0 with a CRC32 of the raw bytes. Never expands by more
    than the 18-byte header; if the encoded form would be larger than raw,
    stores raw (elem_size=0 means stored)."""
    raw = np.frombuffer(data, np.uint8)
    crc = zlib.crc32(data) & 0xFFFFFFFF
    if raw.size % max(elem_size, 1) == 0 and elem_size > 1:
        payload = rle0_encode(shuffle(raw, elem_size))
        flags = 1
    else:
        payload = rle0_encode(raw)
        flags = 0
        elem_size = 1
    if len(payload) >= raw.size:  # incompressible: store
        return _HDR.pack(_MAGIC, 0, 0, raw.size, crc) + data
    return _HDR.pack(_MAGIC, elem_size, flags, raw.size, crc) + payload


# -- native fast path (fold kernels + batched ingest) ------------------------
#
# PS_NO_NATIVE=1 force-disables the OPTIONAL native fast paths — the
# wc_fold_* homomorphic fold kernels below and the tcpps batched C++
# frame ingest — proving the pure-Python/numpy fallbacks still carry
# every feature. It does NOT disable the native transports themselves
# (psqueue/tcpps ARE the shm/TCP wire; there is no Python substitute),
# nor the shuffle/rle0 filters above (their numpy fallbacks engage only
# when the toolchain is missing).

def fast_path_disabled() -> bool:
    """True when the ``PS_NO_NATIVE`` env var asks for pure-Python
    fallbacks (any value except empty/``0``/``false``). Read per call:
    tests flip it with monkeypatch."""
    return os.environ.get("PS_NO_NATIVE", "0").strip().lower() not in (
        "", "0", "false")


def fold_lib() -> Optional[ctypes.CDLL]:
    """The wirecodec library with the ``wc_fold_*`` kernels bound, or
    None (``PS_NO_NATIVE`` set, no toolchain, or a stale pre-fold
    cached build) — callers fall back to the numpy fold."""
    if fast_path_disabled():
        return None
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_folds", False):
        return None
    return lib


def _f32(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int8))


def _i32(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def fold_scaled_i8(lib, acc: np.ndarray, q: np.ndarray, scale) -> None:
    """acc += scale * q (int8 payload, f32 accumulator) in one pass."""
    lib.wc_fold_scaled_i8(_f32(acc), _i8(q), ctypes.c_float(float(scale)),
                          acc.size)


def fold_tern(lib, acc: np.ndarray, packed: np.ndarray, scale) -> None:
    """acc += scale * unpack_base4(packed) (terngrad) in one pass."""
    lib.wc_fold_tern(_f32(acc), _u8(packed), ctypes.c_float(float(scale)),
                     acc.size)


def fold_sign(lib, votes: np.ndarray, packed: np.ndarray) -> None:
    """votes += unpacked bits (little bitorder), int32 vote counters."""
    lib.wc_fold_sign(_i32(votes), _u8(packed), votes.size)


def fold_sparse(lib, acc: np.ndarray, values: np.ndarray,
                indices: np.ndarray, acc_ptr=None) -> None:
    """acc[idx] += val scatter-add; out-of-range indices dropped.
    ``acc_ptr`` lets a hot caller reuse a cached ctypes pointer for the
    long-lived accumulator (the data_as conversion is ~µs — real money
    against a 2048-entry scatter)."""
    lib.wc_fold_sparse(acc_ptr if acc_ptr is not None else _f32(acc),
                       _f32(values), _i32(indices),
                       values.size, acc.size)


def zero_sparse(lib, acc: np.ndarray, indices: np.ndarray,
                acc_ptr=None) -> None:
    """acc[idx] = 0 for in-range idx — the pooled-buffer recycle pass."""
    lib.wc_zero_sparse(acc_ptr if acc_ptr is not None else _f32(acc),
                       _i32(indices), indices.size, acc.size)


def fold_sparse_q8(lib, acc: np.ndarray, q: np.ndarray, scales: np.ndarray,
                   indices: np.ndarray, acc_ptr=None) -> None:
    """Dequantized (per-block int8 x scale) scatter-add in one pass."""
    nb = scales.size
    kb = q.size // max(nb, 1)
    lib.wc_fold_sparse_q8(acc_ptr if acc_ptr is not None else _f32(acc),
                          _i8(q), _f32(scales), _i32(indices),
                          nb, kb, acc.size)


def fold_dense_f32(lib, acc: np.ndarray, x: np.ndarray) -> None:
    lib.wc_fold_dense_f32(_f32(acc), _f32(x), acc.size)


def fold_dense_bf16(lib, acc: np.ndarray, x: np.ndarray) -> None:
    lib.wc_fold_dense_bf16(
        _f32(acc), x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        acc.size)


def decompress(blob: bytes) -> bytes:
    magic, elem_size, flags, raw_len, crc = _HDR.unpack_from(blob, 0)
    if magic != _MAGIC:
        raise ValueError("not a wirecodec blob")
    payload = blob[_HDR.size :]
    if elem_size == 0:  # stored
        out_bytes = payload[:raw_len]
    else:
        out = rle0_decode(payload, raw_len)
        if flags & 1:
            out = unshuffle(out, elem_size)
        out_bytes = out.tobytes()
    if (zlib.crc32(out_bytes) & 0xFFFFFFFF) != crc:
        raise ValueError("wirecodec blob failed CRC32 check (corrupt)")
    return out_bytes
