"""torch ↔ JAX pytree interop.

The TPU-native analog of the reference's recursive converters ``to_np`` /
``to_torch`` (``mpi_comms.py:32-58`` — including the Python-3.6-only
``d.cuda(async=True)`` this replaces, SURVEY §2.3): lets a user of the
reference bring their ``torch.nn.Module`` parameters into this framework
(named_parameters → pytree) and read trained values back.

torch is imported lazily — the framework never requires it.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


def torch_params_to_pytree(named_params: Iterable[Tuple[str, Any]]) -> Dict[str, jax.Array]:
    """``model.named_parameters()`` → flat {name: jnp array} pytree (the
    reference's constructor input shape, ``ps.py:54-63``)."""
    out = {}
    for name, p in named_params:
        out[name] = jnp.asarray(p.detach().cpu().numpy())
    return out


def pytree_to_torch_params(tree: Dict[str, jax.Array], model: Any) -> None:
    """Write a {name: array} pytree back into a torch module's parameters
    in place (the read-back direction of ``to_torch``,
    ``mpi_comms.py:46-58``)."""
    import torch

    named = dict(model.named_parameters())
    missing = set(tree) - set(named)
    if missing:
        raise KeyError(f"params not in model: {sorted(missing)}")
    with torch.no_grad():
        for name, arr in tree.items():
            named[name].copy_(torch.from_numpy(np.asarray(arr)))


def to_np(tree: PyTree) -> PyTree:
    """Recursive to-numpy over dict/list pytrees (``mpi_comms.py:32-43``),
    torch tensors included."""
    def leaf(x):
        if hasattr(x, "detach"):
            return x.detach().cpu().numpy()
        return np.asarray(x)
    return jax.tree.map(leaf, tree)


def to_jnp(tree: PyTree, dtype=None) -> PyTree:
    """Recursive to-jax (``to_torch``'s mirror, ``mpi_comms.py:46-58``)."""
    def leaf(x):
        if hasattr(x, "detach"):
            x = x.detach().cpu().numpy()
        arr = jnp.asarray(x)
        return arr.astype(dtype) if dtype is not None else arr
    return jax.tree.map(leaf, tree)
