"""Profiler integration: the deep-dive layer above the per-step metrics
dicts (SURVEY §5.1's disposition: keep the reference's returned-timings
contract and add ``jax.profiler`` traces for what host clocks can't see
inside a fused XLA program)."""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard/Perfetto:

    >>> with trace('/tmp/jax-trace'):
    ...     opt.step(loss_fn=loss_fn, batch=batch)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a trace (shows up on the timeline)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats() -> Optional[dict]:
    """Per-device HBM stats where the backend exposes them."""
    try:
        dev = jax.devices()[0]
        return dev.memory_stats()
    except Exception:
        return None
