"""Profiler integration: the deep-dive layer above the per-step metrics
dicts (SURVEY §5.1's disposition: keep the reference's returned-timings
contract and add ``jax.profiler`` traces for what host clocks can't see
inside a fused XLA program)."""

from __future__ import annotations

import collections
import contextlib
import glob
import os
import shutil
import tempfile
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard/Perfetto:

    >>> with trace('/tmp/jax-trace'):
    ...     opt.step(loss_fn=loss_fn, batch=batch)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a trace (shows up on the timeline)."""
    with jax.profiler.TraceAnnotation(name):
        yield


# HLO/primitive names that are interconnect work. Covers both the jax
# primitive names XLA:CPU surfaces (``psum.7``) and the HLO collective op
# names TPU planes use (``all-reduce-start.1`` etc.).
_COMM_SUBSTRINGS = (
    "psum", "all-reduce", "allreduce", "all-gather", "allgather",
    "reduce-scatter", "reducescatter", "collective", "ppermute",
    "all-to-all", "alltoall",
)


def _interval_union(intervals):
    """Merge [start, end) intervals; returns disjoint sorted list."""
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _interval_intersection_len(a, b):
    """Total length of the intersection of two DISJOINT-SORTED interval
    lists (outputs of :func:`_interval_union`)."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _iter_hlo_events(trace_dir: str):
    """Yield ``(device, name, start_ns, dur_ns)`` for every device op
    execution (events carrying an ``hlo_op`` stat) in a trace dir.

    Reader selection: ``jax.profiler.ProfileData`` where the jax build
    ships it; otherwise the dependency-free wire-format fallback in
    ``utils/xplane.py`` (older jax writes the same ``xplane.pb`` files
    but provides no reader)."""
    for f in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True):
        if not hasattr(jax.profiler, "ProfileData"):
            from pytorch_ps_mpi_tpu.utils import xplane

            try:
                yield from xplane.iter_hlo_events(f)
            except Exception:
                pass
            continue
        try:
            pd = jax.profiler.ProfileData.from_file(f)
        except Exception:
            continue
        for plane in pd.planes:
            for line in plane.lines:
                for e in line.events:
                    dur = e.duration_ns or 0.0
                    if dur <= 0:
                        continue
                    st = dict(e.stats)
                    if "hlo_op" not in st:
                        continue
                    dev = st.get("device_ordinal", plane.name)
                    yield dev, str(e.name), float(e.start_ns or 0.0), dur


def _participant_lanes(events):
    """The execution lanes that PARTICIPATED in the traced program.

    An HLO collective instruction name is unique within its module
    (SSA), so the set of lanes (devices / executor threads) that
    emitted an execution event for it is exactly the collective's
    participant set — the same number the lowered program's
    collective-launch counters (``bucketing.count_collectives``, one
    launch executed once per participant) predict.  Counting distinct
    LANES (not events) stays correct when a loop executes the same
    collective several times per lane.  With no collective events,
    every lane counts.

    Returns ``(participant_lanes, all_lanes)``; callers restrict the
    comm/compute interval math to the participants so host-side result
    -fetch programs (which also carry ``hlo_op`` stats on jax 0.4.x
    CPU) cannot dilute the per-device means."""
    by_name: Dict[str, set] = {}
    lanes_all = set()
    for dev, name, _start, _dur in events:
        lanes_all.add(dev)
        if any(s in name.lower() for s in _COMM_SUBSTRINGS):
            by_name.setdefault(name, set()).add(dev)
    if by_name:
        widest = max(by_name.values(), key=len)
        return set(widest), lanes_all
    return set(lanes_all), lanes_all


def _launch_derived_devices(events, lowered) -> int:
    """Fallback participant count when the trace carries NO per-lane
    attribution at all (every event on one merged lane): divide the
    trace's collective-event count by the lowered program's
    collective-launch count (``bucketing.count_collectives``) — one
    launch executes once per participant, so for a single traced run
    ``events / launches`` IS the participant count.  Returns 0 when it
    cannot be derived (no lowered text, no collectives)."""
    if lowered is None:
        return 0
    try:
        text = lowered() if callable(lowered) else lowered
        from pytorch_ps_mpi_tpu.bucketing import count_collectives

        launches = int(count_collectives(text)["total"])
    except Exception:
        return 0
    if launches <= 0:
        return 0
    comm_events = sum(
        1 for _dev, name, _s, _d in events
        if any(s in name.lower() for s in _COMM_SUBSTRINGS))
    return comm_events // launches if comm_events >= launches else 0


def profiled_overlap(thunk: Callable[[], Any]) -> Tuple[Any, Dict[str, Any]]:
    """Run ``thunk()`` once under the profiler and measure how much of
    the communication time actually EXECUTES CONCURRENTLY with compute —
    the timeline-level fact :func:`profiled_device_split` (duration sums)
    cannot see, and the reference's signature design claim (encode/comm
    overlapped with backprop via hooks + a 200-thread pool,
    ``/root/reference/ps.py:65-66,85``) that this framework delegates to
    XLA's scheduler (VERDICT r3 item 3).

    Per device: union the [start, end) intervals of collective ops
    (``_COMM_SUBSTRINGS``) and of every other device op, then intersect.
    Returns ``(out, d)`` with per-device MEANS in seconds: ``comm_s``/
    ``compute_s`` (union lengths, so a thread blocked inside one psum
    event counts once), ``overlap_s`` (comm∩compute), ``overlap_frac``
    (overlap_s / comm_s — 1.0 means every comm nanosecond rode under
    compute), ``busy_union_s`` (comm∪compute — the device's critical
    path through this step), and ``serial_equiv_s`` (comm_s + compute_s
    — what the step would cost with zero overlap). ``devices=0`` when
    the backend emits no device events."""
    d = tempfile.mkdtemp(prefix="jaxtrace_")
    try:
        jax.profiler.start_trace(d)
        try:
            out = thunk()
            jax.block_until_ready(out)
        finally:
            jax.profiler.stop_trace()
        events = list(_iter_hlo_events(d))
        lanes, _all = _participant_lanes(events)
        comm_iv: Dict[Any, list] = collections.defaultdict(list)
        comp_iv: Dict[Any, list] = collections.defaultdict(list)
        for dev, name, start, dur in events:
            if dev not in lanes:
                continue  # host-side fetch lane, not a participant
            tgt = comm_iv if any(
                s in name.lower() for s in _COMM_SUBSTRINGS
            ) else comp_iv
            tgt[dev].append((start, start + dur))
        devs = sorted(set(comm_iv) | set(comp_iv), key=str)
        n = len(devs)
        if not n:
            return out, {"devices": 0, "comm_s": 0.0, "compute_s": 0.0,
                         "overlap_s": 0.0, "overlap_frac": 0.0,
                         "busy_union_s": 0.0, "serial_equiv_s": 0.0}
        comm = compute = overlap = busy = 0.0
        for dev in devs:
            cu = _interval_union(comm_iv.get(dev, []))
            pu = _interval_union(comp_iv.get(dev, []))
            comm += sum(e - s for s, e in cu)
            compute += sum(e - s for s, e in pu)
            overlap += _interval_intersection_len(cu, pu)
            busy += sum(e - s for s, e in _interval_union(
                list(comm_iv.get(dev, [])) + list(comp_iv.get(dev, []))
            ))
        scale = 1e9 * n
        comm_s, compute_s = comm / scale, compute / scale
        overlap_s = overlap / scale
        return out, {
            "devices": n,
            "comm_s": comm_s,
            "compute_s": compute_s,
            "overlap_s": overlap_s,
            "overlap_frac": overlap_s / comm_s if comm_s > 0 else 0.0,
            "busy_union_s": busy / scale,
            "serial_equiv_s": comm_s + compute_s,
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def profiled_device_split(
    thunk: Callable[[], Any], *, lowered=None,
) -> Tuple[Any, Dict[str, Any]]:
    """Run ``thunk()`` once under the JAX profiler and split *device* op
    time into communication vs compute.

    This measures the real fused program — the split host wall-clocks
    around separate sub-programs (``MPI_PS`` ``instrument=True``)
    structurally cannot see, because splitting the program changes what
    XLA can overlap. Only events carrying an ``hlo_op`` stat (device op
    executions) are counted; host-side compile/dispatch events have no
    ``hlo_op`` and are excluded, so tracing a first (compiling) call
    still yields a clean device split.

    Returns ``(thunk result, split)`` where split has per-device *mean*
    seconds: ``device_busy_s``, ``comm_s``, ``compute_s``, plus
    ``devices`` and the ``top_ops`` time sinks. Empty split (zeros,
    ``devices=0``) when the backend emits no device events (some
    remote/tunneled backends do not support tracing).

    ``devices`` is the measured PARTICIPANT count: the lanes that
    executed the program's collectives (per-device planes on real
    backends, per-executor-thread lines on XLA:CPU where jax 0.4.x
    attributes no ``device_ordinal``).  ``lowered`` — the lowered
    program text, or a zero-arg callable producing it — arms the
    launch-counter fallback: on a build whose trace carries NO per-lane
    attribution at all, the participant count is derived as collective
    trace events over lowered collective launches
    (``bucketing.count_collectives``) instead of being misreported
    as 1.
    """
    d = tempfile.mkdtemp(prefix="jaxtrace_")
    try:
        jax.profiler.start_trace(d)
        try:
            out = thunk()
            jax.block_until_ready(out)
        finally:
            jax.profiler.stop_trace()
        events = list(_iter_hlo_events(d))
        lanes, _all = _participant_lanes(events)
        per_dev: Dict[Any, list] = collections.defaultdict(lambda: [0.0, 0.0])
        top: collections.Counter = collections.Counter()
        for dev, name, _start, dur in events:
            if dev not in lanes:
                continue  # host-side fetch lane, not a participant
            per_dev[dev][1] += dur
            top[name] += dur
            if any(s in name.lower() for s in _COMM_SUBSTRINGS):
                per_dev[dev][0] += dur
        ndev = len(per_dev)
        if ndev == 1:
            est = _launch_derived_devices(events, lowered)
            if est > 1:
                # merged-lane trace: the interval sums cover every
                # participant already, so the launch-derived count is
                # both the honest ``devices`` and the mean denominator
                ndev = est
        scale = 1e9 * max(1, ndev)
        comm = sum(v[0] for v in per_dev.values()) / scale
        busy = sum(v[1] for v in per_dev.values()) / scale
        return out, {
            "devices": ndev,
            "device_busy_s": busy,
            "comm_s": comm,
            "compute_s": busy - comm,
            "top_ops": [
                (name, ns / 1e9) for name, ns in top.most_common(8)
            ],
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def device_memory_stats() -> Optional[dict]:
    """Per-device HBM stats where the backend exposes them."""
    try:
        dev = jax.devices()[0]
        return dev.memory_stats()
    except Exception:
        return None
