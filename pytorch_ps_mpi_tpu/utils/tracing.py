"""Profiler integration: the deep-dive layer above the per-step metrics
dicts (SURVEY §5.1's disposition: keep the reference's returned-timings
contract and add ``jax.profiler`` traces for what host clocks can't see
inside a fused XLA program)."""

from __future__ import annotations

import collections
import contextlib
import glob
import os
import shutil
import tempfile
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard/Perfetto:

    >>> with trace('/tmp/jax-trace'):
    ...     opt.step(loss_fn=loss_fn, batch=batch)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a trace (shows up on the timeline)."""
    with jax.profiler.TraceAnnotation(name):
        yield


# HLO/primitive names that are interconnect work. Covers both the jax
# primitive names XLA:CPU surfaces (``psum.7``) and the HLO collective op
# names TPU planes use (``all-reduce-start.1`` etc.).
_COMM_SUBSTRINGS = (
    "psum", "all-reduce", "allreduce", "all-gather", "allgather",
    "reduce-scatter", "reducescatter", "collective", "ppermute",
    "all-to-all", "alltoall",
)


def profiled_device_split(thunk: Callable[[], Any]) -> Tuple[Any, Dict[str, Any]]:
    """Run ``thunk()`` once under the JAX profiler and split *device* op
    time into communication vs compute.

    This measures the real fused program — the split host wall-clocks
    around separate sub-programs (``MPI_PS`` ``instrument=True``)
    structurally cannot see, because splitting the program changes what
    XLA can overlap. Only events carrying an ``hlo_op`` stat (device op
    executions) are counted; host-side compile/dispatch events have no
    ``hlo_op`` and are excluded, so tracing a first (compiling) call
    still yields a clean device split.

    Returns ``(thunk result, split)`` where split has per-device *mean*
    seconds: ``device_busy_s``, ``comm_s``, ``compute_s``, plus
    ``devices`` and the ``top_ops`` time sinks. Empty split (zeros,
    ``devices=0``) when the backend emits no device events (some
    remote/tunneled backends do not support tracing).
    """
    d = tempfile.mkdtemp(prefix="jaxtrace_")
    try:
        jax.profiler.start_trace(d)
        try:
            out = thunk()
            jax.block_until_ready(out)
        finally:
            jax.profiler.stop_trace()
        per_dev: Dict[Any, list] = collections.defaultdict(lambda: [0.0, 0.0])
        top: collections.Counter = collections.Counter()
        for f in glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True):
            try:
                pd = jax.profiler.ProfileData.from_file(f)
            except Exception:
                continue
            for plane in pd.planes:
                for line in plane.lines:
                    for e in line.events:
                        dur = e.duration_ns or 0.0
                        if dur <= 0:
                            continue
                        st = dict(e.stats)
                        if "hlo_op" not in st:
                            continue
                        dev = st.get("device_ordinal", plane.name)
                        nm = str(e.name).lower()
                        per_dev[dev][1] += dur
                        top[str(e.name)] += dur
                        if any(s in nm for s in _COMM_SUBSTRINGS):
                            per_dev[dev][0] += dur
        ndev = len(per_dev)
        scale = 1e9 * max(1, ndev)
        comm = sum(v[0] for v in per_dev.values()) / scale
        busy = sum(v[1] for v in per_dev.values()) / scale
        return out, {
            "devices": ndev,
            "device_busy_s": busy,
            "comm_s": comm,
            "compute_s": busy - comm,
            "top_ops": [
                (name, ns / 1e9) for name, ns in top.most_common(8)
            ],
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def device_memory_stats() -> Optional[dict]:
    """Per-device HBM stats where the backend exposes them."""
    try:
        dev = jax.devices()[0]
        return dev.memory_stats()
    except Exception:
        return None
