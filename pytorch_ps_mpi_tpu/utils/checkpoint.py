"""Checkpoint / resume — absent in the reference (SURVEY §5.4: optimizer
state lived in ``torch.optim.Optimizer.state`` and ``state_dict()`` was
never called). Here it's first-class: Orbax sharded checkpoints of the
full training pytree (params + optimizer state + codec state + step),
with a plain-numpy fallback when Orbax is unavailable.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

PyTree = Any

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    ocp = None
    _HAVE_ORBAX = False

from pytorch_ps_mpi_tpu.utils.serialization import load_pytree, save_pytree


class CheckpointManager:
    """Minimal step-indexed checkpoint manager.

    ``save(step, state)`` / ``restore(template, step=None)`` where state is
    any pytree (typically ``{'params':…, 'opt_state':…, 'step':…}``).
    """

    def __init__(self, directory: str, use_orbax: bool = True, max_to_keep: int = 3,
                 compress: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.use_orbax = use_orbax and _HAVE_ORBAX
        self.max_to_keep = max_to_keep
        self.compress = compress  # numpy fallback: native wire codec
        if self.use_orbax:
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
            )

    def save(self, step: int, state: PyTree) -> None:
        if self.use_orbax:
            self._mgr.save(step, args=ocp.args.StandardSave(state))
            self._mgr.wait_until_finished()
        else:
            save_pytree(
                os.path.join(self.directory, f"ckpt_{step}.npz"), state,
                compress=self.compress,
            )
            self._gc()

    def latest_step(self) -> Optional[int]:
        if self.use_orbax:
            return self._mgr.latest_step()
        steps = self._numpy_steps()
        return steps[-1] if steps else None

    def restore(self, template: PyTree, step: Optional[int] = None) -> PyTree:
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        if self.use_orbax:
            return self._mgr.restore(step, args=ocp.args.StandardRestore(template))
        return load_pytree(
            os.path.join(self.directory, f"ckpt_{step}.npz"), template
        )

    def _numpy_steps(self):
        steps = []
        for f in os.listdir(self.directory):
            if f.startswith("ckpt_") and f.endswith(".npz"):
                steps.append(int(f[len("ckpt_"):-len(".npz")]))
        return sorted(steps)

    def _gc(self):
        steps = self._numpy_steps()
        for s in steps[: -self.max_to_keep]:
            os.remove(os.path.join(self.directory, f"ckpt_{s}.npz"))
