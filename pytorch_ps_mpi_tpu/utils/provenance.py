"""Committed-TPU-artifact recall for the round-record bench.

The axon TPU tunnel flaps: it can be down at the single moment the driver
runs ``bench.py`` while a full TPU sweep sits committed in
``benchmarks/results/*.jsonl`` (captured by ``tools/tpu_watch.py`` during
an earlier liveness window). The round record must carry the measured TPU
truth regardless of tunnel state (VERDICT r3 item 1) — the reference's
entire measured surface is its per-step timing schema
(``/root/reference/ps.py:116-148``), and a CPU-fallback line says nothing
about it.

This module is the pure, testable half: scan the committed artifact files
*and* the watcher's append-only log, keep records that were actually
executed on a TPU backend, pick the newest per metric, and build the
summary line ``bench.py`` emits last on a CPU-fallback run. Every
re-emitted line is tagged ``provenance: "watcher <timestamp>"`` and
``age_hours`` so a stale number can never masquerade as a live one.
"""

from __future__ import annotations

import glob
import json
import os
from datetime import datetime
from typing import Iterable

# Metrics worth re-emitting on fallback: the aggregation latency (the
# reference's whole job) and every MFU-bearing train-step line.
_KEY_SUBSTRINGS = ("grad_aggregation", "train_step")


def _parse_ts(s: str) -> datetime | None:
    """Best-effort ISO timestamp out of 'tpu_watch sweep 2026-07-30T06:02:46'
    or a bare '2026-07-30T06:02:46'."""
    for tok in str(s).split():
        try:
            return datetime.fromisoformat(tok)
        except ValueError:
            continue
    return None


def _records_from_jsonl_line(line: str, default_ts: str | None) -> Iterable[dict]:
    line = line.strip()
    if not line:
        return
    try:
        rec = json.loads(line)
    except json.JSONDecodeError:
        return
    if not isinstance(rec, dict):
        return
    # Watcher stage records wrap a whole bench run's stdout: unwrap each
    # inner JSON line, stamping the stage's own timestamp on it.
    if "stage" in rec and "stdout" in rec:
        for inner in str(rec["stdout"]).splitlines():
            yield from _records_from_jsonl_line(inner, rec.get("ts", default_ts))
        return
    if rec.get("replayed"):
        # a replayed line is a COPY of an older measurement: if a
        # CPU-fallback bench run's stdout gets wrapped into the watcher
        # log, re-ingesting the copy with the wrapper's fresh timestamp
        # would let a stale number masquerade as the newest (echo loop)
        return
    if rec.get("backend") == "tpu":
        if "captured_by" not in rec and default_ts:
            rec["captured_by"] = f"watcher {default_ts}"
        yield rec


def load_tpu_records(repo_root: str) -> list[dict]:
    """All TPU-executed records from committed artifacts + the watcher log."""
    paths = sorted(glob.glob(os.path.join(repo_root, "benchmarks", "results", "*.jsonl")))
    watch = os.path.join(repo_root, "BENCH_TPU_WATCH.jsonl")
    if os.path.exists(watch):
        paths.append(watch)
    out: list[dict] = []
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    out.extend(_records_from_jsonl_line(line, None))
        except OSError:
            continue
    return out


def newest_per_metric(records: Iterable[dict]) -> dict[str, dict]:
    """Newest record per metric name, by captured_by timestamp (records
    without a parseable timestamp lose to any that have one)."""
    best: dict[str, tuple[datetime, dict]] = {}
    epoch = datetime(1970, 1, 1)
    for rec in records:
        metric = rec.get("metric")
        if not metric:
            continue
        ts = _parse_ts(rec.get("captured_by", "")) or epoch
        cur = best.get(metric)
        if cur is None or ts >= cur[0]:
            best[metric] = (ts, rec)
    return {m: r for m, (_, r) in best.items()}


def _num(x) -> float | None:
    try:
        return float(x)
    except (TypeError, ValueError):
        return None


def _age_hours(rec: dict, now: datetime) -> float | None:
    ts = _parse_ts(rec.get("captured_by", ""))
    if ts is None:
        return None
    return round((now - ts).total_seconds() / 3600.0, 1)


def fallback_record_lines(repo_root: str, now: datetime | None = None) -> list[dict]:
    """The lines a CPU-fallback ``bench.py`` run appends: each key TPU
    metric re-emitted with provenance, then one summary line (emitted
    last so the driver's last-line parse lands on TPU numbers).

    Returns [] when no TPU artifact exists anywhere — in that case there
    is genuinely no TPU truth to carry and fabricating one is worse.
    """
    now = now or datetime.now()
    # Plausibility gates: MFU >= 1 is physically impossible — such records
    # are pre-RTT-correction measurement bugs still sitting in the watcher
    # log (the scan-hoisting artifact VERDICT r3 weak #3 describes for
    # powersgd also inflated early bert lines). A value <= 0 on a rate
    # metric is a failed capture (devtime zero-clamp; the committed
    # bert bf16 0.0 row, VERDICT r4 weak #5) — a real step is never free.
    # Never recall either.
    records = [
        r for r in load_tpu_records(repo_root)
        if "error" not in r  # errored rows are provenance, not truth
        and not ((m := _num(r.get("mfu"))) is not None and m >= 1.0)
        and not ((v := _num(r.get("value"))) is not None and v <= 0.0)
    ]
    newest = newest_per_metric(records)
    key = {
        m: r for m, r in newest.items()
        if any(s in m for s in _KEY_SUBSTRINGS)
    }
    if not key:
        return []
    lines: list[dict] = []
    for metric in sorted(key):
        rec = dict(key[metric])
        ts = _parse_ts(rec.get("captured_by", ""))
        rec["provenance"] = (
            f"watcher {ts.isoformat()}" if ts else "committed artifact (undated)"
        )
        age = _age_hours(rec, now)
        if age is not None:
            rec["age_hours"] = age
        # `backend: tpu` states which backend EXECUTED the measurement;
        # `replayed: true` states that THIS bench run merely recalled it.
        # Both are true; consumers distinguish live-vs-recalled on the
        # `replayed` key (bench.py's module docstring documents this).
        rec["replayed"] = True
        rec["record_source"] = "committed TPU artifact re-emitted on CPU fallback"
        lines.append(rec)

    agg = next((key[m] for m in sorted(key) if "grad_aggregation" in m), None)
    mfu_recs = [r for r in key.values() if (_num(r.get("mfu")) or 0.0) > 0.0]
    best_mfu = max(mfu_recs, key=lambda r: _num(r["mfu"])) if mfu_recs else None
    summary: dict = {
        "metric": "tpu_record_summary",
        "backend": "tpu",
        "replayed": True,
        "record_source": (
            "newest committed TPU measurements (benchmarks/results/*.jsonl + "
            "BENCH_TPU_WATCH.jsonl); live backend this run was the host CPU "
            "(tunnel down), so the round record re-emits the measured TPU "
            "truth with provenance instead of reporting nothing"
        ),
    }
    if agg is not None:
        summary["value"] = agg.get("value")
        summary["unit"] = agg.get("unit", "ms")
        summary["aggregation_ms"] = agg.get("value")
        summary["vs_baseline"] = agg.get("vs_baseline")
        summary["aggregation_metric"] = agg.get("metric")
    if best_mfu is not None:
        summary["mfu"] = _num(best_mfu.get("mfu"))
        summary["mfu_metric"] = best_mfu.get("metric")
        summary["steps_per_sec"] = best_mfu.get("value")
        if agg is None:  # keep the value/unit contract every line honors
            summary["value"] = best_mfu.get("value")
            summary["unit"] = best_mfu.get("unit", "steps/sec")
    if "value" not in summary:  # key lines existed but carried neither
        summary["value"] = 0.0
        summary["unit"] = "none"
    # provenance/age_hours describe the records that actually FEED the
    # summary's headline fields (agg + best_mfu): the headline is as
    # stale as its oldest contributor — stamping the newest recalled
    # record here once understated a 13.9h-old headline as 2h fresh.
    # The bound over every recalled key line rides under its own name.
    contributing = [r for r in (agg, best_mfu) if r is not None] or list(
        key.values()
    )
    c_ts = [t for t in (_parse_ts(r.get("captured_by", ""))
                        for r in contributing) if t]
    c_ages = [a for a in (_age_hours(r, now) for r in contributing)
              if a is not None]
    if c_ts:
        summary["provenance"] = f"watcher {min(c_ts).isoformat()}"
    if c_ages:
        summary["age_hours"] = max(c_ages)
    all_ages = [a for a in (_age_hours(r, now) for r in key.values())
                if a is not None]
    if all_ages:
        summary["oldest_record_age_hours"] = max(all_ages)
    lines.append(summary)
    return lines
