"""JAX version compatibility shims.

The codebase targets the current ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=...)`` API. Older jax builds (<= 0.4.x, e.g. the
0.4.37 in some CI containers) only ship
``jax.experimental.shard_map.shard_map`` with the same semantics under the
pre-rename ``check_rep`` flag. Installing the alias here keeps every call
site on the one modern spelling instead of scattering try/excepts.
"""

from __future__ import annotations

import jax


def ensure_shard_map() -> None:
    """Install ``jax.shard_map`` on jax builds that predate the alias.
    No-op when the real thing exists (never shadows it)."""
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma  # pre-rename spelling
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kw)

    jax.shard_map = shard_map


def ensure_axis_size() -> None:
    """Install ``jax.lax.axis_size`` (static mapped-axis size; newer-jax
    API) on builds that predate it — ``jax.core.axis_frame(name)``
    returns the same static int there."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return
    import jax.core as core

    def axis_size(axis_name):
        names = (axis_name if isinstance(axis_name, (tuple, list))
                 else (axis_name,))
        out = 1
        for n in names:
            out *= int(core.axis_frame(n))
        return out

    lax.axis_size = axis_size
