"""Minimal XSpace (``*.xplane.pb``) reader — no proto deps.

``jax.profiler.ProfileData`` (the supported xplane reader) only exists
on newer jax builds; older ones (<= 0.4.x) write the same ``xplane.pb``
files but give you nothing to read them with, and this container's
tensorboard profile plugin ships no python xplane proto either. The
format is stable protobuf wire encoding of the XSpace schema
(tsl/profiler/protobuf/xplane.proto), and the subset observability needs
— planes → lines → events, plus the event/stat metadata string tables —
is small enough to decode by hand:

  XSpace.planes=1
  XPlane{ id=1 name=2 lines=3 event_metadata=4 stat_metadata=5 }
  XLine{ id=1 name=2 timestamp_ns=3 events=4 }
  XEvent{ metadata_id=1 offset_ps=2 duration_ps=3 stats=4 }
  XStat{ metadata_id=1 double=2 uint64=3 int64=4 str=5 bytes=6 ref=7 }
  X*Metadata{ id=1 name=2 }

Used as the fallback behind ``utils.tracing._iter_hlo_events`` (device
comm/compute split, merged Perfetto export). Unknown fields are skipped
by wire type, so schema growth does not break the reader.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Tuple

_WT_VARINT, _WT_I64, _WT_LEN, _WT_I32 = 0, 1, 2, 5


def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _skip(buf: bytes, i: int, wt: int) -> int:
    if wt == _WT_VARINT:
        return _varint(buf, i)[1]
    if wt == _WT_I64:
        return i + 8
    if wt == _WT_LEN:
        n, i = _varint(buf, i)
        return i + n
    if wt == _WT_I32:
        return i + 4
    raise ValueError(f"unsupported wire type {wt}")


def _fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over one message's bytes;
    LEN fields yield raw bytes, varints ints, fixed widths raw bytes."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == _WT_VARINT:
            v, i = _varint(buf, i)
            yield fno, wt, v
        elif wt == _WT_LEN:
            ln, i = _varint(buf, i)
            yield fno, wt, buf[i:i + ln]
            i += ln
        else:
            j = _skip(buf, i, wt)
            yield fno, wt, buf[i:j]
            i = j


def _metadata_names(entries: List[bytes]) -> Dict[int, str]:
    """map<int64, X{Event,Stat}Metadata> → {id: name}. Each entry is a
    MapEntry{ key=1, value=2 } whose value holds { id=1, name=2 }."""
    out: Dict[int, str] = {}
    for entry in entries:
        key, name = 0, ""
        for fno, wt, v in _fields(entry):
            if fno == 1 and wt == _WT_VARINT:
                key = v
            elif fno == 2 and wt == _WT_LEN:
                for f2, w2, v2 in _fields(v):
                    if f2 == 2 and w2 == _WT_LEN:
                        name = v2.decode("utf-8", "replace")
        out[key] = name
    return out


def _event_stats(ev_stats: List[bytes],
                 stat_names: Dict[int, str]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for raw in ev_stats:
        mid, val = 0, None
        for fno, wt, v in _fields(raw):
            if fno == 1 and wt == _WT_VARINT:
                mid = v
            elif fno in (3, 4, 7) and wt == _WT_VARINT:
                # uint64 / int64 / ref (ref resolves through the same
                # stat-name table — the profiler interns hlo names there)
                val = stat_names.get(v, v) if fno == 7 else v
            elif fno == 5 and wt == _WT_LEN:
                val = v.decode("utf-8", "replace")
            elif fno == 2:
                import struct

                val = struct.unpack("<d", v)[0] if len(v) == 8 else None
        name = stat_names.get(mid)
        if name:
            out[name] = val
    return out


def iter_plane_events(
    path: str,
) -> Iterator[Tuple[str, str, float, float, Dict[str, Any]]]:
    """Yield ``(plane_name, event_name, start_ns, dur_ns, stats)`` for
    every event in every plane of one ``xplane.pb`` file.  The line
    (execution thread) the event sits on rides ``stats["_line"]`` —
    XLA:CPU runs each virtual device's program on its own executor
    thread, so on builds whose events carry no ``device_ordinal`` stat
    the line is the only per-participant attribution the trace has."""
    with open(path, "rb") as f:
        space = f.read()
    for fno, wt, plane_buf in _fields(space):
        if fno != 1 or wt != _WT_LEN:
            continue
        plane_name = ""
        lines: List[bytes] = []
        emd_raw: List[bytes] = []
        smd_raw: List[bytes] = []
        for pf, pw, pv in _fields(plane_buf):
            if pf == 2 and pw == _WT_LEN:
                plane_name = pv.decode("utf-8", "replace")
            elif pf == 3 and pw == _WT_LEN:
                lines.append(pv)
            elif pf == 4 and pw == _WT_LEN:
                emd_raw.append(pv)
            elif pf == 5 and pw == _WT_LEN:
                smd_raw.append(pv)
        event_names = _metadata_names(emd_raw)
        stat_names = _metadata_names(smd_raw)
        for line_buf in lines:
            t0_ns = 0
            line_id = 0
            line_name = ""
            events: List[bytes] = []
            for lf, lw, lv in _fields(line_buf):
                if lf == 1 and lw == _WT_VARINT:
                    line_id = lv
                elif lf == 2 and lw == _WT_LEN:
                    line_name = lv.decode("utf-8", "replace")
                elif lf == 3 and lw == _WT_VARINT:
                    t0_ns = lv
                elif lf == 4 and lw == _WT_LEN:
                    events.append(lv)
            for ev_buf in events:
                mid = offset_ps = dur_ps = 0
                ev_stats: List[bytes] = []
                for ef, ew, evv in _fields(ev_buf):
                    if ef == 1 and ew == _WT_VARINT:
                        mid = evv
                    elif ef == 2 and ew == _WT_VARINT:
                        offset_ps = evv
                    elif ef == 3 and ew == _WT_VARINT:
                        dur_ps = evv
                    elif ef == 4 and ew == _WT_LEN:
                        ev_stats.append(evv)
                stats = _event_stats(ev_stats, stat_names)
                stats["_line"] = line_name or str(line_id)
                yield (
                    plane_name,
                    event_names.get(mid, str(mid)),
                    t0_ns + offset_ps / 1e3,
                    dur_ps / 1e3,
                    stats,
                )


def iter_hlo_events(path: str):
    """The ``_iter_hlo_events`` contract from one file: ``(lane, name,
    start_ns, dur_ns)`` for device op executions (events carrying an
    ``hlo_op`` stat).  The lane is the ``device_ordinal`` stat where
    the build provides one, else the (plane, line) pair — on jax
    0.4.x's XLA:CPU the events carry no per-device stat but each
    virtual device executes on its own ``tf_XLATfrtCpuClient/*``
    thread line, so the line IS the participant."""
    for plane, name, start_ns, dur_ns, stats in iter_plane_events(path):
        if dur_ns <= 0 or "hlo_op" not in stats:
            continue
        dev = stats.get("device_ordinal")
        if dev is None:
            dev = f"{plane}/{stats.get('_line', '')}"
        yield dev, name, float(start_ns), float(dur_ns)
