"""Per-step timing/metrics — the reference's observability surface.

The reference returned a hand-rolled wall-clock dict from every ``step``
(``ps.py:116-148,191``; ``igather``'s dict ``mpi_comms.py:90-93``). These
helpers keep that contract ergonomic, and ``jax.profiler`` covers what
host wall-clocks can't see inside a fused XLA program.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, List


class StepTimer:
    """Accumulates named wall-clock segments into a dict.

    >>> t = StepTimer()
    >>> with t("comm_wait"): ...
    >>> t.data
    {'comm_wait': 0.0123}

    Subsumed by the telemetry FlightRecorder's span API: when the
    run-wide recorder is enabled, every segment is ALSO recorded as a
    span there, so legacy StepTimer call sites join the unified
    timeline for free. The dict contract stays (the reference's
    returned-timings schema rides on it).
    """

    def __init__(self):
        self.data: Dict[str, float] = {}

    @contextlib.contextmanager
    def __call__(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        t0_mono = time.monotonic()  # recorder spans stamp their START
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.data[name] = self.data.get(name, 0.0) + dt
            from pytorch_ps_mpi_tpu.telemetry import get_recorder

            rec = get_recorder()
            if rec is not None:
                rec.event(name, kind="span", ts=t0_mono, dur=dt)


def print_summary(obj, _depth: int = 0) -> str:
    """One-line human summary of a nested dict/list, arrays shown as
    shapes — the reference's debug printer (``mpi_comms.py:176-184``)."""
    if isinstance(obj, dict):
        inner = ", ".join(f"{k}: {print_summary(v, _depth + 1)}" for k, v in obj.items())
        out = "{" + inner + "}"
    elif isinstance(obj, (list, tuple)):
        out = "[" + ", ".join(print_summary(v, _depth + 1) for v in obj) + "]"
    elif hasattr(obj, "shape") and getattr(obj, "ndim", 0) > 0:
        out = f"array{tuple(obj.shape)}"
    else:
        out = repr(obj)
    if _depth == 0:
        print(out)
    return out


class MetricsAccumulator:
    """Collects per-step dicts; reports means (the host-side analog of the
    reference's ``data`` list the caller was expected to keep)."""

    def __init__(self):
        self._rows: List[Dict[str, float]] = []

    def add(self, row: Dict[str, float]) -> None:
        self._rows.append(dict(row))

    def mean(self) -> Dict[str, float]:
        sums: Dict[str, float] = defaultdict(float)
        counts: Dict[str, int] = defaultdict(int)
        for row in self._rows:
            for k, val in row.items():
                if isinstance(val, str):  # e.g. wire_lowering label
                    continue
                sums[k] += val
                counts[k] += 1
        return {k: sums[k] / counts[k] for k in sums}

    def __len__(self) -> int:
        return len(self._rows)
