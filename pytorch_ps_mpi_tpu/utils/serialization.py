"""Host-side wire format: typed pytree pack/unpack.

Replaces the reference's pickle(+blosc) object shipping
(``mpi_comms.py:186-193``, and the abandoned zero-copy experiment in its
``serialization.py``): gradients/params are pytrees of typed arrays, so
the wire format is (flat byte buffer, static spec) — no pickling of code
objects, no sentinel framing (the ``0x29`` collision bug, SURVEY §2.3),
and the spec is exchanged once, not per message. On-device nothing here is
needed at all; this is for host I/O (checkpoints, cross-process metadata).
"""

from __future__ import annotations

import json
from typing import Any, List, Tuple

import jax
import numpy as np

PyTree = Any


def _spec_of(leaves: List[np.ndarray]) -> List[dict]:
    return [{"shape": list(x.shape), "dtype": str(x.dtype)} for x in leaves]


def pack_arrays_into(out_u8: np.ndarray, arrays, offset: int = 0) -> int:
    """Copy each array's bytes into ``out_u8`` (a uint8 buffer view) at
    sequential offsets — ONE memcpy per array, no intermediate bytes
    objects. Returns the end offset. The one packing loop shared by
    :func:`pack_pytree` and the codec wire (``parallel/dcn.CodecWire``)."""
    for x in arrays:
        x = np.asarray(x)
        n = x.nbytes
        out_u8[offset:offset + n] = (
            np.ascontiguousarray(x).reshape(-1).view(np.uint8)
        )
        offset += n
    return offset


def read_arrays(buf, specs, copy: bool = True, offset: int = 0):
    """Read ``[(shape, dtype), ...]`` sequentially from a bytes-like
    buffer through one ``memoryview`` (no per-item slice copies).
    ``copy=False`` returns zero-copy views valid only while ``buf``
    lives. Raises :class:`ValueError` naming both sizes when the buffer
    is shorter than the specs demand. Shared by :func:`unpack_pytree`
    and the codec wire."""
    dims = []
    needed = offset
    for shape, dtype in specs:
        dtype = np.dtype(dtype)
        shape = tuple(shape)
        count = int(np.prod(shape)) if shape else 1
        dims.append((dtype, shape, count))
        needed += count * dtype.itemsize
    mv = memoryview(buf)
    if mv.nbytes < needed:
        raise ValueError(
            f"truncated buffer: specs describe {needed} bytes "
            f"({len(dims)} arrays), got {mv.nbytes}"
        )
    out = []
    for dtype, shape, count in dims:
        arr = np.frombuffer(mv, dtype=dtype, count=count,
                            offset=offset).reshape(shape)
        out.append(arr.copy() if copy else arr)
        offset += count * dtype.itemsize
    return out


def pack_pytree(tree: PyTree) -> Tuple[bytearray, str]:
    """Flatten a pytree of arrays into one contiguous byte buffer plus a
    JSON spec (shapes/dtypes + treedef). Inverse: :func:`unpack_pytree`.

    The buffer is built in ONE preallocated ``bytearray`` with each leaf
    copied exactly once into its final offset — the old
    ``b"".join(tobytes())`` form copied every leaf twice (tobytes
    materializes a per-leaf bytes object, the join copies again), which at
    checkpoint scale doubles both the transient memory and the memcpy
    traffic. The returned bytearray is bytes-like everywhere a wire/file
    API wants one; call ``bytes(buf)`` only if immutability is required.
    """
    leaves, treedef = jax.tree.flatten(tree)
    np_leaves = [np.asarray(x) for x in leaves]
    total = sum(x.nbytes for x in np_leaves)
    buf = bytearray(total)
    spec = json.dumps({"leaves": _spec_of(np_leaves), "treedef": str(treedef)})
    if total:
        pack_arrays_into(np.frombuffer(buf, np.uint8), np_leaves)
    return buf, spec


def unpack_pytree(buf, spec: str, treedef=None, template: PyTree = None,
                  copy: bool = True):
    """Rebuild arrays from :func:`pack_pytree` output. Pass either the
    ``treedef`` or a ``template`` pytree with the target structure.

    Reads through a single ``memoryview`` — no per-leaf
    ``buf[offset:offset+n]`` slice copies. ``copy=True`` (default) returns
    independent writable arrays; ``copy=False`` returns zero-copy views
    into ``buf`` (read-only when ``buf`` is immutable ``bytes``) — the
    checkpoint-load fast path, valid only while ``buf`` is kept alive and
    unmodified.

    A buffer shorter than the spec demands raises :class:`ValueError`
    naming both sizes (previously it surfaced as an opaque downstream
    ``reshape`` failure).
    """
    meta = json.loads(spec)
    leaves = read_arrays(
        buf,
        [(m["shape"], m["dtype"]) for m in meta["leaves"]],
        copy=copy,
    )
    if treedef is None:
        if template is None:
            raise ValueError("need treedef or template")
        treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, leaves)


def save_pytree(path: str, tree: PyTree, compress: bool = False) -> None:
    """Write a pytree to ``path``. ``compress=True`` runs each leaf's bytes
    through the native wire codec (shuffle+RLE0+CRC, ``utils/native.py``) —
    the in-repo replacement for the reference's pickle+blosc checkpoint-ish
    path (``mpi_comms.py:186-193``)."""
    leaves, treedef = jax.tree.flatten(tree)
    if compress:
        from pytorch_ps_mpi_tpu.utils import native

        arrays = {}
        for i, x in enumerate(leaves):
            arr = np.asarray(x)
            blob = native.compress(arr.tobytes(), elem_size=arr.dtype.itemsize)
            arrays[f"leaf_{i}"] = np.frombuffer(blob, np.uint8)
        arrays["__compressed__"] = np.ones(1, np.uint8)
    else:
        arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    np.savez(
        path,
        __treedef__=np.frombuffer(str(treedef).encode(), dtype=np.uint8),
        **arrays,
    )


def load_pytree(path: str, template: PyTree) -> PyTree:
    """Read arrays saved by :func:`save_pytree` into ``template``'s
    structure (transparently decompressing if saved with
    ``compress=True``)."""
    tmpl_leaves, treedef = jax.tree.flatten(template)
    with np.load(path) as data:
        compressed = "__compressed__" in data.files
        n_meta = 2 if compressed else 1
        n = len(data.files) - n_meta
        if treedef.num_leaves != n:
            raise ValueError(
                f"template has {treedef.num_leaves} leaves, file has {n}"
            )
        if compressed:
            from pytorch_ps_mpi_tpu.utils import native

            leaves = []
            for i, t in enumerate(tmpl_leaves):
                raw = native.decompress(data[f"leaf_{i}"].tobytes())
                # template leaves may be plain python scalars (an
                # optimizer state_dict carries step_count as an int):
                # coerce ONLY those — np.asarray on an array leaf would
                # device->host copy every sharded param just to read its
                # dtype (and raise on non-addressable multi-host arrays)
                if hasattr(t, "dtype"):
                    dt, shp = np.dtype(t.dtype), np.shape(t)
                else:
                    scalar = np.asarray(t)
                    dt, shp = scalar.dtype, scalar.shape
                leaves.append(np.frombuffer(raw, dt).reshape(shp))
        else:
            leaves = [data[f"leaf_{i}"] for i in range(n)]
    return jax.tree.unflatten(treedef, leaves)
