"""Host-side wire format: typed pytree pack/unpack.

Replaces the reference's pickle(+blosc) object shipping
(``mpi_comms.py:186-193``, and the abandoned zero-copy experiment in its
``serialization.py``): gradients/params are pytrees of typed arrays, so
the wire format is (flat byte buffer, static spec) — no pickling of code
objects, no sentinel framing (the ``0x29`` collision bug, SURVEY §2.3),
and the spec is exchanged once, not per message. On-device nothing here is
needed at all; this is for host I/O (checkpoints, cross-process metadata).
"""

from __future__ import annotations

import json
from typing import Any, List, Tuple

import jax
import numpy as np

PyTree = Any


def _spec_of(leaves: List[np.ndarray]) -> List[dict]:
    return [{"shape": list(x.shape), "dtype": str(x.dtype)} for x in leaves]


def pack_pytree(tree: PyTree) -> Tuple[bytes, str]:
    """Flatten a pytree of arrays into one contiguous byte buffer plus a
    JSON spec (shapes/dtypes + treedef). Inverse: :func:`unpack_pytree`."""
    leaves, treedef = jax.tree.flatten(tree)
    np_leaves = [np.asarray(x) for x in leaves]
    buf = b"".join(x.tobytes() for x in np_leaves)
    spec = json.dumps({"leaves": _spec_of(np_leaves), "treedef": str(treedef)})
    return buf, spec


def unpack_pytree(buf: bytes, spec: str, treedef=None, template: PyTree = None):
    """Rebuild arrays from :func:`pack_pytree` output. Pass either the
    ``treedef`` or a ``template`` pytree with the target structure."""
    meta = json.loads(spec)
    leaves = []
    offset = 0
    for leaf_meta in meta["leaves"]:
        dtype = np.dtype(leaf_meta["dtype"])
        shape = tuple(leaf_meta["shape"])
        nbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        n = max(nbytes, dtype.itemsize)
        arr = np.frombuffer(buf[offset : offset + n], dtype=dtype).reshape(shape)
        leaves.append(arr)
        offset += n
    if treedef is None:
        if template is None:
            raise ValueError("need treedef or template")
        treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, leaves)


def save_pytree(path: str, tree: PyTree, compress: bool = False) -> None:
    """Write a pytree to ``path``. ``compress=True`` runs each leaf's bytes
    through the native wire codec (shuffle+RLE0+CRC, ``utils/native.py``) —
    the in-repo replacement for the reference's pickle+blosc checkpoint-ish
    path (``mpi_comms.py:186-193``)."""
    leaves, treedef = jax.tree.flatten(tree)
    if compress:
        from pytorch_ps_mpi_tpu.utils import native

        arrays = {}
        for i, x in enumerate(leaves):
            arr = np.asarray(x)
            blob = native.compress(arr.tobytes(), elem_size=arr.dtype.itemsize)
            arrays[f"leaf_{i}"] = np.frombuffer(blob, np.uint8)
        arrays["__compressed__"] = np.ones(1, np.uint8)
    else:
        arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    np.savez(
        path,
        __treedef__=np.frombuffer(str(treedef).encode(), dtype=np.uint8),
        **arrays,
    )


def load_pytree(path: str, template: PyTree) -> PyTree:
    """Read arrays saved by :func:`save_pytree` into ``template``'s
    structure (transparently decompressing if saved with
    ``compress=True``)."""
    tmpl_leaves, treedef = jax.tree.flatten(template)
    with np.load(path) as data:
        compressed = "__compressed__" in data.files
        n_meta = 2 if compressed else 1
        n = len(data.files) - n_meta
        if treedef.num_leaves != n:
            raise ValueError(
                f"template has {treedef.num_leaves} leaves, file has {n}"
            )
        if compressed:
            from pytorch_ps_mpi_tpu.utils import native

            leaves = []
            for i, t in enumerate(tmpl_leaves):
                raw = native.decompress(data[f"leaf_{i}"].tobytes())
                # template leaves may be plain python scalars (an
                # optimizer state_dict carries step_count as an int):
                # coerce ONLY those — np.asarray on an array leaf would
                # device->host copy every sharded param just to read its
                # dtype (and raise on non-addressable multi-host arrays)
                if hasattr(t, "dtype"):
                    dt, shp = np.dtype(t.dtype), np.shape(t)
                else:
                    scalar = np.asarray(t)
                    dt, shp = scalar.dtype, scalar.shape
                leaves.append(np.frombuffer(raw, dt).reshape(shp))
        else:
            leaves = [data[f"leaf_{i}"] for i in range(n)]
    return jax.tree.unflatten(treedef, leaves)
