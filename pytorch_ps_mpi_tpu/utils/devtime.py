"""Honest device timing on remote/tunneled JAX backends.

The axon TPU backend on this machine is fully asynchronous AND its
``block_until_ready`` is effectively a local no-op — a 4096³ bf16 matmul
"completes" in 24 µs (5700 TFLOP/s, 29× the chip's peak) if you trust
it. The only operation that genuinely waits for device completion is a
*value fetch* (``device_get`` of data dependent on the computation),
which costs one tunnel round-trip (~68 ms here, measured).

Correct recipe, validated against a known-FLOPs control (4096³ bf16
matmul chain → 191 TFLOP/s = 97% of v5e peak):

1. measure the fetch RTT floor on a tiny *already-computed* array;
2. run K dependent steps fused in one ``lax.scan`` program, then fetch
   one scalar element of the result (forces the whole chain);
3. device time per step = (wall − rtt_floor) / K.

``timed(fn, args, k)`` returns both the per-call wall (what a user of
this tunneled chip actually waits, RTT included) and the K-amortized
device seconds (what the silicon spends — the number comparable across
backends and to rooflines).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np

_RTT: float | None = None

# Below this fetch RTT the backend is effectively synchronous: per-call
# wall IS device time and the scanned pass is skipped. The ONE constant
# both timed() and its callers' provenance labels consult.
RTT_SCAN_THRESHOLD = 1e-3


def scan_pass_runs() -> bool:
    """True iff :func:`timed` will run (and subtract-RTT-amortize) the
    scanned pass on this backend — callers labeling methodology must use
    this, not a re-derived threshold."""
    return rtt_floor() >= RTT_SCAN_THRESHOLD

# bf16 peak FLOP/s per JAX device, keyed by device_kind substring
# (lowercased) — the single table every benchmark's MFU is reported
# against (v3 entry is per core; 2 cores/chip).
PEAK_FLOPS_BF16 = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 61.25e12),
    ("v2", 22.5e12),
]


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def peak_flops_for(kind: str | None = None) -> float:
    kind = (kind if kind is not None else device_kind()).lower()
    for sub, peak in PEAK_FLOPS_BF16:
        if sub in kind:
            return peak
    return 0.0


def safe_ratio(num: float, den: float) -> float:
    """num/den, or 0.0 when the denominator is 0 — which ``timed``'s
    zero-clamp legitimately produces when RTT jitter exceeds the k-step
    signal. A 0.0 ratio reads as "not measured", never crashes a sweep."""
    return num / den if den > 0 else 0.0


def fetch_sync(out: Any) -> None:
    """Force *real* completion of ``out`` by fetching one scalar element
    of its first ARRAY leaf (a data-dependent host read — the only sync
    primitive the tunneled backend honors). Host-scalar leaves (Python
    floats mixed into a metrics pytree) are skipped — syncing on one of
    those would await nothing."""
    leaf = next(
        (l for l in jax.tree.leaves(out) if hasattr(l, "ndim")), None
    )
    if leaf is None:
        return  # no array leaves: nothing on device to await
    np.asarray(jax.device_get(leaf[(0,) * leaf.ndim] if leaf.ndim else leaf))


def _rtt_sample(x) -> float:
    t0 = time.perf_counter()
    fetch_sync(x)
    return time.perf_counter() - t0


def rtt_floor(reps: int = 10) -> float:
    """Measured cost of fetching a scalar from an already-computed
    device array: the per-fetch overhead to subtract from amortized
    timings. Cached per process — use ONLY for the is-this-backend-
    remote decision (:func:`scan_pass_runs`); subtraction must use an
    RTT co-measured with the timing window (the tunnel RTT swings tens
    of ms with host load, so a process-start floor subtracted from a
    later window can swallow or inflate the whole signal)."""
    global _RTT
    if _RTT is None:
        import jax.numpy as jnp

        x = jnp.ones((8, 8))
        fetch_sync(x)
        _RTT = min(_rtt_sample(x) for _ in range(reps))
    return _RTT


# RTT actually subtracted by the most recent windowed measurement, for
# benchmark provenance labels (the cached rtt_floor() can drift from it
# by tens of ms with host load).
LAST_WINDOW_RTT: float | None = None


def rtt_subtracted_ms() -> float | None:
    """RTT in ms actually subtracted by the most recent windowed
    measurement (None before any ran) — emit THIS next to device times,
    not the process-start ``rtt_floor``, so readers can reconcile
    ``wall − rtt ≈ k * device_per_step`` exactly."""
    return (
        round(LAST_WINDOW_RTT * 1e3, 2) if LAST_WINDOW_RTT is not None
        else None
    )


def _windowed_min(timed_call: Callable[[], float], reps: int) -> Tuple[float, float]:
    """(min wall of ``timed_call``, min RTT) with the RTT samples
    interleaved rep-by-rep in the SAME window, so load drift between
    process start and measurement cannot skew the subtraction."""
    global LAST_WINDOW_RTT
    import jax.numpy as jnp

    x = jnp.ones((8, 8))
    fetch_sync(x)
    walls, rtts = [], []
    for _ in range(reps):
        rtts.append(_rtt_sample(x))
        walls.append(timed_call())
        rtts.append(_rtt_sample(x))
    LAST_WINDOW_RTT = min(rtts)
    return min(walls), LAST_WINDOW_RTT


def timed(
    call: Callable[[], Any],
    scanned_call: Callable[[], Any],
    k: int,
    reps: int = 5,
) -> Tuple[float, float]:
    """(per-call wall seconds incl. fetch, per-step device seconds).

    ``call()`` runs one step; ``scanned_call()`` runs ``k`` dependent
    steps in one program (callers build it with ``lax.scan``). Warm-up
    (compile) of both is handled HERE — callers must not pre-run
    ``scanned_call`` themselves, because on a backend with a negligible
    fetch RTT (< 1 ms — the host CPU fallback, where block/fetch are
    genuinely synchronous) the scanned pass is skipped entirely: per-call
    wall already IS device time, and even one warm-up execution of a
    k-step program would multiply an already-slow fallback's wall clock
    for no information.
    """
    fetch_sync(call())  # compile + warm
    # per-call wall never subtracts RTT (it reports what a user waits),
    # so a plain min-of-reps needs no co-measured floor
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch_sync(call())
        ts.append(time.perf_counter() - t0)
    per_call = min(ts)
    if not scan_pass_runs():
        return per_call, per_call
    fetch_sync(scanned_call())  # compile + warm (only when it will run)

    def one_scan():
        t0 = time.perf_counter()
        fetch_sync(scanned_call())
        return time.perf_counter() - t0

    wall, rtt = _windowed_min(one_scan, max(3, reps // 2))
    device_per_step = max(0.0, wall - rtt) / k
    return per_call, device_per_step


def scan_timed(loop_call: Callable[[], Any], k: int, reps: int = 3) -> float:
    """Device seconds per step of a pre-compiled k-step fused loop:
    min-of-reps wall with one scalar fetch, minus a co-measured RTT
    floor, over k. Returns 0.0 when the signal is below the RTT noise
    floor (guard divisions with :func:`safe_ratio`)."""
    fetch_sync(loop_call())  # warm / ensure compiled

    def one():
        t0 = time.perf_counter()
        fetch_sync(loop_call())
        return time.perf_counter() - t0

    wall, rtt = _windowed_min(one, reps)
    return max(0.0, wall - rtt) / k


def codec_roundtrip_seconds(code, shape, dtype, k: Optional[int] = None,
                            phase: str = "roundtrip") -> float:
    """Device seconds for one ``encode`` + ``decode`` of a codec at
    ``shape`` — a k-iteration fused scan whose iterations carry a
    numerically-negligible data dependence (``+ decoded * 1e-30``) AND
    loop-carry the codec state, so XLA can neither hoist the codec out of
    the loop nor dead-code the stateful half (PowerSGD's warm-started Q,
    error-feedback residuals, adaptive thresholds). A loop-invariant
    state once let the best-compressing codec measure 0.0 ms at 132M
    (VERDICT r3 weak #3) — and steady-state cost with an evolving Q is
    what a training step actually pays anyway. The one shared
    implementation of the honest codec timing recipe (bench consumers
    must not re-roll it).

    ``k=None`` picks the scan length ADAPTIVELY: a coarse k=8 estimate
    sizes the real run so the total signal is ≥ ~20 ms, far above the
    tunnel's RTT jitter. A fixed small k once measured the same kernel
    anywhere between 0.05 ms and 1.3 ms run-to-run (a 3 ms signal under
    ±2 ms jitter), flipping which of two implementations looked faster.
    k is snapped to {8, 64, 512} so the compilation cache holds across
    runs.

    ``phase='encode'`` times the encode half alone (decode cost is then
    the roundtrip minus this). The carry dependence switches to a full
    reduction over every payload leaf — a first-element dependence would
    let XLA slice-fuse away most of the encode, while a jnp.sum forces
    full payload materialization at the cost of one extra payload read
    per iteration (negligible: the encode itself writes those bytes)."""
    import jax.numpy as jnp

    if phase not in ("roundtrip", "encode"):
        raise ValueError(f"phase={phase!r}: expected 'roundtrip' or 'encode'")
    g = jax.random.normal(jax.random.key(0), shape, dtype)
    st = code.init_state(shape, dtype)
    rng = jax.random.key(1) if code.needs_rng else None

    def make_loop(length):
        @jax.jit
        def loop(g, st):
            def body(carry, _):
                g_c, st_c = carry
                payload, st_new = code.encode(g_c, st_c, rng)
                if phase == "encode":
                    dep = sum(
                        jnp.sum(leaf).astype(g_c.dtype)
                        for leaf in jax.tree.leaves(payload)
                    )
                else:
                    dep = code.decode(payload, shape, dtype).astype(g_c.dtype)
                g_next = g_c + dep * jnp.asarray(1e-30, g_c.dtype)
                return (g_next, st_new), None

            (out, st_out), _ = jax.lax.scan(body, (g, st), None, length=length)
            # return the state too: the fetch syncs on `out`, but keeping
            # st_out live in the program output closes the last
            # dead-code-elimination door for state-only compute
            return out, st_out

        return loop

    if k is not None:
        loop = make_loop(k)
        return scan_timed(lambda: loop(g, st), k)
    if not scan_pass_runs():  # synchronous backend: no jitter to outrun
        loop = make_loop(8)
        return scan_timed(lambda: loop(g, st), 8)
    coarse = make_loop(8)
    est = scan_timed(lambda: coarse(g, st), 8)
    target = 0.020  # seconds of total signal
    for kk in (8, 64, 512):
        if est * kk >= target or kk == 512:
            break
    if kk == 8:
        return est
    loop = make_loop(kk)
    return scan_timed(lambda: loop(g, st), kk)
