"""Guard against a wedged accelerator transport.

The axon TPU plugin on this machine can hang indefinitely on the first
device op when its tunnel is down, and it ignores the ``JAX_PLATFORMS``
env var — so benchmark entry points probe device health in a subprocess
under a hard timeout and pin the process to the CPU backend (via
``jax.config``, which the plugin does respect) when the probe fails.
"""

from __future__ import annotations

import os
import subprocess
import sys


def enable_compilation_cache(path: str = "/tmp/jax_comp_cache") -> None:
    """Persistent compiled-program cache shared by the repo's entry points
    — significant when the TPU backend compiles remotely. Safe no-op on
    JAX versions lacking the config knobs."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


def ensure_live_backend(timeout: float | None = None) -> bool:
    """Run one trivial device op in a subprocess under ``timeout`` seconds
    (default: ``$BENCH_PROBE_TIMEOUT`` or 240). On failure, switch this
    process to the CPU backend so callers always complete.

    Must be called before the current process initializes its JAX
    backend. Returns True if the default backend is live.
    """
    import jax

    if timeout is None:
        timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
    try:
        subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; jax.block_until_ready(jax.numpy.ones((8, 8)))",
            ],
            timeout=timeout,
            check=True,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return True
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        print(
            f"backend probe: accelerator unresponsive after {timeout:.0f}s; "
            "falling back to CPU",
            file=sys.stderr,
        )
        jax.config.update("jax_platforms", "cpu")
        return False
