"""Guard against a wedged accelerator transport.

The axon TPU plugin on this machine can hang indefinitely on the first
device op when its tunnel is down, and it ignores the ``JAX_PLATFORMS``
env var — so benchmark entry points probe device health in a subprocess
under a hard timeout and pin the process to the CPU backend (via
``jax.config``, which the plugin does respect) when the probe fails.
"""

from __future__ import annotations

import os
import subprocess
import sys


def enable_compilation_cache(path: str = "/tmp/jax_comp_cache") -> None:
    """Persistent compiled-program cache shared by the repo's entry points
    — significant when the TPU backend compiles remotely. Safe no-op on
    JAX versions lacking the config knobs."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except (AttributeError, ValueError) as e:  # missing knobs on old JAX
        print(f"compilation cache not enabled: {e}", file=sys.stderr)


def size_virtual_cpu_mesh(n: int) -> None:
    """Size the host-CPU virtual device pool to >= ``n`` — call BEFORE
    anything initializes the backend (a no-op afterwards: JAX reads the
    knob once). The ONE implementation of the new-knob-try /
    XLA-flag-fallback dance the example CLIs and the dryrun entry all
    need (three hand-copied variants had already drifted)."""
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
    except (RuntimeError, AttributeError):
        # RuntimeError: backend already initialized (caller's devices
        # stand). AttributeError: older JAX without the knob — the XLA
        # flag works as long as the backend has not initialized yet.
        if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""
        ):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n}"
            )


def ensure_live_backend(
    timeout: float | None = None, retries: int | None = None
) -> bool:
    """Probe the accelerator with one trivial device op in a subprocess,
    retrying up to ``retries`` extra times of ``timeout`` seconds each
    (defaults: ``$BENCH_PROBE_TIMEOUT`` or 420 s, ``$BENCH_PROBE_RETRIES``
    or 1 — i.e. up to 14 minutes of patience, because the axon TPU tunnel
    can take minutes to come up). Only after every attempt fails is the
    process pinned to the CPU backend so callers always complete.

    Must be called before the current process initializes its JAX
    backend. Returns True if the default (accelerator) backend is live —
    callers MUST surface this (plus ``jax.default_backend()``) in any
    reported numbers so a CPU-fallback run can never masquerade as a TPU
    result (VERDICT r1 item 1).
    """
    import jax

    # already pinned to the host platform (e.g. the test conftest) —
    # there is no accelerator to probe, and a probe subprocess would try
    # the axon plugin anyway (it ignores the JAX_PLATFORMS env var) and
    # hang the caller for the full timeout. Only the PRIMARY platform
    # counts: this environment's ambient value is 'axon,cpu' (cpu as the
    # fallback entry), and a substring test silently skipped the probe
    # AND the pin — callers then hung on the dead tunnel's first op.
    pinned = str(jax.config.jax_platforms or "")
    if pinned.split(",")[0].strip() == "cpu":
        return False

    if timeout is None:
        timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "420"))
    if retries is None:
        retries = int(os.environ.get("BENCH_PROBE_RETRIES", "1"))
    attempts = 1 + max(0, retries)
    for attempt in range(attempts):
        try:
            out = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; jax.block_until_ready(jax.numpy.ones((8, 8)));"
                    "print(jax.default_backend())",
                ],
                timeout=timeout,
                check=True,
                capture_output=True,
                text=True,
            )
            print(
                f"backend probe: live ({out.stdout.strip()}, "
                f"attempt {attempt + 1}/{attempts})",
                file=sys.stderr,
            )
            return True
        except subprocess.TimeoutExpired:
            print(
                f"backend probe: no response after {timeout:.0f}s "
                f"(attempt {attempt + 1}/{attempts})",
                file=sys.stderr,
            )
        except subprocess.CalledProcessError as e:
            print(
                f"backend probe: probe process failed "
                f"(attempt {attempt + 1}/{attempts}): {e.stderr[-500:]}",
                file=sys.stderr,
            )
    print(
        f"backend probe: accelerator unresponsive after {attempts} x "
        f"{timeout:.0f}s; falling back to CPU",
        file=sys.stderr,
    )
    jax.config.update("jax_platforms", "cpu")
    return False
