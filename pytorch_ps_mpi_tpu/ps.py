"""``MPI_PS`` / ``SGD`` / ``Adam`` — the drop-in distributed-optimizer API.

The TPU-native rebuild of the reference's ``ps.py``: an optimizer-style
object whose ``step`` (1) obtains per-worker gradients, (2) encodes them
through a pluggable codec, (3) exchanges them across workers with on-chip
collectives, (4) decodes + sums, and (5) applies a fused SGD/Adam update —
returning ``(loss, data)`` where ``data`` is the per-step timing/bytes
metrics dict (the reference's contract, ``ps.py:193``; schema keys
``ps.py:116-148``).

What changed architecturally (SURVEY §3.1 vs. this file):

- The reference overlapped encode with backprop via autograd hooks feeding
  a 200-thread pool (``ps.py:65-66,85,98-101``). Here the *whole* pipeline
  — grad, encode, collective, decode, update — is one XLA program per step;
  where the backend emits async collectives (TPU/GPU), the compiler
  overlaps them with the remaining backward compute — the TPU-native form
  of the same optimization, with no threads, futures, or GIL reasoning
  (the races of SURVEY §5.2 are gone by construction). This is measured,
  not assumed: ``benchmarks/overlap_bench.py`` traces the fused step and
  reports the comm∩compute timeline fraction
  (``utils.tracing.profiled_overlap``); on the XLA:CPU test backend the
  collective thunks are synchronous and the measured overlap is 0.0 —
  the committed artifact quantifies exactly where the claim does and
  does not hold.
- The two-phase size exchange (``prepare``/``Iallgatherv``,
  ``ps.py:140-147``) is compile-time: payload shapes are static.
- The per-parameter reverse-order receive loop (``ps.py:155-176``)
  becomes a tree-mapped collective; XLA schedules transfers.
- Both reference topologies are kept: ``mode='allgather'`` is the live
  decentralized path (every rank decodes+steps redundantly, ``ps.py:75``);
  ``mode='leader'`` is the rank-0 PS path (gather→step-on-leader→broadcast,
  ``mpi_comms.py:60-133``, README pseudo-code), lowered TPU-natively as a
  ZeRO-1 sharded-optimizer step: per-leaf reduce_scatter of the summed
  gradient, each worker updates only its 1/world shard (owning that
  shard's optimizer state AND the master parameter copy, see
  :class:`LeaderState`), then all_gather the updated shards. Same
  numerics, but update FLOPs and optimizer-state memory divide by world
  size instead of every rank redundantly stepping the full model.

Async (AsySG-InCon) training lives in ``parallel/async_ps.py``.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_ps_mpi_tpu import comms
from pytorch_ps_mpi_tpu.bucketing import (
    BucketPlan,
    flatten_into_buckets,
    plan_buckets,
    unflatten_from_buckets,
)
from pytorch_ps_mpi_tpu.codecs import Codec, ErrorFeedback, IdentityCodec
from pytorch_ps_mpi_tpu.telemetry import get_recorder
from pytorch_ps_mpi_tpu.mesh import DATA_AXIS, make_mesh
from pytorch_ps_mpi_tpu.optim import (
    OPTIMIZERS,
    AdafactorState,
    adafactor_check_sharding,
    adafactor_state_specs,
    adafactor_update,
)

PyTree = Any


def _tree_bytes(tree: PyTree) -> int:
    """Total raw bytes of a pytree's arrays (the reference's ``_bytes_of``,
    ``ps.py:25-43`` — without its self-documented 2-D bug, SURVEY §2.3)."""
    return sum(
        int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize
        for x in jax.tree.leaves(tree)
    )


def _spec_axes(spec) -> Tuple[str, ...]:
    """Flattened mesh-axis names a PartitionSpec shards over (in spec
    order); () for a replicated leaf."""
    out = []
    for entry in tuple(spec or ()):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.extend(entry)
        else:
            out.append(entry)
    return tuple(out)


def _local_shape(shape, spec, mesh: Mesh) -> Tuple[int, ...]:
    """Per-device shard shape of a leaf with PartitionSpec ``spec`` on
    ``mesh`` (each sharded dim divided by its mesh-axis size)."""
    shape = list(shape)
    for i, entry in enumerate(tuple(spec or ())):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        for a in axes:
            n = int(mesh.shape[a])
            if shape[i] % n:
                raise ValueError(
                    f"dim {i} of shape {tuple(shape)} is not divisible by "
                    f"mesh axis {a!r} (size {n})"
                )
            shape[i] //= n
    return tuple(shape)


class LeaderState(NamedTuple):
    """Optimizer state for ``mode='leader'`` (ZeRO-1): each worker owns a
    1/world shard of every parameter (``param_shards`` leaves are
    ``[world, shard_len]``, partitioned over the mesh) plus the matching
    shard of the inner optimizer state. The master copy of the parameters
    lives HERE, sharded — the replicated ``MPI_PS.params`` is the
    all-gathered working copy for the forward pass, re-derived every step
    (so reassigning ``opt.params`` directly is overwritten; go through
    ``load_state_dict``)."""

    param_shards: Any
    inner: Any


def _to_shards(x: jax.Array, world: int) -> jax.Array:
    """ravel + zero-pad to a multiple of ``world`` + reshape so row r is
    worker r's shard (the layout ``lax.psum_scatter``/``all_gather``
    tiled=True use)."""
    flat = jnp.ravel(x)
    ss = -(-flat.shape[0] // world)
    return jnp.pad(flat, (0, ss * world - flat.shape[0])).reshape(world, ss)


def leader_init_state(
    params: PyTree, init_state: Callable, world: int,
    param_specs: Optional[PyTree] = None, mesh: Optional[Mesh] = None,
) -> LeaderState:
    """Host-side construction of the sharded leader (ZeRO-1) state: the
    master param shards plus the inner optimizer state, leaves stacked
    ``[world, shard_len]`` for a ``P(axis)`` sharding.

    With ``param_specs`` (model-parallel composition): a model-sharded
    leaf — REQUIRED to follow the leading-shard-axis convention, spec
    ``P(model_axis)`` on dim 0 only (``parallel/tp.py``'s layout) — is
    raveled PER model shard and data-scattered within it, stacked
    ``[world * n_model, shard_len]`` data-major for a
    ``P((data, *model_axes))`` joint sharding: each (data, model) device
    owns the ZeRO-1 shard of its own model shard."""
    struct = jax.tree.structure(params)
    if param_specs is None:
        factors = [1] * struct.num_leaves
        shards = jax.tree.map(lambda p: _to_shards(p, world), params)
    else:
        spec_leaves = struct.flatten_up_to(param_specs)

        def build(p, sp):
            axes = _spec_axes(sp)
            if not axes:
                return _to_shards(p, world), 1
            nm = int(np.prod([mesh.shape[a] for a in axes]))
            per = p.reshape(nm, -1)       # [n_model, local_numel]
            ss = -(-per.shape[1] // world)
            per = jnp.pad(per, ((0, 0), (0, ss * world - per.shape[1])))
            # data-major layout matches P((data, *model)) linearization
            per = per.reshape(nm, world, ss).transpose(1, 0, 2)
            return per.reshape(world * nm, ss), nm

        built = [build(p, sp)
                 for p, sp in zip(jax.tree.leaves(params), spec_leaves)]
        shards = jax.tree.unflatten(struct, [b[0] for b in built])
        factors = [b[1] for b in built]

    shard_tmpl = jax.tree.map(lambda s: jnp.zeros(s.shape[1:], s.dtype), shards)
    inner = init_state(shard_tmpl)
    tmpl_struct = jax.tree.structure(shard_tmpl)
    tmpl_shapes = [x.shape for x in jax.tree.leaves(shard_tmpl)]

    def bcast_field(val):
        leaves_v = jax.tree.leaves(val)
        if (jax.tree.structure(val) == tmpl_struct
                and [x.shape for x in leaves_v] == tmpl_shapes):
            # params-mirroring field: stack with each leaf's own factor
            return jax.tree.unflatten(tmpl_struct, [
                jnp.broadcast_to(x[None], (world * f,) + x.shape)
                for x, f in zip(leaves_v, factors)
            ])
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (world,) + x.shape)
            if x.ndim > 0 else x,
            val,
        )

    inner = type(inner)(*[bcast_field(v) for v in inner])
    return LeaderState(shards, inner)


def leader_state_spec(opt_state: LeaderState, axis_name,
                      param_specs: Optional[PyTree] = None):
    """PartitionSpec pytree for :class:`LeaderState` (arrays sharded over
    ``axis_name``, scalars replicated). With ``param_specs``
    (model-parallel composition) the ``[world * n_model, shard_len]``
    leaves are jointly sharded ``P((data axes, *leaf model axes))``."""
    if param_specs is None:
        return jax.tree.map(
            lambda x: P(axis_name) if x.ndim > 0 else P(), opt_state
        )
    agg = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    shard_struct = jax.tree.structure(opt_state.param_shards)
    spec_leaves = shard_struct.flatten_up_to(param_specs)
    leaf_specs = [
        P(agg + axes) if (axes := _spec_axes(sp)) else P(axis_name)
        for sp in spec_leaves
    ]
    shard_shapes = [x.shape for x in jax.tree.leaves(opt_state.param_shards)]

    def field_spec(val):
        lv = jax.tree.leaves(val)
        if (jax.tree.structure(val) == shard_struct
                and [x.shape for x in lv] == shard_shapes):
            return jax.tree.unflatten(shard_struct, leaf_specs)
        return jax.tree.map(
            lambda x: P(axis_name) if x.ndim > 0 else P(), val
        )

    return LeaderState(
        jax.tree.unflatten(shard_struct, leaf_specs),
        type(opt_state.inner)(*[field_spec(v) for v in opt_state.inner]),
    )


def leader_scatter_shards(
    grads: PyTree, axis_name: str, world: int, comm_dtype=None,
    average: bool = False,
) -> PyTree:
    """Per-leaf reduce_scatter of local gradients: each worker receives
    only its shard's cross-worker sum (half of a psum's work)."""

    def scatter(g):
        rows = _to_shards(g, world).reshape(-1)  # row-major == tiled layout
        if comm_dtype is not None:
            rows = rows.astype(comm_dtype)
        sh = lax.psum_scatter(
            rows, axis_name, scatter_dimension=0, tiled=True
        ).astype(g.dtype)
        return sh / world if average else sh

    return jax.tree.map(scatter, grads)


def leader_slice_shards(summed: PyTree, axis_name: str, world: int) -> PyTree:
    """When every worker already holds the full summed gradient (non-psum
    codec decode path), index out each leaf's local shard row."""
    idx = lax.axis_index(axis_name)
    return jax.tree.map(
        lambda g: _to_shards(g, world)[idx], summed
    )


def clip_by_global_norm(grads: PyTree, clip_norm: float,
                        axis_name: Optional[str] = None,
                        leaf_extra_axes: Optional[list] = None) -> PyTree:
    """Scale ``grads`` so their global L2 norm is at most ``clip_norm``
    (torch ``clip_grad_norm_`` semantics, applied to the AGGREGATED
    gradient). With ``axis_name`` the leaves are device-local SHARDS of
    the global gradient (the ZeRO-1 psum_scatter fast path) and the
    norm is psum'd across the axis — shard-local norms would clip each
    device differently and silently diverge from the dense path.

    ``leaf_extra_axes`` (model-parallel composition): flat list aligned
    with ``jax.tree.leaves(grads)`` of extra mesh-axis tuples; each
    leaf's sum-square is psum'd over its tuple BEFORE the total, so a
    model-sharded leaf contributes its full cross-shard norm while
    replicated leaves are counted once."""
    leaves = jax.tree.leaves(grads)
    extras = leaf_extra_axes or [()] * len(leaves)
    sumsq = 0.0
    for g, axes in zip(leaves, extras):
        s = jnp.sum(jnp.square(g.astype(jnp.float32)))
        if axes:
            s = lax.psum(s, tuple(axes))
        sumsq = sumsq + s
    if axis_name is not None:
        sumsq = lax.psum(sumsq, axis_name)
    gnorm = jnp.sqrt(sumsq)
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm, 1e-12))
    return jax.tree.map(lambda g: (g * scale.astype(g.dtype)), grads)


def leader_shard_update(
    params: PyTree, opt_state: LeaderState, grad_shards: PyTree,
    update_fn: Callable, hyper, axis_name: str,
) -> Tuple[PyTree, LeaderState]:
    """Shard-local optimizer step + all_gather back to replicated params
    (runs inside shard_map; ``opt_state`` leaves carry the local ``[1,
    shard_len]`` slice)."""
    p_shards = jax.tree.map(lambda x: x[0], opt_state.param_shards)
    inner = jax.tree.map(lambda x: x[0] if x.ndim > 0 else x, opt_state.inner)
    new_shards, new_inner = update_fn(p_shards, grad_shards, inner, hyper)

    def gather(sh, p):
        full = lax.all_gather(sh, axis_name, tiled=True)
        n = int(np.prod(p.shape)) if p.shape else 1
        return lax.slice(full, (0,), (n,)).reshape(p.shape)

    new_params = jax.tree.map(gather, new_shards, params)
    new_opt_state = LeaderState(
        jax.tree.map(lambda x: x[None], new_shards),
        jax.tree.map(lambda x: x[None] if x.ndim > 0 else x, new_inner),
    )
    return new_params, new_opt_state


class _IdKey:
    """Hash/eq by object identity while holding a strong reference, so an
    id() can never be recycled into a false cache hit after GC."""

    __slots__ = ("obj",)

    def __init__(self, obj):
        self.obj = obj

    def __hash__(self):
        return id(self.obj)

    def __eq__(self, other):
        return isinstance(other, _IdKey) and other.obj is self.obj


def _fn_cache_key(fn: Optional[Callable]) -> Any:
    """Compile-cache key for a user loss function that survives fresh
    function *objects* with identical behavior — ``(code, closure cells,
    defaults, bound self)`` instead of bare identity — so
    ``step(loss_fn=lambda p, b: ...)`` in a loop, or a bound method
    (``model.loss`` creates a new object per attribute access), compiles
    once. Anything that can change behavior distinguishes the key:
    closure cell values, default args, and the method receiver; unhashable
    values are wrapped in :class:`_IdKey` (identity + strong ref).
    Known limits (same caveats as ``jax.jit`` identity keying avoids): a
    function reading a rebound module-level *global* is indistinguishable,
    and a captured hashable object *mutated in place* yields a stale hit —
    pass a fresh closure when either changes behavior."""
    if fn is None or not hasattr(fn, "__code__"):
        return fn

    def h(v):
        try:
            hash(v)
            return v
        except TypeError:
            return _IdKey(v)

    def cell(c):
        try:
            return h(c.cell_contents)
        except ValueError:  # empty (not-yet-assigned) cell
            return _IdKey(c)

    cells = tuple(cell(c) for c in (fn.__closure__ or ()))
    defaults = tuple(h(d) for d in (fn.__defaults__ or ()))
    bound_self = _IdKey(fn.__self__) if hasattr(fn, "__self__") else None
    return (fn.__code__, cells, defaults, bound_self)


# ---------------------------------------------------------------------------
# SPMD pipeline pieces, shared with the functional API in parallel/dp.py.
# All run *inside* shard_map.
# ---------------------------------------------------------------------------

def encode_tree(code: Codec, grads: PyTree, codec_state: PyTree, rng, axis_name: str):
    """Per-worker encode of every gradient leaf (the reference's autograd
    hook + thread pool, ``ps.py:94-101``, collapsed into the traced step).

    ``codec_state`` leaves carry a leading local-shard axis of size 1 (the
    shard_map slice of the host-side ``[world, ...]`` stack).
    """
    leaves, treedef = jax.tree.flatten(grads)
    keys = None
    if code.needs_rng:
        worker_rng = jax.random.fold_in(rng, lax.axis_index(axis_name))
        keys = list(jax.random.split(worker_rng, len(leaves)))
    flat_states = treedef.flatten_up_to(codec_state)
    payloads, new_states = [], []
    for i, g in enumerate(leaves):
        st = jax.tree.map(lambda x: x[0], flat_states[i])  # squeeze shard axis
        payload, new_st = code.encode(g, st, keys[i] if keys is not None else None)
        payloads.append(payload)
        new_states.append(jax.tree.map(lambda x: x[None], new_st))
    return (
        jax.tree.unflatten(treedef, payloads),
        jax.tree.unflatten(treedef, new_states),
    )


def _accumulate_grads(loss_fn, accum_steps: int, params: PyTree,
                      batches: PyTree, axis_name: str, *,
                      reduce_loss: Callable):
    """Microbatch gradient accumulation inside one SPMD program: scan
    ``accum_steps`` microbatches, mean the local grads, cross-worker-
    reduce the mean loss via ``reduce_loss`` (REQUIRED — every caller
    must pass the optimizer's own reduction so the reported loss can
    never fork between the fused accum step and the instrumented grad
    stage; they are asserted numerically equal in tests)."""
    def micro(acc, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        return jax.tree.map(jnp.add, acc, grads), loss

    zero = jax.tree.map(jnp.zeros_like, params)
    grads, losses = lax.scan(micro, zero, batches)
    grads = jax.tree.map(lambda g: g / accum_steps, grads)
    return reduce_loss(losses.mean()), grads


def decode_sum_payloads(code: Codec, gathered: PyTree, shape, dtype):
    """The ONE payload-summing call site discipline (used by
    :func:`aggregate`, :func:`bucketed_aggregate` and the instrumented
    decode stage): route through the codec's compressed-domain
    ``Codec.aggregate`` algebra when it is EXACT — sum in the integer /
    sparse-index / factor domain, then decode once — and fall back to
    ``decode_sum`` otherwise. Approximate algebras (sign's vote counts,
    ``agg_exact=False``) never enter the training path implicitly; they
    ride only the host wire, behind the measured fidelity contract."""
    if (getattr(code, "supports_aggregate", False)
            and getattr(code, "agg_exact", True)
            and code.can_aggregate(shape, dtype)):
        agg_payload, meta = code.aggregate(gathered, shape, dtype)
        return code.agg_decode(agg_payload, meta, shape, dtype)
    return code.decode_sum(gathered, shape, dtype)


def aggregate(
    code: Codec,
    grads: PyTree,
    payloads: PyTree,
    axis_name,
    average: bool,
    size: int,
    comm_dtype=None,
    leaf_axes: Optional[list] = None,
    leaf_sizes: Optional[list] = None,
) -> PyTree:
    """Collective + decode + sum across workers (reference
    ``ps.py:140-176``). Identity-like codecs lower to one fused ``psum``;
    everything else all-gathers static-shape payloads and scatter/sums.

    ``comm_dtype`` (e.g. ``jnp.bfloat16``) narrows the psum path's wire
    dtype — halving ICI bytes, the cheap always-on compression every TPU
    program should use — and casts back for the f32 update. A psum-capable
    codec that declares a ``wire_dtype`` (the bf16/f16 cast codecs) is
    lowered the same way: the cast IS its encode, so the fused path must
    narrow the collective or the codec would silently be an identity
    no-op.

    ``leaf_axes`` (model-parallel composition): flat list aligned with
    ``jax.tree.leaves(grads)`` of per-leaf aggregation-axis tuples — a
    leaf SHARDED over one of the data axes (expert parallelism, where
    the expert axis carries both the shard and extra tokens) aggregates
    only over the remaining axes; ``()`` means the local gradient is
    already complete (codec filtering still applies via its own
    payload). ``leaf_sizes`` carries each leaf's worker count for
    ``average``."""
    leaves, treedef = jax.tree.flatten(grads)
    axes_list = leaf_axes if leaf_axes is not None else [axis_name] * len(leaves)
    sizes = leaf_sizes if leaf_sizes is not None else [size] * len(leaves)
    summed_leaves = []
    if code.supports_psum:
        wire = comm_dtype if comm_dtype is not None else getattr(
            code, "wire_dtype", None
        )
        for g, axes in zip(leaves, axes_list):
            if isinstance(axes, tuple) and not axes:
                # sharded over every data axis: local grad is complete,
                # but the wire cast must still round-trip (the cast IS
                # the codec's lossy encode — skipping it would silently
                # treat this leaf at full precision)
                summed_leaves.append(
                    g.astype(wire).astype(g.dtype) if wire is not None else g
                )
            elif wire is not None:
                summed_leaves.append(
                    lax.psum(g.astype(wire), axes).astype(g.dtype)
                )
            else:
                summed_leaves.append(lax.psum(g, axes))
    else:
        payload_list = treedef.flatten_up_to(payloads)
        for g, payload, axes in zip(leaves, payload_list, axes_list):
            if isinstance(axes, tuple) and not axes:
                # decode own payload only (codec filter still applies)
                gathered = jax.tree.map(lambda x: x[None], payload)
            else:
                gathered = jax.tree.map(
                    lambda x: lax.all_gather(x, axes), payload
                )
            summed_leaves.append(
                decode_sum_payloads(code, gathered, g.shape, g.dtype))
    if average:
        summed_leaves = [x / n for x, n in zip(summed_leaves, sizes)]
    return jax.tree.unflatten(treedef, summed_leaves)


def _encode_buckets(code: Codec, buckets, rng, axis_name):
    """Per-worker, per-bucket codec encode (stateless by the
    ``bucketable`` contract): ONE rng-derivation for every bucketed
    lowering, so the allgather and leader dense_scatter paths can never
    drift onto different randomness."""
    keys = None
    if code.needs_rng:
        worker_rng = jax.random.fold_in(rng, lax.axis_index(axis_name))
        keys = list(jax.random.split(worker_rng, len(buckets)))
    return [
        code.encode(b, (), keys[i] if keys is not None else None)[0]
        for i, b in enumerate(buckets)
    ]


def bucketed_aggregate(
    code: Codec,
    grads: PyTree,
    plan: BucketPlan,
    axis_name,
    average: bool,
    size: int,
    comm_dtype=None,
    rng=None,
) -> PyTree:
    """Flat-bucket form of :func:`aggregate` (mode='allgather' and the
    leader payload-gather lowering): flatten the gradient tree into
    dtype-grouped buckets, run ONE collective per bucket instead of one
    per leaf, and unflatten the summed buckets back to the tree. Runs
    inside shard_map.

    psum-capable codecs psum each bucket (wire-narrowed exactly as the
    per-leaf path would be, so numerics are bit-identical — a bucket is a
    permutation-into-concatenation of the leaves and psum is elementwise).
    Non-psum ``bucketable`` codecs encode each bucket as if it were one
    large leaf (stateless by the ``bucketable`` contract), all-gather the
    per-bucket payloads, and decode_sum per bucket — per-input statistics
    (sign's mean|g|, int8's absmax) then apply per bucket, the documented
    semantics shift for those lossy codecs."""
    buckets = flatten_into_buckets(plan, grads)
    if code.supports_psum:
        wire = comm_dtype if comm_dtype is not None else getattr(
            code, "wire_dtype", None
        )
        summed_b = comms.allreduce_sum_buckets(buckets, axis_name, wire)
    else:
        payloads = _encode_buckets(code, buckets, rng, axis_name)
        summed_b = []
        for b, payload in zip(buckets, payloads):
            gathered = jax.tree.map(
                lambda x: lax.all_gather(x, axis_name), payload
            )
            summed_b.append(
                decode_sum_payloads(code, gathered, b.shape, b.dtype))
    if average:
        summed_b = [x / size for x in summed_b]
    return unflatten_from_buckets(plan, summed_b)


def fused_allreduce_tree(
    code: Codec, grads: PyTree, codec_state: PyTree, axis_name,
    average: bool, size: int, comm_dtype=None,
    leaf_axes: Optional[list] = None, leaf_sizes: Optional[list] = None,
):
    """Tree-mapped collective-protocol aggregation for codecs declaring
    ``supports_fused_allreduce`` (PowerSGD's two-psum form): returns
    ``(summed, new_codec_state)``. Runs inside shard_map. ``leaf_axes``
    / ``leaf_sizes`` as in :func:`aggregate` (model-parallel per-leaf
    aggregation); codec-state leaves carry the leading local-shard axis
    of 1 (the shard_map slice), like :func:`encode_tree`."""
    leaves, treedef = jax.tree.flatten(grads)
    flat_states = treedef.flatten_up_to(codec_state)
    axes_list = leaf_axes if leaf_axes is not None else [axis_name] * len(leaves)
    sizes = leaf_sizes if leaf_sizes is not None else [size] * len(leaves)
    summed, new_states = [], []
    for g, st_stacked, axes in zip(leaves, flat_states, axes_list):
        st = jax.tree.map(lambda x: x[0], st_stacked)
        if isinstance(axes, tuple) and not axes:
            # sharded over every data axis (EP): local grad is complete
            s, new_st = g, st
        else:
            s, new_st = code.fused_allreduce(g, st, axes, comm_dtype=comm_dtype)
        summed.append(s)
        new_states.append(jax.tree.map(lambda x: x[None], new_st))
    if average:
        summed = [x / n for x, n in zip(summed, sizes)]
    return (
        jax.tree.unflatten(treedef, summed),
        jax.tree.unflatten(treedef, new_states),
    )


class MPI_PS:
    """Distributed parameter-server optimizer over a device mesh.

    Parameters mirror the reference constructor (``ps.py:54-59``) where
    they still make sense; MPI/cuda knobs are replaced by mesh/codec ones:

    Args:
      params: pytree of parameter arrays (replicated across the mesh).
      optim: ``'sgd'`` or ``'adam'`` (reference ``ps.py:181-188``).
      code: a :class:`Codec` (reference ``code=`` hook); default identity.
      mesh: ``jax.sharding.Mesh``; default 1-D data mesh over all devices.
      axis_name: mesh axis to aggregate over.
      mode: ``'allgather'`` (decentralized replicated step — the
        reference's live path) or ``'leader'`` (PS topology: the update
        runs once, sharded over workers ZeRO-1 style, not redundantly —
        optimizer state and the master parameter copy are partitioned
        1/world per device, per leaf, preserving leaf dtypes).
      average: if True, average worker gradients instead of the
        reference's sum semantics (``ps.py:176``).
      instrument: if True, ``step`` runs the pipeline as separate stages
        with host-side timing to fill the full metrics schema; if False,
        one fused XLA program (fast path) and only end-to-end time.
      seed: base PRNG seed for stochastic codecs.
      clip_norm: if > 0, clip the AGGREGATED gradient to this global L2
        norm before the update (torch ``clip_grad_norm_`` semantics) —
        in leader mode the norm is psum'd across shard sum-squares so
        both topologies clip identically.
      donate_buffers: if True, the fused step donates the params /
        optimizer-state / codec-state buffers to XLA (in-place update on
        device: peak HBM drops by roughly one params+state copy — at
        BERT-base/Adam scale ~2 GB). The PREVIOUS step's ``opt.params``
        etc. become invalid after each step; only enable when no outside
        reference holds them.
      param_specs: optional PartitionSpec pytree (matching ``params``)
        for MODEL-PARALLEL composition: leaves sharded over non-data
        mesh axes (e.g. ``parallel.tp.tp_param_spec`` for Megatron TP,
        ``parallel.pp.stage_spec`` for pipeline stages) stay sharded
        through the whole pipeline — the codec encodes each device's
        LOCAL shard gradient and the collective aggregates over the
        data axis only, so the drop-in optimizer (codecs, leader
        ZeRO-1, clip, metrics) drives 2-D/3-D meshes (VERDICT r4
        weak #4). The loss_fn must produce per-device local losses with
        vma-unchecked-correct collectives (``tp_mlp(...,
        local_grads=True)`` / ``pipeline_loss(..., local_grads=True)``)
        and a STATIC global normalizer; the reported loss is then the
        SUM of local losses across the aggregation axes (matching the
        gradient-sum semantics — a pmean would deflate it by the world
        size). Default None: fully-replicated params (pure DP, the
        reference's regime, ``ps.py:54-59``).
      bucket_mb: if > 0, fuse per-leaf collectives into dtype-grouped
        flat buckets of about this many megabytes (``bucketing.BucketPlan``)
        — one psum (allgather mode) / psum_scatter (leader mode, each
        worker owning a contiguous bucket shard) per BUCKET instead of
        per leaf, cutting a BERT-size tree's collective launch count by
        an order of magnitude. Bit-exact vs. the per-leaf path for
        identity/cast codecs; shape-agnostic stateless codecs
        (``Codec.bucketable``: sign, int8, qsgd, terngrad, and randomk's
        fraction form) encode per bucket (their per-input statistics
        then apply per bucket); per-tensor codecs (PowerSGD, top-k,
        absolute-k randomk) keep the per-leaf path automatically. ``0`` (default) preserves per-leaf behavior
        exactly. Requires pure-DP layouts (no ``param_specs``).
      numerics: if True, fuse on-device gradient statistics into the
        lowered step programs (``telemetry.numerics``): global finite
        grad norm, NaN/Inf element count, update-to-weight ratio
        ``||dp||/||p||``, per-BUCKET grad norms when ``bucket_mb`` is
        active, and the error-feedback residual norm when ``code`` is an
        :class:`~pytorch_ps_mpi_tpu.codecs.ErrorFeedback`. All
        reductions run inside the jit (XLA fuses them into the step for
        ~free) and land in the returned metrics dict as ``grad_norm`` /
        ``nonfinite_total`` / ``update_ratio`` / ``bucket_grad_norms``
        / ``ef_residual_norm`` — one tiny stats vector fetched per
        step. The fused and accumulation paths compute them;
        ``instrument=True`` stages and ``run_steps`` (one opaque scanned
        program) do not. Requires pure-DP layouts (no ``param_specs``).
      batch_spec: optional PartitionSpec for the batch pytree's leaves
        (default ``P(axis_name)``: leading dim split over the data
        axis). With model parallelism e.g. ``P('data')`` replicates the
        batch across model shards, or ``P('data', 'seq')`` also splits
        the sequence dim.
      loss_reduction: how the per-device loss is reduced for reporting:
        ``'pmean'`` (pure-DP local-batch-mean convention) or ``'psum'``
        (local loss with a static global normalizer — the param_specs /
        tuple-axes contract). Default None picks by convention:
        psum when param_specs or tuple aggregation axes are in play,
        pmean otherwise.
      **hyper: optimizer hyperparameters (lr, momentum, betas, ...).
        ``lr`` may be a float or a schedule callable ``step -> scalar``
        from :data:`pytorch_ps_mpi_tpu.optim.SCHEDULES` (e.g.
        ``warmup_cosine``): it is evaluated on the optimizer's traced
        step counter inside the compiled program, so the rate varies per
        step with no recompiles.

    ``axis_name`` may also be a TUPLE of mesh axes (e.g. ``('data',
    'seq')``): gradients aggregate over their product — the sequence-
    parallel composition where every seq shard holds the same params
    and contributes partial gradients.
    """

    def __init__(
        self,
        params: PyTree,
        *,
        optim: str = "sgd",
        code: Optional[Codec] = None,
        mesh: Optional[Mesh] = None,
        axis_name=DATA_AXIS,
        mode: str = "allgather",
        average: bool = False,
        instrument: bool = False,
        comm_dtype=None,
        seed: int = 0,
        donate_buffers: bool = False,
        clip_norm: float = 0.0,
        bucket_mb: float = 0.0,
        numerics: bool = False,
        param_specs: Optional[PyTree] = None,
        batch_spec=None,
        loss_reduction: Optional[str] = None,
        **hyper,
    ):
        if optim not in OPTIMIZERS:
            raise ValueError(f"optim must be one of {sorted(OPTIMIZERS)}")
        if mode not in ("allgather", "leader"):
            raise ValueError("mode must be 'allgather' or 'leader'")
        if clip_norm < 0:
            # a negative threshold would flip scale's sign and silently
            # turn the update into gradient ASCENT
            raise ValueError(f"clip_norm must be >= 0, got {clip_norm}")
        if loss_reduction not in (None, "pmean", "psum"):
            raise ValueError(
                f"loss_reduction must be 'pmean', 'psum', or None "
                f"(auto), got {loss_reduction!r}"
            )
        self._loss_reduction = loss_reduction
        hyper_cls, init_state, update_fn = OPTIMIZERS[optim]
        self.hyper = hyper_cls(**hyper)
        self._update_fn = update_fn
        self.params = params
        self.code = code if code is not None else IdentityCodec()
        if mesh is None and not isinstance(axis_name, str):
            mesh = make_mesh(axis_names=tuple(axis_name))
        self.mesh = mesh if mesh is not None else make_mesh(axis_names=(axis_name,))
        self.axis_name = axis_name
        self._agg_axes = (
            (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        )
        self.mode = mode
        self.average = average
        self.donate_buffers = donate_buffers
        self.clip_norm = float(clip_norm)
        self.instrument = instrument
        self.comm_dtype = comm_dtype
        self.rank = jax.process_index()           # reference ps.py:71-72
        self.size = int(np.prod(                  # reference ps.py:73
            [self.mesh.shape[a] for a in self._agg_axes]
        ))
        # -- model-parallel composition (param_specs) ---------------------
        if param_specs is None:
            param_specs = jax.tree.map(lambda _: P(), params)
        struct = jax.tree.structure(params)
        self._spec_leaves = struct.flatten_up_to(param_specs)
        # canonical full tree (exact params structure, P leaves) so
        # jax.tree.map over (params, param_specs) is always legal
        self.param_specs = jax.tree.unflatten(struct, self._spec_leaves)
        # Per-leaf aggregation axes: a leaf sharded over one of the data
        # axes (expert parallelism — the expert axis carries both the
        # shard and extra tokens) aggregates only over the remaining
        # axes; its shard gradient over its own axis is already complete.
        self._leaf_agg_axes = [
            tuple(a for a in self._agg_axes if a not in _spec_axes(sp))
            for sp in self._spec_leaves
        ]
        self._leaf_agg_sizes = [
            int(np.prod([self.mesh.shape[a] for a in axes]) if axes else 1)
            for axes in self._leaf_agg_axes
        ]
        self._model_parallel = any(_spec_axes(sp) for sp in self._spec_leaves)
        self._uniform_agg = all(
            axes == self._agg_axes for axes in self._leaf_agg_axes
        )
        if mode == "leader" and not self._uniform_agg:
            raise ValueError(
                "leader (ZeRO-1) mode requires every leaf to aggregate "
                "over the full data axes — param_specs must not shard "
                "over the aggregation axes; use mode='allgather' for "
                "expert-parallel layouts"
            )
        if optim == "adafactor" and mode == "leader":
            # leader mode flattens leaves to 1-D per-worker shards —
            # Adafactor's factored moments depend on each leaf's GLOBAL
            # 2-D shape, so the sharded step would silently compute a
            # DIFFERENT update than the allgather form.
            raise NotImplementedError(
                "optim='adafactor' does not support mode='leader': "
                "ZeRO-1's 1-D shards destroy the leaf shapes the "
                "factored second moments are defined over (and its "
                "state-sharding win is marginal for a sublinear-state "
                "optimizer). Use mode='allgather'"
            )
        if optim == "adafactor" and self._model_parallel:
            # model-parallel Adafactor is exactly shard-local
            # decomposable iff no FACTORED dim is sharded (then the
            # row/col means never span devices); the two per-leaf
            # scalar reductions (clip RMS, parameter scale) become
            # global via pmean over the model axes — identity on
            # replicated leaves, exact global mean on uniform shards.
            if not self._uniform_agg:
                raise NotImplementedError(
                    "optim='adafactor' with expert-parallel layouts "
                    "(leaves sharded over a data axis) is unsupported: "
                    "the per-leaf scalar reductions would need per-leaf "
                    "axis sets. Use optim='adam'/'sgd' for EP"
                )
            adafactor_check_sharding(params, self.param_specs)
            model_axes = tuple(a for a in self.mesh.axis_names
                               if a not in self._agg_axes)
            self._update_fn = functools.partial(
                adafactor_update,
                scalar_mean=lambda s: lax.pmean(s, model_axes),
            )
        if self._model_parallel and mode == "leader":
            for p, sp in zip(jax.tree.leaves(params), self._spec_leaves):
                entries = tuple(sp)
                sharded_dims = [i for i, e in enumerate(entries)
                                if e is not None]
                if sharded_dims and sharded_dims != [0]:
                    raise ValueError(
                        "leader mode requires model-sharded leaves to use "
                        "the leading-shard-axis convention (spec P(axis) on "
                        f"dim 0 only); got {sp} for shape {p.shape}"
                    )
        # -- flat-bucket aggregation (bucket_mb) --------------------------
        if bucket_mb < 0:
            raise ValueError(f"bucket_mb must be >= 0, got {bucket_mb}")
        self.bucket_mb = float(bucket_mb)
        self._bucket_plan: Optional[BucketPlan] = None
        if self.bucket_mb > 0:
            if self._model_parallel or not self._uniform_agg:
                raise NotImplementedError(
                    "bucket_mb > 0 requires pure-DP layouts: model-sharded "
                    "or expert-parallel leaves aggregate over per-leaf axis "
                    "sets that one flat bucket cannot represent. Drop "
                    "param_specs or set bucket_mb=0"
                )
            if (self.code.bucketable
                    and not self.code.supports_fused_allreduce):
                if jax.tree.leaves(self.code.init_state((1,), jnp.float32)):
                    raise TypeError(
                        f"{type(self.code).__name__}.bucketable=True but "
                        "init_state is non-empty — bucketable codecs must "
                        "be stateless (see codecs.base.Codec.bucketable)"
                    )
                self._bucket_plan = plan_buckets(params, self.bucket_mb)
            # else: per-tensor codec — keep the per-leaf path (the
            # documented Codec.bucketable opt-out), no error
        self._bucket_templates = (
            self._bucket_plan.bucket_templates()
            if self._bucket_plan is not None else None
        )
        # -- fused numerics statistics (numerics=True) --------------------
        self.numerics = bool(numerics)
        if self.numerics and self._model_parallel:
            raise NotImplementedError(
                "numerics=True requires pure-DP layouts: model-sharded "
                "leaves would need per-leaf reduction axis sets for the "
                "global norms. Drop param_specs or set numerics=False"
            )
        self.batch_spec = batch_spec if batch_spec is not None else P(axis_name)
        if self._model_parallel and instrument:
            raise NotImplementedError(
                "instrument=True (the staged host-timed pipeline) is not "
                "supported with param_specs — use profile=True on the "
                "fused step for the trace-derived comm/compute split"
            )
        if mode == "leader":
            # ZeRO-1-style sharded optimizer: each worker owns a 1/world
            # shard of every parameter and the optimizer state for it —
            # the TPU-native lowering of the reference's rank-0 PS
            # (gather to rank 0, rank 0 alone steps, broadcast back,
            # mpi_comms.py:60-133, README.md:61-77), generalized so every
            # chip is the "leader" of its own shard: per-leaf
            # reduce_scatter → shard-local update → all_gather. Update
            # FLOPs and optimizer-state memory divide by world size; comm
            # volume matches a psum (which IS reduce_scatter+all_gather
            # on a ring). Per-leaf sharding (not one flat concat)
            # preserves leaf dtypes and lets XLA fuse per-tensor.
            from jax.sharding import NamedSharding

            specs_arg = self.param_specs if self._model_parallel else None

            # Construct the state *directly sharded* (jit + out_shardings)
            # so no device ever materializes the full [world, shard_len]
            # stack — a host-side build-then-reshard would transiently use
            # world× the sharded memory, defeating ZeRO-1's point at the
            # model scales it targets.
            #
            # With a bucket plan the master copy is kept in BUCKET form:
            # LeaderState.param_shards leaves are per-bucket [world, ss]
            # stacks, so the step's psum_scatter of a flat bucket lands
            # directly on the shard the optimizer owns — no re-slicing
            # between the wire layout and the state layout. The update is
            # elementwise (SGD/Adam; adafactor is rejected in leader mode
            # above), so per-bucket state is numerically identical to
            # per-leaf state, and dtype grouping preserves leaf dtypes.
            def build(p):
                if self._bucket_plan is not None:
                    p = flatten_into_buckets(self._bucket_plan, p)
                return leader_init_state(
                    p, init_state, self.size, specs_arg, self.mesh
                )

            structs = jax.eval_shape(build, params)
            spec_tree = leader_state_spec(structs, axis_name, specs_arg)
            shardings = jax.tree.map(
                lambda s, sp: NamedSharding(self.mesh, sp), structs, spec_tree
            )
            self.opt_state = jax.jit(build, out_shardings=shardings)(params)
        else:
            self.opt_state = init_state(params)
        self._rng = jax.random.key(seed)
        self.codec_state = self._init_codec_state()
        self._codec_spec = self._codec_state_spec()
        self.aux_state = None  # mutable model state (e.g. BN batch_stats)
        self._compiled: Dict[Any, Callable] = {}
        self._step_count = 0
        self._payload_bytes_per_leaf = float(sum(
            self.code.payload_bits(
                _local_shape(p.shape, sp, self.mesh), p.dtype
            ) // 8
            for p, sp in zip(jax.tree.leaves(params), self._spec_leaves)
        ))
        if self._bucket_plan is not None:
            # encode (when used) runs per BUCKET: the payload accounting
            # must match or packaged_bytes would overstate per-leaf
            # overheads (e.g. sign's one scale scalar per unit). The
            # per-leaf figure is kept for the staged instrument pipeline,
            # whose encode/gather stages stay per-leaf.
            self._payload_bytes = float(sum(
                self.code.payload_bits((b.size,), b.dtype) // 8
                for b in self._bucket_plan.buckets
            ))
        else:
            self._payload_bytes = self._payload_bytes_per_leaf
        self._local_param_bytes = float(sum(
            int(np.prod(_local_shape(p.shape, sp, self.mesh)) if p.shape else 1)
            * jnp.dtype(p.dtype).itemsize
            for p, sp in zip(jax.tree.leaves(params), self._spec_leaves)
        ))
        self._init_wire_accounting()
        # static per-step launch accounting for the metrics dict / trace:
        # aggregation units = buckets when a plan is active, leaves
        # otherwise (the quantity bucketing exists to shrink)
        if self._bucket_plan is not None:
            self._agg_units = self._bucket_plan.num_buckets
            self._bucket_bytes_total = float(self._bucket_plan.total_bytes)
        else:
            self._agg_units = len(self._spec_leaves)
            self._bucket_bytes_total = 0.0

    # -- codec state: per-worker, stored host-side stacked on a leading
    #    [world] axis so shard_map can scatter/gather it. Model-sharded
    #    leaves build state from the LOCAL shard shape and stack
    #    [world * n_model_shards] for a joint P((data, *model)) sharding:
    #    per-(data, model)-device codec state (e.g. error feedback is per
    #    shard of the gradient each device actually encodes) ---------------
    def _leaf_state_axes(self, sp) -> Tuple[str, ...]:
        """Mesh axes a leaf's codec state varies over: its aggregation
        axes (one state per data worker) then its shard axes (one per
        model/expert shard) — every distinct (worker, shard) cell."""
        spec_axes = _spec_axes(sp)
        agg = tuple(a for a in self._agg_axes if a not in spec_axes)
        return agg + spec_axes

    def _init_codec_state(self) -> PyTree:
        def leaf(p, sp):
            lshape = _local_shape(p.shape, sp, self.mesh)
            s = self.code.init_state(lshape, p.dtype)
            axes = self._leaf_state_axes(sp)
            n = int(np.prod([self.mesh.shape[a] for a in axes]) if axes else 1)
            return jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), s
            )
        return jax.tree.map(leaf, self.params, self.param_specs)

    def _codec_state_spec(self) -> PyTree:
        """Per-leaf PartitionSpec pytree matching ``codec_state``
        (abstract eval only — re-materializing real state arrays here
        would transiently double the param-sized error-feedback buffers
        at BERT scale)."""
        def leaf(p, sp):
            axes = self._leaf_state_axes(sp)
            ax = P(axes) if _spec_axes(sp) else P(self.axis_name)
            lshape = _local_shape(p.shape, sp, self.mesh)
            s = jax.eval_shape(
                lambda: self.code.init_state(lshape, p.dtype)
            )
            return jax.tree.map(lambda _: ax, s)
        return jax.tree.map(leaf, self.params, self.param_specs)

    # -- SPMD pipeline pieces (run inside shard_map) ----------------------
    def _encode_tree(self, grads, codec_state, rng):
        return encode_tree(self.code, grads, codec_state, rng, self.axis_name)

    def _aggregate(self, grads, payloads):
        return aggregate(
            self.code, grads, payloads, self.axis_name, self.average, self.size,
            self.comm_dtype,
            leaf_axes=None if self._uniform_agg else self._leaf_agg_axes,
            leaf_sizes=None if self._uniform_agg else self._leaf_agg_sizes,
        )

    def _reduce_loss(self, loss):
        """Cross-worker reduction of the per-device loss for reporting.

        Pure DP: loss_fn computes a local-batch MEAN, so pmean over the
        data axis is the global mean. With param_specs — or tuple
        aggregation axes (the SP composition) — the documented
        convention is a local loss with a STATIC GLOBAL normalizer
        (matching the optimizer's gradient-sum semantics), so the local
        losses SUM to the global loss — pmean would deflate the reported
        value by the world size. ``loss_reduction`` overrides either
        default."""
        how = self._loss_reduction
        if how is None:
            how = ("psum" if self._model_parallel
                   or not isinstance(self.axis_name, str) else "pmean")
        if how == "psum":
            return lax.psum(loss, self.axis_name)
        return lax.pmean(loss, self.axis_name)

    def _leaf_clip_axes(self):
        """Per-leaf extra psum axes for the global clip norm: a model-
        sharded leaf's sum-square spans its shards; replicated leaves
        count once."""
        if not self._model_parallel:
            return None
        return [_spec_axes(sp) for sp in self._spec_leaves]

    def _update(self, params, opt_state, summed):
        if self.clip_norm:
            summed = clip_by_global_norm(
                summed, self.clip_norm, leaf_extra_axes=self._leaf_clip_axes()
            )
        if self.mode == "leader":
            # Every rank already holds the full summed gradient (non-psum
            # codec decode path, or the instrumented stages); slice out
            # each leaf's local shard and run the sharded step.
            if self._bucket_plan is not None:
                # bucket-sharded state: slice each worker's contiguous
                # BUCKET shard (the layout the opt state was built in)
                buckets = flatten_into_buckets(self._bucket_plan, summed)
                shards = leader_slice_shards(buckets, self.axis_name, self.size)
                return self._leader_bucket_update(opt_state, shards)
            grad_shards = leader_slice_shards(summed, self.axis_name, self.size)
            return leader_shard_update(
                params, opt_state, grad_shards, self._update_fn, self.hyper,
                self.axis_name,
            )
        return self._update_fn(params, summed, opt_state, self.hyper)

    def _leader_bucket_update(self, opt_state, bucket_shards):
        """Shard-local optimizer step on contiguous bucket shards +
        all_gather + unflatten back to replicated params (the bucketed
        leader/ZeRO-1 lowering: opt state and master params live per
        bucket, see ``__init__``). Runs inside shard_map."""
        new_bucket_params, new_opt_state = leader_shard_update(
            self._bucket_templates, opt_state, bucket_shards,
            self._update_fn, self.hyper, self.axis_name,
        )
        new_params = unflatten_from_buckets(self._bucket_plan, new_bucket_params)
        return new_params, new_opt_state

    def _bucketed_encode_aggregate_update(self, params, opt_state,
                                          codec_state, grads, rng):
        """Flat-bucket lowering of the encode → aggregate → update seam
        (``_bucket_plan`` is set: bucketable codec, pure-DP layout). The
        codec is stateless by the ``bucketable`` contract, so
        ``codec_state`` passes through untouched."""
        plan = self._bucket_plan
        lowering = self._leader_lowering()
        if lowering in ("psum_scatter", "dense_scatter"):
            if lowering == "psum_scatter":
                to_scatter = flatten_into_buckets(plan, grads)
                wire = self.comm_dtype if self.comm_dtype is not None else (
                    getattr(self.code, "wire_dtype", None)
                )
            else:
                # decode the own-bucket payload to the codec-filtered
                # dense bucket, then reduce_scatter that (numerics match
                # the gather form exactly as in the per-leaf path)
                buckets = flatten_into_buckets(plan, grads)
                payloads = _encode_buckets(
                    self.code, buckets, rng, self.axis_name
                )
                to_scatter = [
                    self.code.decode(p, b.shape, b.dtype)
                    for b, p in zip(buckets, payloads)
                ]
                wire = self.comm_dtype
            grad_shards = leader_scatter_shards(
                to_scatter, self.axis_name, self.size, wire, self.average
            )
            if self.clip_norm:
                # bucket shards partition the aggregated gradient exactly
                # as leaf shards do (padding is zeros): same global norm
                grad_shards = clip_by_global_norm(
                    grad_shards, self.clip_norm, self.axis_name
                )
            new_params, new_opt_state = self._leader_bucket_update(
                opt_state, grad_shards
            )
            return new_params, new_opt_state, codec_state
        # allgather mode, or the leader payload_gather lowering (strongly
        # compressing codec): bucketed collective + decode, then the
        # shared update path (which re-buckets for the leader slice)
        summed = bucketed_aggregate(
            self.code, grads, plan, self.axis_name, self.average, self.size,
            self.comm_dtype, rng,
        )
        new_params, new_opt_state = self._update(params, opt_state, summed)
        return new_params, new_opt_state, codec_state

    def _tree_wire_bytes(self, wire_dtype) -> float:
        """Dense gradient bytes at the collective's wire dtype (per-leaf
        LOCAL-shard numel x itemsize — global numel when replicated;
        ``wire_dtype=None`` keeps each leaf's own)."""
        return float(sum(
            int(np.prod(_local_shape(p.shape, sp, self.mesh)) if p.shape
                else 1)
            * (jnp.dtype(wire_dtype).itemsize if wire_dtype is not None
               else jnp.dtype(p.dtype).itemsize)
            for p, sp in zip(jax.tree.leaves(self.params), self._spec_leaves)
        ))

    def _init_wire_accounting(self) -> None:
        """Chosen aggregation lowering + analytic bytes RECEIVED per
        worker per step — computed ONCE (static per instance) and
        surfaced in every step's metrics dict. This is the reference's
        msg-bytes accounting (``ps.py:135-136``) extended to make each
        topology's traffic comparable (VERDICT r3 item 9).

        Leader-mode lowering choice, by minimum received bytes (the PS
        topology's whole point is less traffic per worker — reference
        ``README.md:61-77``):

        - ``psum_scatter``: psum-capable codec — per-leaf reduce_scatter
          (wire dtype: ``comm_dtype`` or the codec's ``wire_dtype``).
        - ``dense_scatter``: non-psum codec with a WEAK wire ratio:
          decode the OWN payload to the dense codec-filtered gradient
          locally, then reduce_scatter that (wire dtype: ``comm_dtype``
          only — a non-psum codec's wire_dtype, e.g. f16's, is excluded
          from on-chip collectives by design, see codecs/cast.py).
          psum(decode(own)) == decode_sum(allgather(payloads)) by
          decode_sum's definition, so numerics are identical; received
          bytes drop from (W-1)·p to (W-1)/W·n_w.
        - ``payload_gather``: strongly-compressing sparse codec —
          all-gather the payloads and decode-sum. UNAVOIDABLE for this
          class under SPMD collectives: payload indices are
          data-dependent, XLA collectives cannot route by content, and
          a dense reduce_scatter would receive (W-1)/W·n_w per worker —
          more than the whole (W-1)·p payload exchange when p is small.
          What leader mode still buys is the 1/W update FLOPs and
          optimizer-state HBM (ZeRO-1), paid for with the param
          all_gather; ``wire_bytes_per_worker`` makes that trade
          visible per configuration.
        """
        w = self.size
        frac = (w - 1) / w
        n = self._local_param_bytes  # == _tree_bytes(params) when pure-DP
        p = self._payload_bytes
        psum_wire = self.comm_dtype if self.comm_dtype is not None else (
            getattr(self.code, "wire_dtype", None)
        )
        if self.code.supports_fused_allreduce:
            # two rank-sized ring psums per compressed leaf (plain psum
            # for uncompressed ones): received bytes are world-size-
            # INDEPENDENT in the payload term — the protocol's headline
            # property (Vogels et al. 2019 Alg. 1)
            fused = float(sum(
                self.code.fused_wire_bits(
                    _local_shape(pp.shape, sp, self.mesh), pp.dtype,
                    comm_dtype=self.comm_dtype,
                ) // 8
                for pp, sp in zip(jax.tree.leaves(self.params),
                                  self._spec_leaves)
            ))
            recv = 2 * frac * fused
            if self.mode == "leader":
                recv += frac * n  # sharded update's param all_gather
            self._wire_accounting = ("two_psum_lowrank", recv)
            return
        if self.mode == "leader":
            if self.code.supports_psum:
                self._wire_accounting = (
                    "psum_scatter",
                    frac * (self._tree_wire_bytes(psum_wire) + n),
                )
                return
            dense_recv = frac * self._tree_wire_bytes(self.comm_dtype)
            payload_recv = (w - 1) * p
            if dense_recv < payload_recv:
                self._wire_accounting = (
                    "dense_scatter", dense_recv + frac * n
                )
            else:
                self._wire_accounting = (
                    "payload_gather", payload_recv + frac * n
                )
            return
        if self.code.supports_psum:
            self._wire_accounting = (
                "psum", 2 * frac * self._tree_wire_bytes(psum_wire)
            )
        else:
            self._wire_accounting = ("allgather", (w - 1) * p)

    def _leader_lowering(self) -> str:
        return self._wire_accounting[0] if self.mode == "leader" else ""

    def _aggregate_update(self, params, opt_state, grads, payloads):
        """Aggregate + update, choosing the cheapest lowering per mode
        (see :meth:`_leader_lowering`)."""
        lowering = self._leader_lowering()
        if lowering in ("psum_scatter", "dense_scatter"):
            if lowering == "psum_scatter":
                to_scatter = grads
                # a cast codec's wire_dtype narrows the scatter exactly
                # as comm_dtype would (same rationale as aggregate())
                wire = self.comm_dtype if self.comm_dtype is not None else (
                    getattr(self.code, "wire_dtype", None)
                )
            else:
                # decode the local payload to the codec-filtered dense
                # gradient; the scatter then sums those across workers
                leaves, treedef = jax.tree.flatten(grads)
                pls = treedef.flatten_up_to(payloads)
                to_scatter = jax.tree.unflatten(
                    treedef,
                    [self.code.decode(pl_, g.shape, g.dtype)
                     for g, pl_ in zip(leaves, pls)],
                )
                wire = self.comm_dtype
            grad_shards = leader_scatter_shards(
                to_scatter, self.axis_name, self.size, wire, self.average
            )
            if self.clip_norm:
                # shards partition the aggregated gradient: the global
                # norm is the psum of shard sum-squares (model-sharded
                # leaves additionally psum over their model axes)
                grad_shards = clip_by_global_norm(
                    grad_shards, self.clip_norm, self.axis_name,
                    self._leaf_clip_axes(),
                )
            return leader_shard_update(
                params, opt_state, grad_shards, self._update_fn, self.hyper,
                self.axis_name,
            )
        summed = self._aggregate(grads, payloads)
        return self._update(params, opt_state, summed)

    def _fused_allreduce_tree(self, grads, codec_state):
        """Per-leaf collective-protocol aggregation (codec declares
        ``supports_fused_allreduce``, e.g. PowerSGD's two-psum shared-Q
        form): returns ``(summed, new_codec_state)``. Runs inside
        shard_map; the module-level :func:`fused_allreduce_tree` is the
        one implementation (dp.py's functional step shares it)."""
        return fused_allreduce_tree(
            self.code, grads, codec_state, self.axis_name, self.average,
            self.size, self.comm_dtype,
            leaf_axes=None if self._uniform_agg else self._leaf_agg_axes,
            leaf_sizes=None if self._uniform_agg else self._leaf_agg_sizes,
        )

    def _encode_aggregate_update(self, params, opt_state, codec_state,
                                 grads, rng):
        """The ONE seam every step builder (fused, accum, grads-only,
        scan) lowers through: encode → aggregate → update, dispatching
        on the codec's collective capability."""
        if self.code.supports_fused_allreduce:
            summed, new_codec_state = self._fused_allreduce_tree(
                grads, codec_state
            )
            new_params, new_opt_state = self._update(params, opt_state, summed)
            return new_params, new_opt_state, new_codec_state
        if self._bucket_plan is not None:
            return self._bucketed_encode_aggregate_update(
                params, opt_state, codec_state, grads, rng
            )
        payloads, new_codec_state = self._encode_tree(grads, codec_state, rng)
        new_params, new_opt_state = self._aggregate_update(
            params, opt_state, grads, payloads
        )
        return new_params, new_opt_state, new_codec_state

    def _numerics_vec(self, old_params, new_params, grads, codec_state):
        """On-device numerics statistics, computed INSIDE the lowered
        step (runs under shard_map; XLA fuses the reductions into the
        surrounding program). Returns one f32 vector::

            [grad_sumsq, nonfinite, update_sumsq, param_sumsq,
             ef_residual_sumsq, *per_bucket_sumsq]

        grad sums are finite-masked (a NaN element must not erase the
        healthy part's norm) and psum'd across the data axis — the
        GLOBAL gradient energy and total NaN/Inf count; update/param
        sums read the replicated params, no collective needed."""
        def finite_sumsq(x):
            xf = x.astype(jnp.float32)
            return jnp.sum(jnp.square(jnp.where(jnp.isfinite(xf), xf, 0.0)))

        leaves = jax.tree.leaves(grads)
        gss = sum(finite_sumsq(g) for g in leaves)
        nonf = sum(
            jnp.sum(~jnp.isfinite(g.astype(jnp.float32))).astype(jnp.float32)
            for g in leaves
        )
        gss = lax.psum(gss, self.axis_name)
        nonf = lax.psum(nonf, self.axis_name)
        upd = sum(
            jnp.sum(jnp.square((n.astype(jnp.float32)
                                - o.astype(jnp.float32))))
            for o, n in zip(jax.tree.leaves(old_params),
                            jax.tree.leaves(new_params))
        )
        psq = sum(
            jnp.sum(jnp.square(o.astype(jnp.float32)))
            for o in jax.tree.leaves(old_params)
        )
        if isinstance(self.code, ErrorFeedback):
            flat_states = jax.tree.structure(self.params).flatten_up_to(
                codec_state
            )
            ef = sum(
                jnp.sum(jnp.square(st["memory"].astype(jnp.float32)))
                for st in flat_states
            )
            ef = lax.psum(ef, self.axis_name)
        else:
            ef = jnp.float32(0.0)
        parts = [gss, nonf, upd, psq, ef]
        if self._bucket_plan is not None:
            parts.extend(
                lax.psum(finite_sumsq(b), self.axis_name)
                for b in flatten_into_buckets(self._bucket_plan, grads)
            )
        return jnp.stack([jnp.asarray(p, jnp.float32) for p in parts])

    def _fill_numerics(self, data: Dict[str, float], nvec) -> None:
        """Unpack the fetched stats vector into the step's metrics dict
        (the one device fetch the numerics leg costs per step)."""
        v = np.asarray(nvec, np.float32)
        data["grad_norm"] = float(np.sqrt(v[0]))
        data["nonfinite_total"] = float(v[1])
        data["update_ratio"] = float(np.sqrt(v[2])) / max(
            float(np.sqrt(v[3])), 1e-30
        )
        if isinstance(self.code, ErrorFeedback):
            data["ef_residual_norm"] = float(np.sqrt(v[4]))
        if self._bucket_plan is not None:
            data["bucket_grad_norms"] = [
                float(np.sqrt(x)) for x in v[5:]
            ]

    def _opt_state_spec(self):
        """shard_map PartitionSpec pytree for the optimizer state: sharded
        over the mesh axis in leader mode (ZeRO-1); with param_specs the
        params-mirroring fields (momentum/adam moments) inherit each
        param's model sharding; replicated otherwise."""
        if self.mode == "leader":
            return leader_state_spec(
                self.opt_state, self.axis_name,
                self.param_specs if self._model_parallel else None,
            )
        if not self._model_parallel:
            return P()
        if isinstance(self.opt_state, AdafactorState):
            # factored moments are NOT param-shaped: v_row/v_col carry
            # the leaf's spec minus the deleted (unsharded) factored
            # dim — a replicated spec here broadcasts global state
            # against shard-local updates (shape corruption)
            return adafactor_state_specs(self.params, self.param_specs)
        ptd = jax.tree.structure(self.params)
        pshapes = [x.shape for x in jax.tree.leaves(self.params)]

        def field_spec(val):
            lv = jax.tree.leaves(val)
            if (jax.tree.structure(val) == ptd
                    and [x.shape for x in lv] == pshapes):
                return self.param_specs
            return jax.tree.map(lambda _: P(), val)

        return type(self.opt_state)(*[field_spec(v) for v in self.opt_state])

    # -- compiled step builders -------------------------------------------
    def _build_instrumented_stages(self, loss_fn, has_aux: bool = False,
                                   accum_steps: int = 0):
        """Pipeline as four separately-dispatched programs so host timers
        can fill the reference's per-stage schema (``ps.py:116-148``) with
        real wall times: encode → collective → decode+sum → update.
        Slower than the fused path (extra dispatches + no cross-stage
        fusion); for measurement, not production.

        ``has_aux`` stages the aux pmean into the grad stage (mutable-state
        models under instrument, VERDICT r3 item 8). ``accum_steps > 0``
        makes the grad stage the microbatch-accumulation scan — one fused
        program by design, so instrument reports its total wall plus a
        per-microbatch mean, while the encode/comm/decode/update stages
        time exactly as in the plain step."""
        axis = self.axis_name
        state_spec = jax.tree.map(lambda _: P(axis), self.codec_state)
        grads_spec = jax.tree.map(lambda _: P(axis), self.params)

        if accum_steps:
            def grad_spmd(params, batches):
                loss, grads = _accumulate_grads(
                    loss_fn, accum_steps, params, batches, axis,
                    reduce_loss=self._reduce_loss,
                )
                return loss, jax.tree.map(lambda g: g[None], grads)

            grad_in, grad_out = (P(), P(None, axis)), (P(), grads_spec)
        elif has_aux:
            def grad_spmd(params, aux, batch):
                (loss, new_aux), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params, aux, batch)
                new_aux = jax.tree.map(lambda x: lax.pmean(x, axis), new_aux)
                return (
                    self._reduce_loss(loss),
                    jax.tree.map(lambda g: g[None], grads),
                    new_aux,
                )

            grad_in, grad_out = (P(), P(), P(axis)), (P(), grads_spec, P())
        else:
            def grad_spmd(params, batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                return self._reduce_loss(loss), jax.tree.map(
                    lambda g: g[None], grads
                )

            grad_in, grad_out = (P(), P(axis)), (P(), grads_spec)

        grad_fn = jax.jit(
            jax.shard_map(
                grad_spmd, mesh=self.mesh, in_specs=grad_in,
                out_specs=grad_out, check_vma=False,
            )
        ) if loss_fn is not None else None

        def encode_spmd(grads_stacked, codec_state, rng):
            grads = jax.tree.map(lambda x: x[0], grads_stacked)
            payloads, new_state = encode_tree(self.code, grads, codec_state, rng, axis)
            return jax.tree.map(lambda x: x[None], payloads), new_state

        payload_spec = jax.tree.map(lambda _: P(axis), self._payload_struct())
        encode_fn = jax.jit(
            jax.shard_map(
                encode_spmd, mesh=self.mesh,
                in_specs=(grads_spec, state_spec, P()),
                out_specs=(payload_spec, state_spec),
                check_vma=False,
            )
        )

        def gather_spmd(payloads_stacked):
            local = jax.tree.map(lambda x: x[0], payloads_stacked)
            return jax.tree.map(lambda x: lax.all_gather(x, axis), local)

        def sum_spmd(grads_stacked):
            grads = jax.tree.map(lambda x: x[0], grads_stacked)
            if self._bucket_plan is not None:
                # measure the same launch-fused collective topology the
                # fused step runs (one psum per bucket, not per leaf)
                return bucketed_aggregate(
                    self.code, grads, self._bucket_plan, axis, False,
                    self.size, self.comm_dtype,
                )
            return aggregate(
                self.code, grads, None, axis, False, self.size, self.comm_dtype
            )

        def update_spmd(params, opt_state, summed):
            if self.average:
                summed = jax.tree.map(lambda x: x / self.size, summed)
            # self._update includes the mode='leader' broadcast, so the
            # instrumented optim_step_time covers the same collective the
            # fused path pays; run under shard_map so the axis is bound.
            return self._update(params, opt_state, summed)

        opt_spec = self._opt_state_spec()
        update_fn_impl = jax.shard_map(
            update_spmd, mesh=self.mesh, in_specs=(P(), opt_spec, P()),
            out_specs=(P(), opt_spec), check_vma=False,
        )

        return {
            "grad": grad_fn,
            "encode": encode_fn,
            "gather": jax.jit(
                jax.shard_map(
                    gather_spmd, mesh=self.mesh,
                    in_specs=(payload_spec,),
                    out_specs=P(), check_vma=False,
                )
            ),
            "psum": jax.jit(
                jax.shard_map(
                    sum_spmd, mesh=self.mesh, in_specs=(grads_spec,),
                    out_specs=P(), check_vma=False,
                )
            ),
            "decode": jax.jit(
                lambda gathered: jax.tree.unflatten(
                    jax.tree.structure(self.params),
                    [
                        decode_sum_payloads(self.code, pl, p.shape, p.dtype)
                        for p, pl in zip(
                            jax.tree.leaves(self.params),
                            jax.tree.structure(self.params).flatten_up_to(gathered),
                        )
                    ],
                )
            ),
            "update": jax.jit(update_fn_impl),
        }

    def _payload_struct(self):
        """Shape-structs of the stacked (leading local-shard axis of 1)
        per-worker payload pytree, used as shard_map out_specs prefix."""
        def leaf(p, sp):
            lshape = _local_shape(p.shape, sp, self.mesh)
            payload, _ = jax.eval_shape(
                lambda: self.code.encode(
                    jnp.zeros(lshape, p.dtype),
                    self.code.init_state(lshape, p.dtype),
                    jax.random.key(0) if self.code.needs_rng else None,
                )
            )
            return jax.tree.map(
                lambda s: jax.ShapeDtypeStruct((1,) + s.shape, s.dtype), payload
            )
        return jax.tree.map(leaf, self.params, self.param_specs)

    def _step_instrumented(self, data, rng, grads=None, loss_fn=None,
                           batch=None, aux_state=None, microbatches=None):
        """Staged pipeline with host-side timing (reference schema,
        ``ps.py:116-148``)."""
        has_aux = aux_state is not None
        accum_steps = (
            int(jax.tree.leaves(microbatches)[0].shape[0])
            if microbatches is not None else 0
        )
        key = ("instr", _fn_cache_key(loss_fn), has_aux, accum_steps)
        if key not in self._compiled:
            self._compiled[key] = self._build_instrumented_stages(
                loss_fn, has_aux, accum_steps
            )
        stages = self._compiled[key]
        timer = time.perf_counter
        loss = None

        # the staged pipeline's collective topology differs from the
        # fused lowering _schema_dict describes (it always full-psums or
        # payload-gathers; never the dense/psum scatter): relabel so the
        # reported bytes match the comm_wait actually measured
        w, frac = self.size, (self.size - 1) / self.size
        n = float(_tree_bytes(self.params))
        if self.code.supports_psum:
            wire_dt = self.comm_dtype if self.comm_dtype is not None else (
                getattr(self.code, "wire_dtype", None)
            )
            data["wire_lowering"] = "psum_staged"
            data["wire_bytes_per_worker"] = 2 * frac * self._tree_wire_bytes(
                wire_dt
            )
        else:
            # the staged encode/gather stages run PER LEAF even when a
            # bucket plan is active (only the psum stage is bucketed), so
            # the reported bytes/launches must describe the per-leaf
            # topology actually measured — not the fused step's buckets
            data["wire_lowering"] = "payload_gather_staged"
            data["wire_bytes_per_worker"] = (
                (w - 1) * self._payload_bytes_per_leaf
            )
            data["packaged_bytes"] = self._payload_bytes_per_leaf
            data["bucket_count"] = 0.0
            data["agg_launches"] = float(len(self._spec_leaves))
        if self.mode == "leader":
            # the staged update stage all_gathers the sharded params back
            data["wire_bytes_per_worker"] += frac * n

        if accum_steps:
            t0 = timer()
            loss, grads = stages["grad"](self.params, microbatches)
            jax.block_until_ready(grads)
            data["grad_time"] = timer() - t0
            # the scan is one fused program by design; the per-microbatch
            # mean is the documented estimate, not a separable wall
            data["grad_time_per_microbatch"] = data["grad_time"] / accum_steps
        elif loss_fn is not None:
            t0 = timer()
            if has_aux:
                loss, grads, new_aux = stages["grad"](
                    self.params, aux_state, batch
                )
                self.aux_state = new_aux
            else:
                loss, grads = stages["grad"](self.params, batch)
            jax.block_until_ready(grads)
            data["grad_time"] = timer() - t0

        t0 = timer()
        payloads, new_codec_state = stages["encode"](grads, self.codec_state, rng)
        jax.block_until_ready(payloads)
        data["code_wait"] = timer() - t0          # reference ps.py:138

        if self.code.supports_psum:
            t0 = timer()
            summed = stages["psum"](grads)
            jax.block_until_ready(summed)
            data["comm_wait"] = timer() - t0      # reference ps.py:162
        else:
            t0 = timer()
            gathered = stages["gather"](payloads)
            data["isend_time"] = timer() - t0     # dispatch (ps.py:148)
            jax.block_until_ready(gathered)
            data["comm_wait"] = timer() - t0
            t0 = timer()
            summed = stages["decode"](gathered)
            jax.block_until_ready(summed)
            data["decode_time"] = timer() - t0    # reference ps.py:168

        t0 = timer()
        self.params, self.opt_state = stages["update"](
            self.params, self.opt_state, summed
        )
        jax.block_until_ready(self.params)
        data["optim_step_time"] = timer() - t0    # reference ps.py:191
        self.codec_state = new_codec_state
        return loss

    def _build_grad_step(self, loss_fn, has_aux: bool = False):
        """Fused grad→encode→collective→decode→update step.

        With ``has_aux``, ``loss_fn(params, aux_state, batch) -> (loss,
        new_aux_state)`` supports mutable-state models (flax
        ``batch_stats``): each step's per-worker aux is cross-replica
        averaged with ``pmean``. By default that averages only the
        *running* stats — normalization inside the forward still uses
        per-replica batch statistics (plain per-device BN). For TRUE
        SyncBatchNorm semantics, build the model with its BN axis bound
        to this optimizer's data axis (e.g. ``ResNet(norm='batch',
        bn_axis='data')``): flax's BatchNorm then psum-averages the batch
        statistics across replicas inside this shard_map, matching a
        single device seeing the global batch (equivalence tested in
        ``tests/test_models.py::test_syncbn_matches_global_batch_oracle``)."""
        axis = self.axis_name

        def spmd(params, opt_state, codec_state, batch, rng, *maybe_aux):
            if has_aux:
                (loss, new_aux), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params, maybe_aux[0], batch)
                new_aux = jax.tree.map(lambda x: lax.pmean(x, axis), new_aux)
            else:
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                new_aux = ()
            loss = self._reduce_loss(loss)
            new_params, new_opt_state, new_codec_state = (
                self._encode_aggregate_update(
                    params, opt_state, codec_state, grads, rng
                )
            )
            out = (new_params, new_opt_state, new_codec_state, loss, new_aux)
            if self.numerics:
                out += (self._numerics_vec(params, new_params, grads,
                                           new_codec_state),)
            return out

        state_spec = self._codec_spec
        opt_spec = self._opt_state_spec()
        pspec = self.param_specs if self._model_parallel else P()
        in_specs = (pspec, opt_spec, state_spec, self.batch_spec, P()) + (
            (P(),) if has_aux else ()
        )
        out_specs = (pspec, opt_spec, state_spec, P(), P()) + (
            (P(),) if self.numerics else ()
        )
        return jax.jit(
            jax.shard_map(
                spmd,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            ),
            # in-place params/state update on device: the outputs reuse
            # the donated input buffers, cutting peak HBM by one
            # params+opt-state copy (see donate_buffers in __init__)
            donate_argnums=(0, 1, 2) if self.donate_buffers else (),
        )

    def _build_accum_grad_step(self, loss_fn, accum_steps: int):
        """Gradient accumulation: each worker scans ``accum_steps``
        microbatches, summing local grads, then one aggregate+update.
        Trades HBM (no giant activation batch) for sequential compute —
        the standard big-model batch-scaling tool the reference never
        needed at MNIST scale."""
        axis = self.axis_name

        def spmd(params, opt_state, codec_state, batches, rng):
            loss, grads = _accumulate_grads(
                loss_fn, accum_steps, params, batches, axis,
                reduce_loss=self._reduce_loss,
            )
            new_params, new_opt_state, new_codec_state = (
                self._encode_aggregate_update(
                    params, opt_state, codec_state, grads, rng
                )
            )
            out = (new_params, new_opt_state, new_codec_state, loss)
            if self.numerics:
                out += (self._numerics_vec(params, new_params, grads,
                                           new_codec_state),)
            return out

        state_spec = self._codec_spec
        opt_spec = self._opt_state_spec()
        pspec = self.param_specs if self._model_parallel else P()
        mb_spec = P(*((None,) + tuple(self.batch_spec)))
        out_specs = (pspec, opt_spec, state_spec, P()) + (
            (P(),) if self.numerics else ()
        )
        return jax.jit(
            jax.shard_map(
                spmd,
                mesh=self.mesh,
                in_specs=(pspec, opt_spec, state_spec, mb_spec, P()),
                out_specs=out_specs,
                check_vma=False,
            ),
            donate_argnums=(0, 1, 2) if self.donate_buffers else (),
        )

    def step_memory_analysis(
        self, loss_fn: Callable, batch: PyTree, rng=None,
        aux_state: PyTree = None,
    ) -> Dict[str, Optional[int]]:
        """HBM footprint of the fused step from XLA's own buffer
        assignment (``compiled.memory_analysis()``), independent of
        runtime allocator stats — some PJRT plugins (e.g. the tunneled
        axon TPU) return no ``memory_stats()``, and this is the honest
        substitute: ``donate_buffers`` shows up as
        ``alias_size_in_bytes`` (outputs re-using argument buffers), so
        ``argument + output + temp - alias`` estimates the step's peak
        working set either way. Pass ``aux_state`` iff the step does
        (the loss_fn signature changes with it). NOTE the first call
        per loss_fn pays a full AOT compile — ``jitted.lower()`` does
        not consult the jit dispatch cache — so the compiled object is
        memoized here for repeat calls."""
        has_aux = aux_state is not None
        key = ("grad", _fn_cache_key(loss_fn), has_aux)
        if key not in self._compiled:
            self._compiled[key] = self._build_grad_step(loss_fn, has_aux)
        rng = jax.random.key(0) if rng is None else rng
        extra = (aux_state,) if has_aux else ()
        # the batch's avals join the key — jit keys its dispatch cache
        # the same way, and without them a second call with a larger
        # batch would silently return the first batch's footprint
        batch_avals = tuple(
            (getattr(l, "shape", ()), str(jnp.asarray(l).dtype))
            for l in jax.tree.leaves((batch,) + extra)
        )
        ma_key = ("memory_analysis",) + key + (batch_avals,)
        if ma_key not in self._compiled:
            self._compiled[ma_key] = self._compiled[key].lower(
                self.params, self.opt_state, self.codec_state, batch, rng,
                *extra
            ).compile()
        ma = self._compiled[ma_key].memory_analysis()
        out = {
            k: int(getattr(ma, k))
            for k in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "alias_size_in_bytes",
                      "generated_code_size_in_bytes")
            if getattr(ma, k, None) is not None
        }
        if {"argument_size_in_bytes", "output_size_in_bytes",
                "temp_size_in_bytes"} <= out.keys():
            out["estimated_peak_bytes"] = (
                out["argument_size_in_bytes"] + out["output_size_in_bytes"]
                + out["temp_size_in_bytes"] - out.get("alias_size_in_bytes", 0)
            )
        return out

    def step_accumulate(
        self, loss_fn: Callable, microbatches: PyTree, *,
        profile: bool = False,
    ) -> Tuple[jax.Array, Dict[str, float]]:
        """One optimizer step over ``accum_steps`` microbatches per worker.
        ``microbatches`` leaves are ``[accum_steps, global_batch, ...]``;
        returns ``(mean_loss, data)``.

        ``instrument=True`` stage-times this path like :meth:`step`: the
        accumulation scan is one fused program (grad stage), timed whole
        with a per-microbatch mean in ``grad_time_per_microbatch``; the
        encode/comm/decode/update stages get real per-stage walls.
        ``profile=True`` instead traces the fully-fused program and fills
        ``comm_wait`` with the real per-device collective time."""
        accum_steps = int(jax.tree.leaves(microbatches)[0].shape[0])
        if self.instrument:
            if profile:
                raise ValueError(
                    "profile=True and instrument=True are mutually "
                    "exclusive: instrument runs a staged pipeline (host "
                    "walls per stage) while profile traces the fused "
                    "program — construct the optimizer without "
                    "instrument=True to use profile"
                )
            t0 = time.perf_counter()
            data = self._schema_dict()
            data["accum_steps"] = float(accum_steps)
            self._rng, rng = jax.random.split(self._rng)
            loss = self._step_instrumented(
                data, rng, loss_fn=loss_fn, microbatches=microbatches
            )
            self._step_count += 1
            data["step_time"] = time.perf_counter() - t0
            self._record_step("ps.step_accumulate", data)
            return loss, data
        key = ("accum", _fn_cache_key(loss_fn), accum_steps)
        if key not in self._compiled:
            self._compiled[key] = self._build_accum_grad_step(loss_fn, accum_steps)
        t0 = time.perf_counter()
        data = self._schema_dict()
        data["accum_steps"] = float(accum_steps)
        self._rng, rng = jax.random.split(self._rng)
        call = lambda: self._compiled[key](
            self.params, self.opt_state, self.codec_state, microbatches, rng
        )
        if profile:
            out, _ = self._profiled_call(
                call, data,
                lowered=lambda: self._compiled[key].lower(
                    self.params, self.opt_state, self.codec_state,
                    microbatches, rng).as_text())
        else:
            out = call()
        if self.numerics:
            (self.params, self.opt_state, self.codec_state, loss,
             nvec) = out
            self._fill_numerics(data, nvec)
        else:
            self.params, self.opt_state, self.codec_state, loss = out
        jax.block_until_ready(self.params)
        self._step_count += 1
        data["step_time"] = time.perf_counter() - t0
        self._record_step("ps.step_accumulate", data)
        return loss, data

    def _build_grads_only_step(self):
        """Aggregation-only step: caller supplies per-worker grads stacked
        on a leading [world] axis (the reference's usage: backward already
        ran, ``step`` only aggregates + updates)."""
        axis = self.axis_name

        def spmd(params, opt_state, codec_state, grads_stacked, rng):
            grads = jax.tree.map(lambda x: x[0], grads_stacked)  # local shard
            new_params, new_opt_state, new_codec_state = (
                self._encode_aggregate_update(
                    params, opt_state, codec_state, grads, rng
                )
            )
            out = (new_params, new_opt_state, new_codec_state)
            if self.numerics:
                out += (self._numerics_vec(params, new_params, grads,
                                           new_codec_state),)
            return out

        state_spec = self._codec_spec
        grads_spec = jax.tree.map(lambda _: P(axis), self.params)
        opt_spec = self._opt_state_spec()
        out_specs = (P(), opt_spec, state_spec) + (
            (P(),) if self.numerics else ()
        )
        return jax.jit(
            jax.shard_map(
                spmd,
                mesh=self.mesh,
                in_specs=(P(), opt_spec, state_spec, grads_spec, P()),
                out_specs=out_specs,
                check_vma=False,
            ),
            donate_argnums=(0, 1, 2) if self.donate_buffers else (),
        )

    def _schema_dict(self) -> Dict[str, float]:
        """The reference's per-step metrics schema (``ps.py:116-148,
        162-191``), initialized; step paths fill in what they can
        observe. The byte fields are static per instance, computed once
        in ``__init__`` (``payload_bits`` eval-shapes every leaf — too
        expensive to re-derive per step)."""
        lowering, wire_bytes = self._wire_accounting
        return {
            "code_wait": 0.0,
            "iallgather_prepare_time": 0.0,  # compile-time now (static shapes)
            "isend_time": 0.0,
            "comm_wait": 0.0,
            "decode_time": 0.0,
            "optim_step_time": 0.0,
            "msg_bytes": float(_tree_bytes(self.params)),
            "packaged_bytes": self._payload_bytes,
            "wire_lowering": lowering,
            "wire_bytes_per_worker": wire_bytes,
            # flat-bucket aggregation accounting (bucketing.py): 0 buckets
            # means the per-leaf path; agg_launches is the per-step
            # collective launch count of the aggregation stage
            "bucket_count": float(
                self._bucket_plan.num_buckets
                if self._bucket_plan is not None else 0
            ),
            "bucket_bytes_total": self._bucket_bytes_total,
            "agg_launches": float(self._agg_units),
        }

    def _record_step(self, name: str, data: Dict[str, float]) -> None:
        """Mirror one step's metrics dict into the run-wide
        FlightRecorder as a span ending now — the reference's returned-
        timings contract joining the unified timeline. Disabled
        telemetry costs exactly this method's None-check."""
        rec = get_recorder()
        if rec is None:
            return
        dur = float(data.get("step_time", 0.0))
        rec.event(
            name, kind="span", ts=time.monotonic() - dur, dur=dur,
            step=self._step_count,
            **{k: v for k, v in data.items()
               if isinstance(v, (int, float, str))},
        )

    # -- public API --------------------------------------------------------
    def step(
        self,
        grads: Optional[PyTree] = None,
        *,
        loss_fn: Optional[Callable] = None,
        batch: Optional[PyTree] = None,
        aux_state: Optional[PyTree] = None,
        closure: Optional[Callable] = None,
        profile: bool = False,
    ) -> Tuple[Optional[jax.Array], Dict[str, float]]:
        """Run one distributed step; returns ``(loss, data)`` exactly like
        the reference (``ps.py:193`` — its known deviation from the torch
        Optimizer contract, kept deliberately for API parity).

        Either pass ``loss_fn`` + ``batch`` (fused grad+aggregate+update),
        or pass ``grads`` stacked per-worker on a leading ``[world]`` axis
        (aggregation-only, the reference's own division of labor).
        ``closure`` is accepted for signature parity (``ps.py:110-112``)
        and invoked for its loss value if given.

        ``profile=True`` traces THIS step with ``jax.profiler`` and fills
        ``comm_wait`` (the reference's collective-wait metric,
        ``ps.py:162``) with the fused program's real per-device mean
        communication time — the comm/compute split ``instrument=True``
        cannot measure because it splits the program. Extra keys
        ``profile_device_busy``/``profile_compute``/``profile_devices``
        carry the rest of the split. For per-stage encode/decode/update
        walls, use ``instrument=True`` instead.
        """
        t0 = time.perf_counter()
        data = self._schema_dict()
        loss = None
        self._rng, rng = jax.random.split(self._rng)

        if self.instrument:
            if profile:
                raise ValueError(
                    "profile=True and instrument=True are mutually "
                    "exclusive: instrument runs a staged pipeline (host "
                    "walls per stage) while profile traces the fused "
                    "program — construct the optimizer without "
                    "instrument=True to use profile"
                )
            if loss_fn is None and grads is None:
                raise ValueError("pass grads or loss_fn+batch")
            if loss_fn is not None and batch is None:
                raise ValueError("loss_fn requires batch")
            if loss_fn is None and aux_state is not None:
                raise NotImplementedError(
                    "aux_state requires the loss_fn path (grads-only steps "
                    "have no forward pass to produce new aux state)"
                )
            loss = self._step_instrumented(
                data, rng, grads=grads, loss_fn=loss_fn, batch=batch,
                aux_state=aux_state,
            )
            if closure is not None:
                loss = closure()
            data["step_time"] = time.perf_counter() - t0
            self._step_count += 1
            self._record_step("ps.step", data)
            return loss, data

        if loss_fn is not None:
            if batch is None:
                raise ValueError("loss_fn requires batch")
            has_aux = aux_state is not None
            key = ("grad", _fn_cache_key(loss_fn), has_aux)
            if key not in self._compiled:
                self._compiled[key] = self._build_grad_step(loss_fn, has_aux)
            fn = self._compiled[key]
            extra = (aux_state,) if has_aux else ()
            call = lambda: fn(
                self.params, self.opt_state, self.codec_state, batch, rng, *extra
            )
            if profile:
                out, split = self._profiled_call(
                    call, data,
                    lowered=lambda: fn.lower(
                        self.params, self.opt_state, self.codec_state,
                        batch, rng, *extra).as_text())
            else:
                out = call()
            if self.numerics:
                (self.params, self.opt_state, self.codec_state, loss,
                 new_aux, nvec) = out
                self._fill_numerics(data, nvec)
            else:
                (self.params, self.opt_state, self.codec_state, loss,
                 new_aux) = out
            if has_aux:
                self.aux_state = new_aux
        elif grads is not None:
            if aux_state is not None:
                raise NotImplementedError(
                    "aux_state requires the loss_fn path (grads-only steps "
                    "have no forward pass to produce new aux state)"
                )
            if self._model_parallel:
                raise NotImplementedError(
                    "grads-only steps are not supported with param_specs: "
                    "a host-side [world, ...] gradient stack is ambiguous "
                    "for model-sharded leaves — use the loss_fn path"
                )
            key = ("grads-only",)
            if key not in self._compiled:
                self._compiled[key] = self._build_grads_only_step()
            fn = self._compiled[key]
            call = lambda: fn(
                self.params, self.opt_state, self.codec_state, grads, rng
            )
            if profile:
                out, split = self._profiled_call(
                    call, data,
                    lowered=lambda: fn.lower(
                        self.params, self.opt_state, self.codec_state,
                        grads, rng).as_text())
            else:
                out = call()
            if self.numerics:
                (self.params, self.opt_state, self.codec_state,
                 nvec) = out
                self._fill_numerics(data, nvec)
            else:
                self.params, self.opt_state, self.codec_state = out
        else:
            raise ValueError("pass grads or loss_fn+batch")

        if closure is not None:
            loss = closure()

        jax.block_until_ready(self.params)
        # The fused program has no separable comm/decode/update stages —
        # step_time is always a real measurement; profile=True adds the
        # trace-derived comm/compute split, and instrument=True (separate
        # mode) fills the remaining per-stage keys with host wall times.
        data["step_time"] = time.perf_counter() - t0
        self._step_count += 1
        self._record_step("ps.step", data)
        return loss, data

    def _profiled_call(self, call, data: Dict[str, float], lowered=None):
        """Run one compiled fused step under the JAX profiler and fill the
        reference's ``comm_wait`` (``ps.py:162``) with the program's real
        per-device mean collective time (VERDICT r2 item 6).  ``lowered``
        (a lazy lowered-text provider) arms the launch-counter fallback
        for participant counting — ``bucketing.count_collectives`` over
        the lowered program backstops a trace with no per-lane
        attribution at all."""
        from pytorch_ps_mpi_tpu.utils.tracing import profiled_device_split

        out, split = profiled_device_split(call, lowered=lowered)
        data["comm_wait"] = split["comm_s"]
        data["profile_device_busy"] = split["device_busy_s"]
        data["profile_compute"] = split["compute_s"]
        data["profile_devices"] = float(split["devices"])
        return out, split

    def state_dict(self) -> Dict[str, Any]:
        """Checkpointable state in this repo's schema (params/opt_state/
        codec_state/aux_state/step_count/rng) — the role of torch's
        ``Optimizer.state_dict()`` (which the reference inherited but never
        called, SURVEY §5.4), NOT its format: there is no
        ``state``/``param_groups`` layout and the dict holds live array
        references, not copies, so it is not interchangeable with torch
        checkpoints. Pair with ``utils.checkpoint.CheckpointManager`` for
        sharded on-disk saves."""
        return {
            "params": self.params,
            "opt_state": tuple(self.opt_state),
            "codec_state": self.codec_state,
            "aux_state": self.aux_state,
            "step_count": self._step_count,
            "rng_data": jax.random.key_data(self._rng),
        }

    def _decommit_restored(self, tree: PyTree) -> PyTree:
        """Make a restored checkpoint tree steppable on this mesh.

        A restore can hand back arrays committed to the WRONG device set
        (e.g. a single device from the numpy fallback, or a stale
        sharding), which the compiled shard_map step rejects. Leaves
        already committed to exactly this mesh's devices (the common
        orbax case — StandardRestore with a correctly-sharded template,
        incl. ZeRO-1's sharded opt_state) are kept as-is, zero copies;
        everything else is gathered to host numpy in ONE batched
        ``jax.device_get`` (uncommitted, so the next step reshards it)."""
        mesh_devs = set(self.mesh.devices.flat)
        leaves, treedef = jax.tree.flatten(tree)

        def keeps(x):
            if not hasattr(x, "ndim"):
                return True  # python scalar
            devs = getattr(x, "devices", None)
            if devs is None:
                return True  # host numpy already
            try:
                return set(devs()) == mesh_devs
            except Exception:
                return False

        flags = [keeps(l) for l in leaves]
        fetched = iter(jax.device_get(
            [l for l, k in zip(leaves, flags) if not k]
        ))
        out = [l if k else next(fetched) for l, k in zip(leaves, flags)]
        return jax.tree.unflatten(treedef, out)

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.params = self._decommit_restored(sd["params"])
        self.opt_state = type(self.opt_state)(
            *self._decommit_restored(tuple(sd["opt_state"]))
        )
        self.codec_state = self._decommit_restored(sd["codec_state"])
        self.aux_state = self._decommit_restored(sd.get("aux_state"))
        self._step_count = int(sd["step_count"])
        # rng too: a restored key committed to the restore sharding would
        # commit every subsequent step's rng arg and poison jit's device
        # resolution against uncommitted batches
        self._rng = jax.random.wrap_key_data(
            jnp.asarray(np.asarray(sd["rng_data"]))
        )

    def run_steps(
        self, loss_fn: Callable, batches: PyTree, *, unroll: int = 1
    ) -> Tuple[jax.Array, Dict[str, float]]:
        """Run N training steps as ONE fused XLA program (``lax.scan`` over
        the step pipeline inside shard_map), amortizing per-step host
        dispatch — the TPU-native answer to the reference's thread-pool
        overlap: nothing to overlap on the host because the host is out of
        the loop entirely.

        ``batches``: pytree whose leaves are stacked ``[n_steps,
        global_batch, ...]``. Returns ``(losses[n_steps], data)``.
        """
        axis = self.axis_name

        key = ("scan", _fn_cache_key(loss_fn), unroll)
        if key not in self._compiled:
            def spmd(params, opt_state, codec_state, batches, rng):
                def one_step(carry, batch_and_key):
                    params, opt_state, codec_state = carry
                    batch, rng = batch_and_key
                    loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                    loss = self._reduce_loss(loss)
                    params, opt_state, codec_state = (
                        self._encode_aggregate_update(
                            params, opt_state, codec_state, grads, rng
                        )
                    )
                    return (params, opt_state, codec_state), loss

                n_steps = jax.tree.leaves(batches)[0].shape[0]
                keys = jax.random.split(rng, n_steps)
                (params, opt_state, codec_state), losses = lax.scan(
                    one_step, (params, opt_state, codec_state), (batches, keys),
                    unroll=unroll,
                )
                return params, opt_state, codec_state, losses

            state_spec = self._codec_spec
            step_spec = P(*((None,) + tuple(self.batch_spec)))
            batch_spec = jax.tree.map(lambda _: step_spec, batches)
            opt_spec = self._opt_state_spec()
            pspec = self.param_specs if self._model_parallel else P()
            self._compiled[key] = jax.jit(
                jax.shard_map(
                    spmd,
                    mesh=self.mesh,
                    in_specs=(pspec, opt_spec, state_spec, batch_spec, P()),
                    out_specs=(pspec, opt_spec, state_spec, P()),
                    check_vma=False,
                ),
                donate_argnums=(0, 1, 2) if self.donate_buffers else (),
            )
        t0 = time.perf_counter()
        self._rng, rng = jax.random.split(self._rng)
        self.params, self.opt_state, self.codec_state, losses = self._compiled[key](
            self.params, self.opt_state, self.codec_state, batches, rng
        )
        jax.block_until_ready(self.params)
        n_steps = int(jax.tree.leaves(batches)[0].shape[0])
        self._step_count += n_steps
        wall = time.perf_counter() - t0
        data = {
            "step_time": wall / n_steps,
            "steps_per_sec": n_steps / wall,
            "n_steps": float(n_steps),
        }
        rec = get_recorder()
        if rec is not None:
            # ONE span for the fused scan (there are no separable
            # per-step host walls inside one XLA program)
            rec.event("ps.run_steps", kind="span",
                      ts=time.monotonic() - wall, dur=wall,
                      step=self._step_count, **data)
        return losses, data


class SGD(MPI_PS):
    """PS-fused SGD (reference ``ps.py:195-214``)."""

    def __init__(self, params, **kwargs):
        kwargs.setdefault("optim", "sgd")
        super().__init__(params, **kwargs)


class Adam(MPI_PS):
    """PS-fused Adam with amsgrad (reference ``ps.py:217-261``)."""

    def __init__(self, params, **kwargs):
        kwargs.setdefault("optim", "adam")
        super().__init__(params, **kwargs)


class Adafactor(MPI_PS):
    """PS-fused Adafactor (Shazeer & Stern 2018) — beyond the
    reference's SGD/Adam family: factored second moments make the
    optimizer state sublinear in params (``optim.py::adafactor_update``,
    optax-pinned), freeing the ~2x-params Adam state for batch size.
    Composes with codecs, accumulation, and model-parallel
    ``param_specs`` whose sharded axes avoid the factored (two
    largest) dims — the leading-stack-axis TP/PP convention — where
    the step is exactly shard-local-decomposable (row/col means stay
    local; the two per-leaf scalar reductions pmean over the model
    axes; oracle-equality proven in ``tests/test_ps_model_parallel``).
    Leader/ZeRO-1, factored-dim sharding, and EP layouts are rejected
    loudly (see the constructor guards)."""

    def __init__(self, params, **kwargs):
        kwargs.setdefault("optim", "adafactor")
        kwargs.setdefault("lr", None)  # paper's relative step size
        super().__init__(params, **kwargs)
