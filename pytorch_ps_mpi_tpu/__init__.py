"""tpu-ps: a TPU-native distributed-training framework.

Rebuilt from scratch on JAX/XLA/pjit with the capabilities of the reference
``stsievert/pytorch_ps_mpi`` (a mpi4py parameter-server layer for PyTorch,
see ``/root/reference``):

- a drop-in optimizer-style API (``MPI_PS`` / ``SGD`` / ``Adam``, mirroring
  the reference's public surface, reference ``__init__.py:1``) whose ``step``
  aggregates gradients across workers,
- two aggregation topologies (decentralized allgather-sum — the reference's
  live path, ``ps.py:75,140-161`` — and leader-PS gather+broadcast,
  ``mpi_comms.py:60-133``),
- an asynchronous bounded-staleness mode (AsySG-InCon, reference README),
- a pluggable gradient-codec interface (reference ``codings`` hook,
  ``ps.py:94,166``) with identity / top-k / random-k / int8 / sign codecs,
- fused SGD + Adam update rules (reference ``ps.py:195-261``),
- the per-step timing/bytes metrics schema (reference ``ps.py:116-148``).

Everything on-device runs under ``jax.jit``/``shard_map`` over a
``jax.sharding.Mesh``; collectives ride ICI (``psum``/``all_gather``/
``ppermute``) instead of MPI over Ethernet.
"""

from pytorch_ps_mpi_tpu.utils.compat import ensure_axis_size, ensure_shard_map

# before any module that references jax.shard_map / lax.axis_size
ensure_shard_map()
ensure_axis_size()

from pytorch_ps_mpi_tpu.ps import MPI_PS, Adafactor, Adam, SGD

__all__ = ["MPI_PS", "Adafactor", "Adam", "SGD"]
__version__ = "0.1.0"
