"""Pallas TPU kernels for the hot codec ops.

The native-code tier of the framework: where the reference leaned on
c-blosc's C compressor (``mpi_comms.py:25,29``) and ATen's CUDA kernels,
the TPU build uses Pallas kernels compiled to Mosaic — on-chip, fused,
VMEM-resident. Portable jnp fallbacks live next to each kernel and are
used automatically off-TPU (interpret mode on CPU test meshes).
"""

from pytorch_ps_mpi_tpu.ops.quant_pallas import quantize_int8, dequantize_int8
from pytorch_ps_mpi_tpu.ops.attention_pallas import (
    flash_attention,
    flash_supported,
)

__all__ = [
    "quantize_int8",
    "dequantize_int8",
    "flash_attention",
    "flash_supported",
]
