"""Shared constants/predicates for the Pallas kernel family."""

from __future__ import annotations

import jax

LANE = 128      # TPU lane width (last-dim tile)
SUBLANE = 8     # float32 sublane tile


def interpret() -> bool:
    """Run kernels in Pallas interpret mode off-TPU (CPU test meshes)."""
    return jax.default_backend() != "tpu"
