"""Fused 1-bit sign pack/unpack as Pallas TPU kernels.

The hot path of the SignSGD codec: pack 8 sign bits per byte (a true 32×
wire reduction) without leaving VMEM. The pure-jnp version materializes an
intermediate [n/8, 8] uint8 tensor in HBM; here the reshape → weight →
reduce pipeline runs per-tile on the VPU.

Layout: the flat float input is viewed as [rows, 8, 128] — 8 consecutive
*sublanes* fold into one packed row of 128 lanes, so packing is a
weighted sum over the middle axis and unpacking is a broadcast compare,
both native VPU shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.ops._common import LANE as _LANE
from pytorch_ps_mpi_tpu.ops._common import interpret as _interpret

_GROUP = 8 * _LANE  # one packed row of 128 bytes encodes 1024 signs


def _weights():
    # int32, not uint32: Mosaic has no unsigned reductions
    return (2 ** jnp.arange(8, dtype=jnp.int32))[None, :, None]


def _pack_kernel(x_ref, out_ref):
    x = x_ref[:]                                   # [rows, 8, 128] float32
    bits = (x >= 0).astype(jnp.int32)
    packed = (bits * _weights()).sum(axis=1)       # [rows, 128]
    out_ref[:] = packed.astype(jnp.uint8)


def _unpack_kernel(p_ref, out_ref):
    p = p_ref[:].astype(jnp.int32)                 # [rows, 128]
    bits = (p[:, None, :] & _weights()) > 0        # [rows, 8, 128]
    out_ref[:] = jnp.where(bits, 1.0, -1.0).astype(jnp.float32)


_BLOCK_ROWS = 256  # 256×8×128 f32 = 1 MiB per input tile — well under VMEM


def _encode_kernel(rows_total, x_ref, out_ref, sum_ref):
    """Fused encode: packed sign bits AND the |x| partial sum for the
    mean-|g| scale in ONE read of the gradient tile. The scalar SMEM
    accumulator is race-free across the sequential TPU grid; rows past
    ``rows_total`` (the ragged trailing block Pallas pads) are masked
    out of the sum (their packed bytes are garbage the caller never
    reads — the output is sliced to n/8 bytes)."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sum_ref[0, 0] = 0.0

    x = x_ref[:]                                   # [rows, 8, 128] f32
    bits = (x >= 0).astype(jnp.int32)
    out_ref[:] = (bits * _weights()).sum(axis=1).astype(jnp.uint8)
    rid = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) + i * _BLOCK_ROWS
    sum_ref[0, 0] += jnp.sum(jnp.where(rid < rows_total, jnp.abs(x), 0.0))


def encode_signs(flat: jax.Array):
    """float32[n] (n % 1024 == 0) -> (uint8[n/8] packed bits, f32 |x|
    sum). The fused form of ``mean(|g|)`` + ``pack_signs``: one gridded
    pass reads the gradient ONCE where the two-step encode reads it
    twice (the scale reduction, then the pack) — the memory-bound
    encode's traffic halves. The sum accumulates per-block partials
    sequentially in f32 (each block internally tree-reduced), so the
    derived mean may differ from ``jnp.mean`` in the last ulps —
    documented codec-config semantics, like the Pallas bit layout."""
    import functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = flat.shape[0]
    assert n % _GROUP == 0, n
    rows = n // _GROUP
    x3d = flat.reshape(rows, 8, _LANE)
    grid = ((rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS,)
    packed, total = pl.pallas_call(
        functools.partial(_encode_kernel, rows),
        out_shape=(jax.ShapeDtypeStruct((rows, _LANE), jnp.uint8),
                   jax.ShapeDtypeStruct((1, 1), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 8, _LANE), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=(pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)),
        interpret=_interpret(),
    )(x3d)
    return packed.reshape(n // 8), total[0, 0]


def pack_signs(flat: jax.Array) -> jax.Array:
    """float32[n] (n % 1024 == 0) -> uint8[n/8] of packed sign bits.
    Gridded over row tiles so arbitrarily large gradients stream through
    VMEM (Pallas pads the ragged trailing block)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = flat.shape[0]
    assert n % _GROUP == 0, n
    rows = n // _GROUP
    x3d = flat.reshape(rows, 8, _LANE)
    grid = ((rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS,)
    out = pl.pallas_call(
        _pack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 8, _LANE), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(x3d)
    return out.reshape(n // 8)


def unpack_signs(packed: jax.Array) -> jax.Array:
    """uint8[m] (m % 128 == 0) -> float32[8m] of ±1."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = packed.shape[0]
    assert m % _LANE == 0, m
    rows = m // _LANE
    p2d = packed.reshape(rows, _LANE)
    grid = ((rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS,)
    out = pl.pallas_call(
        _unpack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 8, _LANE), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, 8, _LANE), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(p2d)
    return out.reshape(m * 8)
