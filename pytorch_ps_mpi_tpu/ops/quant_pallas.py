"""Fused int8 symmetric quantize / dequantize as Pallas TPU kernels.

Replaces the reference's host-side blosc compress/decompress round-trip
(``mpi_comms.py:18-30``): the gradient never leaves the chip — abs-max
reduction, scale, round, clip and narrow all happen in VMEM in one pass.

On non-TPU backends (the 8-device CPU test mesh) the kernels run in
Pallas interpret mode; tiny shapes fall back to plain jnp to dodge
tiling-constraint edge cases.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_LANE = 128
_SUBLANE = 8
_TILE = _LANE * _SUBLANE  # min float32 tile, flattened


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _quantize_jnp(flat: jax.Array):
    scale = jnp.maximum(jnp.max(jnp.abs(flat)) / 127.0, 1e-12)
    q = jnp.clip(jnp.round(flat / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _quant_kernel(x_ref, q_ref, scale_ref):
    from jax.experimental import pallas as pl  # noqa: F401

    x = x_ref[:]
    scale = jnp.maximum(jnp.max(jnp.abs(x)) / 127.0, 1e-12)
    q_ref[:] = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    scale_ref[0, 0] = scale.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=())
def quantize_int8(flat: jax.Array):
    """flat float array -> (int8 codes, float32 scalar scale)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = flat.shape[0]
    if n % _TILE != 0 or n == 0:
        # Irregular sizes: XLA's fused jnp path is already near-optimal.
        return _quantize_jnp(flat)

    x2d = flat.reshape(n // _LANE, _LANE)
    q, scale = pl.pallas_call(
        _quant_kernel,
        out_shape=(
            jax.ShapeDtypeStruct(x2d.shape, jnp.int8),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        interpret=_interpret(),
    )(x2d)
    return q.reshape(n), scale[0, 0]


def _dequant_kernel(q_ref, scale_ref, out_ref):
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[0, 0]


@jax.jit
def dequantize_int8(q: jax.Array, scale: jax.Array):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = q.shape[0]
    if n % _TILE != 0 or n == 0:
        return q.astype(jnp.float32) * scale

    q2d = q.reshape(n // _LANE, _LANE)
    out = pl.pallas_call(
        _dequant_kernel,
        out_shape=jax.ShapeDtypeStruct(q2d.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(q2d, scale.reshape(1, 1).astype(jnp.float32))
    return out.reshape(n)
