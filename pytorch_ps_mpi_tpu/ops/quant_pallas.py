"""Fused int8 symmetric quantize / dequantize as Pallas TPU kernels.

Replaces the reference's host-side blosc compress/decompress round-trip
(``mpi_comms.py:18-30``): the gradient never leaves the chip — abs-max
reduction, scale, round, clip and narrow all happen in VMEM.

Two gridded passes so arbitrarily large gradients stream through VMEM
(a single-block version OOMs scoped VMEM beyond ~4M floats):
pass 1 reduces the global abs-max tile by tile into SMEM; pass 2 applies
the scalar scale per tile. TPU grids execute sequentially per core, so
the pass-1 accumulator is race-free.

On non-TPU backends (the 8-device CPU test mesh) the kernels run in
Pallas interpret mode; tiny/ragged shapes fall back to plain jnp.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.ops._common import LANE as _LANE, SUBLANE as _SUBLANE
from pytorch_ps_mpi_tpu.ops._common import interpret as _interpret

_TILE = _LANE * _SUBLANE   # min float32 tile, flattened
_BLOCK_ROWS = 1024         # 1024×128 f32 = 512 KiB per tile


def _quantize_jnp(flat: jax.Array):
    scale = jnp.maximum(jnp.max(jnp.abs(flat)) / 127.0, 1e-12)
    q = jnp.clip(jnp.round(flat / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _absmax_kernel(rows, block_rows, x_ref, out_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[0, 0] = 0.0

    x = x_ref[:]
    # The trailing grid step's block may extend past the array; Mosaic
    # fills the overhang with undefined values, which a max reduction must
    # never see — mask them to 0 (absmax-neutral) by global row index.
    row_ids = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) + i * block_rows
    blk = jnp.max(jnp.where(row_ids < rows, jnp.abs(x), 0.0))
    out_ref[0, 0] = jnp.maximum(out_ref[0, 0], blk)


def _quant_kernel(x_ref, scale_ref, q_ref):
    # scale is computed once on the host from the absmax pass; the kernel
    # only applies it, so quantize and dequantize can never drift
    q_ref[:] = jnp.clip(
        jnp.round(x_ref[:] / scale_ref[0, 0]), -127, 127
    ).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=())
def quantize_int8(flat: jax.Array):
    """flat float array -> (int8 codes, float32 scalar scale)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = flat.shape[0]
    if n % _TILE != 0 or n == 0:
        # Irregular sizes: XLA's fused jnp path is already near-optimal.
        return _quantize_jnp(flat)

    rows = n // _LANE  # multiple of _SUBLANE since n % _TILE == 0
    x2d = flat.reshape(rows, _LANE)
    # Shrink the block for small inputs so a 1024-element gradient isn't
    # padded 128x. A non-block-multiple row count needs no data copy: the
    # absmax kernel masks the ragged trailing block's undefined overhang
    # itself, and the quant kernel tolerates it (garbage in → garbage out,
    # never written past `rows` in the output).
    block_rows = min(_BLOCK_ROWS, rows)
    grid = ((rows + block_rows - 1) // block_rows,)

    absmax = pl.pallas_call(
        functools.partial(_absmax_kernel, rows, block_rows),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=_interpret(),
    )(x2d)
    scale = jnp.maximum(absmax[0, 0] / 127.0, 1e-12)

    q = pl.pallas_call(
        _quant_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.int8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(x2d, scale.reshape(1, 1))
    return q.reshape(n), scale


def _dequant_kernel(q_ref, scale_ref, out_ref):
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[0, 0]


@jax.jit
def dequantize_int8(q: jax.Array, scale: jax.Array):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = q.shape[0]
    if n % _TILE != 0 or n == 0:
        return q.astype(jnp.float32) * scale

    rows = n // _LANE
    block_rows = min(_BLOCK_ROWS, rows)
    q2d = q.reshape(rows, _LANE)
    grid = ((rows + block_rows - 1) // block_rows,)
    out = pl.pallas_call(
        _dequant_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(q2d, scale.reshape(1, 1).astype(jnp.float32))
    return out.reshape(n)
