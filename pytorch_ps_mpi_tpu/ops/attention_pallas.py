"""Flash attention as a Pallas TPU kernel (forward AND backward).

The dense-attention path this replaces (``models/bert.py``: plain einsum
softmax) materializes the [l, l] score matrix in HBM per head — the
classic O(L²) memory wall, and the reason BERT MFU collapses past s128
(VERDICT r3 weak #4). This kernel is the standard online-softmax tiling
(Dao et al.; Milakov & Gimelshein max-shift streaming): q tiles stay
resident in VMEM while k/v tiles stream past; the score block, running
row-max, exp-sum and output accumulator never leave VMEM; HBM traffic
drops from O(L²) to O(L·d).

Design choices:

- **Grid** ``(batch*heads, q_tiles, k_tiles)`` — TPU grids execute
  sequentially per core with the last dimension innermost, so the VMEM
  scratch accumulators (acc, running max m, running sum l) persist
  across the k sweep of one q tile; initialized at ``k==0``, finalized
  (normalize + logsumexp write) at ``k==nk-1``.
- **Dynamic position offsets** (SMEM scalars): the causal mask is
  evaluated in GLOBAL coordinates ``k_off + col <= q_off + row``, so the
  same compiled kernel serves dense attention (offsets 0) and ring
  attention's rotating blocks (``parallel/ring.py`` passes the block's
  traced global offset; a fully-future block masks itself to nothing).
  Fully-masked k tiles are skipped with a predicated ``pl.when`` — the
  causal dense case does half the work, ring's future blocks cost ~0.
- **Backward is two Pallas kernels** (dq over k tiles; dk/dv over q
  tiles) recomputing p from the saved logsumexp — no O(L²) residual.
  The custom VJP also accepts a cotangent for the returned logsumexp
  (folded into ``Dm = D - g_lse``), which is what lets ring attention
  combine per-block normalized outputs differentiably.
- **MXU precision**: scores and accumulators are f32
  (``preferred_element_type``); the p@v contraction runs in the input
  dtype (bf16 on TPU) like standard flash implementations.

Off-TPU the kernel runs in Pallas interpret mode (CPU test meshes);
``flash_attention`` falls back to a jnp oracle for shapes the tiling
cannot serve (sequence not a multiple of the minimal sublane tile).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_ps_mpi_tpu.ops._common import LANE as _LANE
from pytorch_ps_mpi_tpu.ops._common import interpret as _interpret

_MASKED = -1e30        # additive mask value
_MASK_THRESH = -1e29   # "this score was masked" test (real scores are tiny)

# Minimum sequence length at which 'full' attention auto-dispatches to
# the kernel. Measured on TPU v5e (tpu_v5e_2026-07-31 sweep +
# benchmarks/flash_tune.py): XLA's fused dense attention wins short
# sequences — its matmuls batch across heads on the MXU while the kernel
# pays a sequential batch*heads grid — and the kernel takes over where
# O(L^2) score materialization dominates. Overridable for re-measurement
# on other chip generations (FLASH_MIN_SEQ env var).
import os as _os

FLASH_MIN_SEQ = int(_os.environ.get("FLASH_MIN_SEQ", "512"))


def _pick_block(length: int, target: int, min_block: int = 8) -> Optional[int]:
    """Largest power-of-two block <= target that divides ``length``
    (>= ``min_block``: 8 = the f32 sublane; bf16 tiles need 16);
    None if the length cannot tile."""
    b = 1
    while b * 2 <= min(target, length) and length % (b * 2) == 0:
        b *= 2
    return b if b >= min_block and length % b == 0 else None


def _min_block_for(dtype) -> int:
    """Minimal sublane tile for the dtype (f32: 8, bf16/f16: 16)."""
    return 16 if jnp.dtype(dtype).itemsize < 4 else 8


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m_sc, l_sc, *, causal, scale, bq, bk, nk):
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _MASKED)
        l_sc[:] = jnp.zeros_like(l_sc)

    q_start = qo_ref[0] + j * bq
    k_start = ko_ref[0] + kk * bk
    # causal: skip tiles that lie entirely in the masked future
    live = (k_start <= q_start + bq - 1) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(cols <= rows, s, _MASKED)
        m_prev = m_sc[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # a row with no visible key keeps m == _MASKED; exp(s - m) would
        # be exp(0) = 1 there — mask p explicitly, never through the exp
        p = jnp.where(s > _MASK_THRESH, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_sc[:, :1] = l_sc[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0]
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:, :1] = m_new

    @pl.when(kk == nk - 1)
    def _():
        l_safe = jnp.maximum(l_sc[:, :1], 1e-30)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)
        # lane-replicated write: lse rides as [bh, lq, LANE] so its block
        # (1, bq, LANE) satisfies Mosaic's (8, 128) tile rule for ANY bh —
        # a (1, bq) block over [bh, lq] only lowers when bh == 1, which is
        # exactly the shape the old probe tested (see _lowering_probe)
        lse_ref[0] = jnp.broadcast_to(
            m_sc[:, :1] + jnp.log(l_safe), (lse_ref.shape[1], _LANE)
        )


def _fwd(q3, k3, v3, q_off, k_off, causal, scale, bq, bk):
    bh, lq, d = q3.shape
    lk = k3.shape[1]
    nq, nk = lq // bq, lk // bk
    kern = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, bq=bq, bk=bk, nk=nk
    )
    return pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda i, j, kk: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, lq, _LANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
        ],
        interpret=_interpret(),
    )(q_off, k_off, q3, k3, v3)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _recompute_p(q, k, lse_tile, q_start, k_start, causal, scale, bq, bk):
    """p = exp(s - lse) with masked entries exactly zero.
    ``lse_tile`` is a [bq, 1] column (lane 0 of the replicated ride)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(cols <= rows, s, _MASKED)
    return jnp.where(s > _MASK_THRESH, jnp.exp(s - lse_tile), 0.0)


def _bwd_dq_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   dm_ref, dq_ref, dq_acc, *, causal, scale, bq, bk, nk):
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qo_ref[0] + j * bq
    k_start = ko_ref[0] + kk * bk
    live = (k_start <= q_start + bq - 1) if causal else True

    @pl.when(live)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p = _recompute_p(q, k, lse_ref[0][:, :1], q_start, k_start, causal,
                         scale, bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dm_ref[0][:, :1])
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(kk == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    dm_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                    *, causal, scale, bq, bk, nq):
    jk = pl.program_id(1)
    jq = pl.program_id(2)

    @pl.when(jq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qo_ref[0] + jq * bq
    k_start = ko_ref[0] + jk * bk
    live = (k_start <= q_start + bq - 1) if causal else True

    @pl.when(live)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p = _recompute_p(q, k, lse_ref[0][:, :1], q_start, k_start, causal,
                         scale, bq, bk)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dm_ref[0][:, :1])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(jq == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q3, k3, v3, q_off, k_off, out, lse, g_out, g_lse,
         causal, scale, bq, bk):
    bh, lq, d = q3.shape
    lk = k3.shape[1]
    nq, nk = lq // bq, lk // bk
    # D folds the out-cotangent; the lse-cotangent enters with opposite
    # sign in ds = p * (dp - (D - g_lse)). lse arrives lane-replicated
    # [bh, lq, LANE] (see _fwd); dm rides the same layout so both block
    # as tile-aligned (1, bq, LANE)
    dm = (jnp.sum(g_out.astype(jnp.float32) * out.astype(jnp.float32),
                  axis=-1) - g_lse)
    dm = jnp.broadcast_to(dm[..., None], (bh, lq, _LANE))
    lse = jnp.broadcast_to(lse[..., None], (bh, lq, _LANE))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          bq=bq, bk=bk, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda i, j, kk: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, kk: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(q_off, k_off, q3, k3, v3, g_out, lse, dm)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          bq=bq, bk=bk, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda i, jk, jq: (i, jq, 0)),
            pl.BlockSpec((1, bk, d), lambda i, jk, jq: (i, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda i, jk, jq: (i, jk, 0)),
            pl.BlockSpec((1, bq, d), lambda i, jk, jq: (i, jq, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda i, jk, jq: (i, jq, 0)),
            pl.BlockSpec((1, bq, _LANE), lambda i, jk, jq: (i, jq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, jk, jq: (i, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda i, jk, jq: (i, jk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q_off, k_off, q3, k3, v3, g_out, lse, dm)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp core on [bh, l, d] arrays
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q3, k3, v3, q_off, k_off, causal, scale, bq, bk):
    out, lse = _fwd(q3, k3, v3, q_off, k_off, causal, scale, bq, bk)
    return out, lse


def _flash_fwd(q3, k3, v3, q_off, k_off, causal, scale, bq, bk):
    out, lse = _fwd(q3, k3, v3, q_off, k_off, causal, scale, bq, bk)
    # residual keeps lane 0 only — every lane is identical, and holding
    # the [bh, lq, LANE] ride through the whole model backward would cost
    # 128x the memory; _bwd re-broadcasts (same pattern as dm)
    return (out, lse), (q3, k3, v3, q_off, k_off, out, lse[..., 0])


def _flash_bwd(causal, scale, bq, bk, res, g):
    q3, k3, v3, q_off, k_off, out, lse = res
    g_out, g_lse = g
    # lse is returned lane-replicated [bh, lq, LANE]; the adjoint of that
    # replication is the lane-sum of the cotangent (the API slices lane 0,
    # so in practice only that column is nonzero)
    g_lse = g_lse.sum(axis=-1)
    dq, dk, dv = _bwd(q3, k3, v3, q_off, k_off, out, lse, g_out, g_lse,
                      causal, scale, bq, bk)
    zero_off = np.zeros((1,), jax.dtypes.float0)  # int inputs: no tangent
    return dq, dk, dv, zero_off, zero_off


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# public API on [b, l, h, d] arrays (the models' layout)
# ---------------------------------------------------------------------------

def _attention_jnp(q, k, v, q_offset, k_offset, causal, scale):
    """Dense oracle with identical semantics (global-coordinate causal
    mask, masked-row-safe, returns lse). Differentiable; used as the
    fallback for untileable shapes and as the test oracle."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        rows = q_offset + jnp.arange(q.shape[1])
        cols = k_offset + jnp.arange(k.shape[1])
        s = jnp.where(cols[None, :] <= rows[:, None], s, _MASKED)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(s > _MASK_THRESH, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.maximum(l, 1e-30)
    out = jnp.einsum("bhqk,bkhd->bqhd", (p / l_safe),
                     v.astype(jnp.float32)).astype(q.dtype)
    lse = (m + jnp.log(l_safe))[..., 0]               # [b, h, q]
    return out, lse


def _default_block_targets(lq: int, lk: int) -> tuple:
    """Measured block-size policy (flash_tune sweep, v5e 2026-08-01,
    `BENCH_TPU_WATCH.jsonl`): at s512 the 128x128 tile wins (3.28 ms vs
    3.55 for 512x512); at s2048 512x1024 wins 4.9x over 128x128 (6.64 vs
    32.5 ms) and at s8192 7.2x (16.4 vs 117.6 ms) — larger k/v tiles
    amortize per-grid-step dispatch and keep the MXU fed once the score
    block is MXU-shaped on both dims, while below ~1k sequence the grid
    is too small for tile residency to matter and 128's divisibility
    into short tails wins. Crossover bracketed between 512 and 2048;
    big tiles engage from 1024 up."""
    if max(lq, lk) >= 1024:
        return 512, 1024
    return 128, 128


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=None, k_offset=None,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    return_lse: bool = False,
):
    """Tiled attention over ``[batch, seq, heads, head_dim]`` tensors.

    ``q_offset``/``k_offset`` (int scalars, may be traced) place the q/k
    blocks in global sequence coordinates for the causal mask — ring
    attention passes its rotating block offsets here. With
    ``return_lse=True`` also returns the per-row logsumexp ``[b, h, q]``
    (differentiable), which is what block-combining needs.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    q_offset = jnp.zeros((), jnp.int32) if q_offset is None else q_offset
    k_offset = jnp.zeros((), jnp.int32) if k_offset is None else k_offset

    mb = _min_block_for(q.dtype)
    dbq, dbk = _default_block_targets(lq, lk)
    bq = _pick_block(lq, block_q if block_q is not None else dbq, mb)
    bk = _pick_block(lk, block_k if block_k is not None else dbk, mb)
    if bq is None or bk is None:
        out, lse = _attention_jnp(q, k, v, q_offset, k_offset, causal, scale)
        return (out, lse) if return_lse else out

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    q_off = jnp.broadcast_to(q_offset, (1,)).astype(jnp.int32)
    k_off = jnp.broadcast_to(k_offset, (1,)).astype(jnp.int32)
    out3, lse3 = _flash(to3(q), to3(k), to3(v), q_off, k_off,
                        causal, float(scale), bq, bk)
    out = out3.reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    if not return_lse:
        return out
    return out, lse3[..., 0].reshape(b, h, lq)


def flash_supported(lq: int, lk: int, block_q: int = 128,
                    block_k: int = 128, dtype=jnp.float32) -> bool:
    """Can the tiled kernel serve these sequence lengths (at this
    dtype's minimal sublane tile)?"""
    mb = _min_block_for(dtype)
    return (_pick_block(lq, block_q, mb) is not None
            and _pick_block(lk, block_k, mb) is not None)


def mosaic_lowering_ok(head_dim: int = 64, dtype=jnp.bfloat16,
                       seq: int = 128, lk: Optional[int] = None) -> bool:
    """Cached compile probe: does this backend's Mosaic lower the kernel
    family for THIS head_dim/dtype (the parameters tiling actually
    depends on)? Probes the CAUSAL forward AND the backward pass (grad
    compiles all three kernels — dq and dk/dv lower independently and
    can regress independently). Gates the AUTO dispatches ('full'
    attention, ring/ulysses defaults) so a lowering regression degrades
    to the dense path instead of breaking every TPU bench/model; the
    explicit 'flash' mode stays ungated and fails loudly. Lowering
    failures are shape-CLASS properties (dtype tiling, lane-dim head
    size, per-block VMEM footprint) — and since the default block size
    is a function of sequence length (`_default_block_targets` targets
    degraded by `_pick_block` divisibility), the probe must compile the
    SAME (bq, bk) family the dispatch would run: a small-tile probe
    passing says nothing about 512x1024 VMEM, and a big-tile probe says
    nothing about the degraded tiles a non-power-of-two-friendly length
    actually gets. The probe resolves the dispatch's exact blocks, then
    compiles them at the shortest length that still exercises a
    MULTI-block grid on both axes (2*max(bq, bk): nq, nk >= 2 — an
    nk==1 probe is the block-dim-equals-array-dim coincidence class
    that let a broken lse block through once before, see
    `_lowering_probe`). ``seq``/``lk`` are the q/k lengths (``lk``
    defaults to ``seq``) — bq derives from the q length and bk from
    the k length SEPARATELY, because ring attention's rotating blocks
    can degrade one axis's tile and not the other's. Cached per
    (head_dim, dtype, bq, bk)."""
    lk = seq if lk is None else lk
    mb = _min_block_for(dtype)
    dbq, dbk = _default_block_targets(seq, lk)
    bq = _pick_block(seq, dbq, mb)
    bk = _pick_block(lk, dbk, mb)
    if bq is None or bk is None:
        return False  # dispatch would fall back to dense anyway
    return _lowering_probe(int(head_dim), jnp.dtype(dtype).name, bq, bk)


@functools.lru_cache(maxsize=16)
def _lowering_probe(head_dim: int, dtype_name: str, bq: int, bk: int) -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        # 2 heads, NOT 1: with a single head the flattened batch*heads dim
        # is 1, and a block dim of 1 trivially "equals the array dim" —
        # Mosaic's tile rule then passes shapes it rejects for every real
        # model (this exact coincidence let a (1, bq) lse block through
        # the probe and then broke BERT on the first live TPU window).
        # The probe length keeps BOTH grid axes multi-block (2*max of
        # two powers of two is divisible by each, so nq, nk >= 2) — an
        # nk==1 probe is the same coincidence class on the k axis.
        seq = 2 * max(bq, bk)
        q = jnp.zeros((1, seq, 2, head_dim), dtype_name)

        def loss(x):
            return jnp.sum(
                flash_attention(x, x, x, causal=True,
                                block_q=bq, block_k=bk).astype(jnp.float32)
            )

        jax.jit(jax.grad(loss)).lower(q).compile()
        return True
    except Exception:
        return False


def flash_auto_ok(lq: int, lk: int, head_dim: int, dtype) -> bool:
    """The ONE auto-dispatch gate every attention entry point (BERT
    'full', ring, ulysses) consults: the sequence is long enough that
    the kernel measured FASTER than XLA's fused dense attention
    (``FLASH_MIN_SEQ``), shapes tile at this dtype, AND the Mosaic probe
    (fwd+bwd, causal) compiles. Off-TPU the probe is False, so no
    separate backend check is needed. The explicit ``attention='flash'``
    mode bypasses this gate entirely."""
    return (max(lq, lk) >= FLASH_MIN_SEQ
            and flash_supported(lq, lk, dtype=dtype)
            and mosaic_lowering_ok(head_dim, dtype, lq, lk))
