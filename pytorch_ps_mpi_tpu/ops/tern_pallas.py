"""Fused TernGrad ternarize + base-4 pack as Pallas TPU kernels.

The jnp encode path runs four separate full-size passes per gradient:
the uniform draw (f32), the keep-probability compare, the ternary digit
select, and the reshape-weight-sum pack — each materializing an n-sized
intermediate in HBM (the committed TPU sweeps show the Pallas-less
codecs at 1.04–1.07× over jnp precisely because nothing is fused). Here
the compare → digit → pack pipeline is ONE gridded VMEM pass: the
kernel reads the gradient tile and a tile of raw uint32 random bits and
writes packed bytes directly — the f32 uniform tensor, the bool keep
mask, and the digit tensor never exist.

Randomness comes in as raw ``jax.random.bits`` uint32 (the TPU Pallas
PRNG primitives have no interpret-mode lowering on this jax, and the
caller already owns chunked key derivation for the scan path): the top
24 bits compare against ``|x|/s * 2^24``, the same 24-bit Bernoulli
resolution ``jax.random.uniform`` has via the f32 mantissa.

Layout: the flat input is viewed as ``[rows, 4, 128]`` — 4 consecutive
*sublanes* fold into one packed row of 128 lanes, so digit ``s`` of
packed byte ``[r, lane]`` holds element ``r*512 + s*128 + lane``. Like
``sign_pallas``, this differs from the jnp path's 4-consecutive-
elements-per-byte grouping: payloads are self-consistent within one
codec configuration (every worker runs the same codec), and the codec
declines host-side aggregation for Pallas-layout units.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pytorch_ps_mpi_tpu.ops._common import LANE as _LANE
from pytorch_ps_mpi_tpu.ops._common import interpret as _interpret

_GROUP = 4 * _LANE  # one packed row of 128 bytes encodes 512 ternaries

_BLOCK_ROWS = 256  # 256×4×128 f32 ×2 inputs = 1 MiB of VMEM tiles


def _weights():
    # base-4 digit weights [1, 4, 16, 64]; int32 (Mosaic has no
    # unsigned reductions)
    return (4 ** jnp.arange(4, dtype=jnp.int32))[None, :, None]


def _pack_kernel(x_ref, u_ref, scale_ref, out_ref):
    x = x_ref[:]                                   # [rows, 4, 128] f32
    u = u_ref[:]                                   # [rows, 4, 128] u32
    s = scale_ref[0, 0]
    # Bernoulli(|x|/s) at 24-bit resolution: top 24 random bits vs
    # p·2^24 — both exact in f32, so the compare is deterministic
    p24 = jnp.abs(x) * (16777216.0 / s)
    u24 = (u >> 8).astype(jnp.float32)
    keep = u24 < p24
    # ternary digit: 0 -> -1, 1 -> 0, 2 -> +1
    digit = jnp.where(keep, jnp.where(x >= 0, 2, 0), 1).astype(jnp.int32)
    out_ref[:] = (digit * _weights()).sum(axis=1).astype(jnp.uint8)


def tern_pack(flat: jax.Array, rand_u32: jax.Array, scale: jax.Array):
    """float32[n] + uint32[n] bits + scalar scale -> uint8[n/4] packed
    ternary digits (n % 512 == 0). One fused compare/digit/pack pass."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = flat.shape[0]
    assert n % _GROUP == 0, n
    rows = n // _GROUP
    x3d = flat.reshape(rows, 4, _LANE)
    u3d = rand_u32.reshape(rows, 4, _LANE)
    grid = ((rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS,)
    out = pl.pallas_call(
        _pack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 4, _LANE), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, 4, _LANE), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(x3d, u3d, jnp.asarray(scale, jnp.float32).reshape(1, 1))
    return out.reshape(n // 4)


def _unpack_kernel(p_ref, scale_ref, out_ref):
    p = p_ref[:].astype(jnp.int32)                 # [rows, 128]
    digits = (p[:, None, :] // _weights()) % 4     # [rows, 4, 128]
    out_ref[:] = (digits - 1).astype(jnp.float32) * scale_ref[0, 0]


def tern_unpack(packed: jax.Array, scale: jax.Array) -> jax.Array:
    """uint8[m] (m % 128 == 0) + scalar scale -> float32[4m] of
    scale·{-1, 0, +1} — the fused dequantizing unpack."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = packed.shape[0]
    assert m % _LANE == 0, m
    rows = m // _LANE
    p2d = packed.reshape(rows, _LANE)
    grid = ((rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS,)
    out = pl.pallas_call(
        _unpack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 4, _LANE), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, 4, _LANE), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(p2d, jnp.asarray(scale, jnp.float32).reshape(1, 1))
    return out.reshape(m * 4)
