"""Exact top-k selection via per-block threshold refine — Pallas TPU.

``lax.top_k`` on a multi-million-element flat gradient lowers to a full
bitonic sort: 17.76 ms at 8M elements on v5e vs 3.25 ms for
``lax.approx_max_k`` (BENCH_TPU_WATCH) — a 5.5× tax for exactness. This
module closes the gap without giving up exactness by splitting selection
into the two parts with very different costs:

1. **Threshold refine (Pallas count kernel).** The k-th largest |x| is
   found WITHOUT sorting: |x| is viewed as its int32 bit pattern (for
   non-negative floats the bit order IS the value order), and the
   threshold is built bit by bit from the MSB — 31 rounds of "does
   count(key >= candidate) still reach k?", each round one gridded
   Pallas pass that accumulates per-block counts into an SMEM scalar
   (sequential TPU grid, race-free — the per-block threshold refine).
   Each pass is a memory-bound read of n int32s; 31 of them cost a few
   ms at 8M where one full sort costs ~18.

2. **Chunked compaction.** With the exact threshold in hand, survivor
   indices are compacted by per-chunk biased-key sorts — ONE vectorized
   ``lax.sort`` over ``[n_chunks, chunk]``, bitonic depth log²(chunk)
   instead of log²(n) — followed by a sequential cursor merge
   (``dynamic_update_slice`` per chunk, each write's garbage tail
   overwritten by its successor). Strict survivors (> threshold) land
   first in global index order, then exactly ``k - m`` threshold ties
   fill the remainder.

The returned (values, indices) hold EXACTLY the k largest-magnitude
elements (ties broken in index order, where ``lax.top_k`` breaks them in
its sort order — same value multiset, asserted by the tests). Runs in
interpret mode off-TPU, so CPU CI tests the algorithm end to end.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.ops._common import LANE as _LANE
from pytorch_ps_mpi_tpu.ops._common import interpret as _interpret

_BLOCK_ROWS = 1024           # 1024×128 i32 = 512 KiB per count tile
_TILE = _BLOCK_ROWS * _LANE


def _count_kernel(t_ref, x_ref, out_ref):
    """Per-block ge/gt counts vs the SMEM threshold, accumulated across
    the sequential grid into one SMEM (1, 2) vector."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[0, 0] = 0
        out_ref[0, 1] = 0

    x = x_ref[:]
    t = t_ref[0, 0]
    out_ref[0, 0] += jnp.sum((x >= t).astype(jnp.int32))
    out_ref[0, 1] += jnp.sum((x > t).astype(jnp.int32))


def _counts(keys2d: jax.Array, t: jax.Array):
    """(count_ge, count_gt) of the padded int32 key plane vs scalar t."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = keys2d.shape[0]
    grid = ((rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS,)
    out = pl.pallas_call(
        _count_kernel,
        out_shape=jax.ShapeDtypeStruct((1, 2), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=_interpret(),
    )(t.reshape(1, 1), keys2d)
    return out[0, 0], out[0, 1]


def _kth_threshold(keys2d: jax.Array, k: int):
    """The k-th largest key, built bit by bit (31 count passes): the
    largest t with count(key >= t) >= k. Keys are non-negative (float
    bit patterns of |x|; padding is -1 and never counted)."""

    def body(b, t):
        cand = t | (jnp.int32(1) << (30 - b))
        ge, _ = _counts(keys2d, cand)
        return jnp.where(ge >= k, cand, t)

    return jax.lax.fori_loop(0, 31, body, jnp.int32(0))


def _compact_two_phase(skeys, counts_strict, counts_tie, chunk, k):
    """Cursor-merge the per-chunk sorted prefixes: strict survivors
    first (global index order), then threshold ties filling to k.
    ``skeys`` is [nc, chunk + take] — per-chunk ascending 3-level biased
    keys (strict -> pos, tie -> pos + C, rest -> pos + 2C) padded with
    take sentinel columns so the tie-phase dynamic slice never clamps."""
    C = chunk
    nc = skeys.shape[0]
    take = min(C, k)
    out0 = jnp.zeros((k + take,), jnp.int32)

    def unbias(key, c):
        local = jnp.where(key >= 2 * C, key - 2 * C,
                          jnp.where(key >= C, key - C, key))
        return local + c * C

    def strict_body(c, state):
        out, cursor = state
        glob = unbias(skeys[c, :take], c)
        out = jax.lax.dynamic_update_slice(
            out, glob, (jnp.minimum(cursor, k),))
        return out, cursor + counts_strict[c]

    out, m = jax.lax.fori_loop(0, nc, strict_body, (out0, jnp.int32(0)))

    def tie_body(c, state):
        out, cursor = state
        # this chunk's ties start right after its strict prefix —
        # dynamic start, static size; the sentinel pad guarantees
        # start + take never exceeds the row
        row = jax.lax.dynamic_slice(
            skeys[c], (counts_strict[c],), (take,))
        glob = unbias(row, c)
        out = jax.lax.dynamic_update_slice(
            out, glob, (jnp.minimum(cursor, k),))
        return out, cursor + counts_tie[c]

    out, _ = jax.lax.fori_loop(0, nc, tie_body, (out, m))
    return out[:k]


@functools.partial(jax.jit, static_argnames=("k", "chunk"))
def exact_topk(flat: jax.Array, k: int, chunk: int = 2048):
    """(values[k], indices[k]) of the k largest-|x| elements — exact.

    Selection = Pallas threshold refine + chunked compaction (module
    doc). ``chunk`` must be a power of two; tensors smaller than
    4×chunk (or with k >= n) fall back to ``lax.top_k``."""
    n = flat.shape[0]
    if k >= n or n < 4 * chunk or n > (1 << 30):
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        return jnp.take(flat, idx), idx.astype(jnp.int32)

    # |x| as monotonic int32 keys, padded to the count tile with -1
    # (never counted: every real key is >= 0)
    keys = jax.lax.bitcast_convert_type(
        jnp.abs(flat.astype(jnp.float32)), jnp.int32)
    unit = max(chunk, _TILE)  # powers of two: a multiple of both
    padded_n = ((n + unit - 1) // unit) * unit
    nc = padded_n // chunk
    keys_pad = jnp.concatenate(
        [keys, jnp.full((padded_n - n,), -1, jnp.int32)]) if padded_n > n \
        else keys
    t = _kth_threshold(keys_pad.reshape(-1, _LANE), k)

    # 3-level biased per-chunk keys: strict survivor -> local pos, tie
    # -> pos + C, rest -> pos + 2C; one vectorized per-chunk sort puts
    # [strict..., ties..., rest...] each in index order
    k2 = keys_pad.reshape(nc, chunk)
    pos = jnp.arange(chunk, dtype=jnp.int32)[None, :]
    biased = jnp.where(k2 > t, pos,
                       jnp.where(k2 == t, pos + chunk, pos + 2 * chunk))
    counts_strict = jnp.sum(k2 > t, axis=1, dtype=jnp.int32)
    counts_tie = jnp.sum(k2 == t, axis=1, dtype=jnp.int32)
    skeys = jax.lax.sort(biased, dimension=-1)
    take = min(chunk, k)
    skeys = jnp.concatenate(
        [skeys, jnp.full((nc, take), 3 * chunk, jnp.int32)], axis=1)
    idx = _compact_two_phase(skeys, counts_strict, counts_tie, chunk, k)
    # padding keys are -1: never strict, never tied (t >= 0), never
    # selected — idx entries are always < n
    return jnp.take(flat, idx), idx
