"""Synthetic data generators for the BASELINE configs.

The reference has no data loading at all (its train scripts lived
elsewhere, SURVEY "What the reference is NOT"); these deterministic
generators produce correctly-shaped batches for MNIST/CIFAR/ImageNet/MLM
workloads without network access, plus a sharded host loader that hands
``MPI_PS.step`` globally-batched arrays (jit shards them over the mesh's
data axis).
"""

from __future__ import annotations

from typing import Dict, Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np

SHAPES = {
    "mnist": ((28, 28, 1), 10),
    "cifar10": ((32, 32, 3), 10),
    "imagenet": ((224, 224, 3), 1000),
}


def synthetic_images(
    name: str, batch: int, seed: int = 0
) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Endless iterator of (images[B,H,W,C], labels[B]) with a learnable
    class signal (per-class mean offsets, so loss actually decreases)."""
    shape, classes = SHAPES[name]
    rng = np.random.RandomState(seed)
    class_means = rng.randn(classes, *shape).astype(np.float32) * 0.5
    while True:
        labels = rng.randint(0, classes, size=(batch,))
        x = rng.randn(batch, *shape).astype(np.float32) + class_means[labels]
        yield jnp.asarray(x), jnp.asarray(labels)


def synthetic_mlm(
    batch: int,
    seq_len: int,
    vocab_size: int,
    mask_rate: float = 0.15,
    mask_token: int = 0,
    seed: int = 0,
) -> Iterator[Dict[str, jax.Array]]:
    """Endless iterator of MLM batches: {'tokens', 'targets', 'mask'}."""
    rng = np.random.RandomState(seed)
    while True:
        targets = rng.randint(1, vocab_size, size=(batch, seq_len))
        mask = rng.rand(batch, seq_len) < mask_rate
        tokens = np.where(mask, mask_token, targets)
        yield {
            "tokens": jnp.asarray(tokens),
            "targets": jnp.asarray(targets),
            "mask": jnp.asarray(mask),
        }


def synthetic_lm(
    batch: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    order: int = 1,
    table_seed: int | None = None,
) -> Iterator[Dict[str, jax.Array]]:
    """Endless iterator of causal-LM batches {'tokens'}: sequences from a
    fixed random Markov chain, so next-token loss has genuine signal
    below the uniform-entropy floor (pure-random tokens would make
    convergence unobservable). ``order=1`` is a plain bigram chain — the
    state IS the previous token, learnable by a 1-layer model; higher
    orders hash the last tokens into the state (harder: the model must
    recover the hash from context).

    ``table_seed`` fixes the CHAIN separately from the sampling stream:
    distributed consumers drawing differently-seeded streams must still
    sample the SAME language or there is nothing stable to learn."""
    rng = np.random.RandomState(seed)
    cum = markov_table(
        vocab_size, seed if table_seed is None else table_seed
    )
    while True:
        yield {"tokens": jnp.asarray(
            sample_markov(cum, batch, seq_len, rng, order=order)
        )}


def markov_table(vocab_size: int, seed: int = 0) -> "np.ndarray":
    """The fixed random chain behind :func:`synthetic_lm` as a cumulative
    table ``[n_ctx, vocab]`` — build ONCE, sample many times (per-batch
    rebuilds were a measurable hot-path cost for distributed workers)."""
    rng = np.random.RandomState(seed)
    n_ctx = min(64, vocab_size)  # contexts hash into this many states
    table = rng.dirichlet(np.ones(vocab_size) * 0.05, size=n_ctx)
    return np.cumsum(table, axis=-1)


def sample_markov(cum: "np.ndarray", batch: int, seq_len: int,
                  rng: "np.random.RandomState", order: int = 1) -> "np.ndarray":
    """One ``[batch, seq_len]`` token batch from a :func:`markov_table`."""
    n_ctx, vocab_size = cum.shape
    toks = np.zeros((batch, seq_len), np.int64)
    toks[:, 0] = rng.randint(0, vocab_size, size=batch)
    state = toks[:, 0] % n_ctx
    for t in range(1, seq_len):
        u = rng.rand(batch, 1)
        toks[:, t] = (u < cum[state]).argmax(axis=-1)
        if order == 1:
            state = toks[:, t] % n_ctx
        else:
            state = (state * 31 + toks[:, t]) % n_ctx
    return toks


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def prefetch(it: Iterator, depth: int = 2) -> Iterator:
    """Host-side input pipelining: a background thread keeps up to
    ``depth`` batches ready so batch construction overlaps the device
    step — the role the reference's 200-thread encode pool played for
    its host-bound pipeline (``ps.py:85``), applied where a host thread
    still helps a TPU program (the input side; gradient work lives
    inside the jitted step here).

    Exceptions in the source iterator propagate to the consumer;
    ``StopIteration`` ends the stream cleanly. Closing or abandoning the
    consumer generator stops the pump thread (it checks a stop event
    around its bounded puts), so long-lived processes don't accumulate
    blocked threads holding queued batches; the thread is also a daemon,
    so interpreter exit never blocks on it.
    """
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    _END = object()

    def offer(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def pump():
        try:
            for item in it:
                if not offer(item):
                    return
        except BaseException as e:  # noqa: BLE001 — forwarded, not dropped
            offer(("__prefetch_error__", e))
            return
        offer(_END)

    threading.Thread(target=pump, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if (isinstance(item, tuple) and len(item) == 2
                    and item[0] == "__prefetch_error__"):
                raise item[1]
            yield item
    finally:
        stop.set()  # consumer closed/abandoned: release the pump
