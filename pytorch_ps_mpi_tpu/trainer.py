"""Trainer: the training-loop layer the reference never shipped.

The reference was a bare optimizer library — its train scripts lived in a
sibling research repo (SURVEY: "no models, no training loop, no CLI").
This closes that gap: a loop that owns an :class:`MPI_PS` optimizer,
fuses steps in ``lax.scan`` chunks for throughput, accumulates the
per-step metrics dicts, and checkpoints/resumes (params + optimizer state
+ step counter) through :class:`CheckpointManager`.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_ps_mpi_tpu.ps import MPI_PS
from pytorch_ps_mpi_tpu.telemetry import get_recorder
from pytorch_ps_mpi_tpu.utils.checkpoint import CheckpointManager
from pytorch_ps_mpi_tpu.utils.metrics import MetricsAccumulator

PyTree = Any


class Trainer:
    """Drive an ``MPI_PS`` optimizer over a batch iterator.

    Args:
      optimizer: a constructed :class:`MPI_PS` (or SGD/Adam subclass).
      loss_fn: ``loss_fn(params, batch) -> scalar``.
      checkpoint_dir: optional; enables save/resume.
      checkpoint_every: steps between checkpoints.
      scan_chunk: >1 fuses that many steps into one XLA program via
        ``run_steps`` (requires a steady batch shape).
    """

    def __init__(
        self,
        optimizer: MPI_PS,
        loss_fn: Callable,
        *,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 100,
        scan_chunk: int = 1,
    ):
        self.opt = optimizer
        self.loss_fn = loss_fn
        self.metrics = MetricsAccumulator()
        self.step_count = 0
        self.scan_chunk = max(1, int(scan_chunk))
        self.checkpoint_every = checkpoint_every
        self._last_saved_step = 0
        self._eval_compiled: Dict[Any, Callable] = {}
        self.ckpt = (
            CheckpointManager(checkpoint_dir) if checkpoint_dir else None
        )

    # -- checkpoint / resume ------------------------------------------------
    def _state(self) -> Dict[str, PyTree]:
        # Delegate to the optimizer's own state_dict so checkpoints carry
        # everything it considers state — including the PRNG stream
        # (stochastic codecs replay keys on resume) and aux_state (BN
        # batch_stats), not just params/opt_state.
        sd = dict(self.opt.state_dict())
        sd["trainer_step"] = jnp.asarray(self.step_count)
        if sd.get("aux_state") is None:
            sd.pop("aux_state")  # pytree restore needs a stable structure
        return sd

    def save(self) -> None:
        if self.ckpt is None:
            raise RuntimeError("no checkpoint_dir configured")
        rec = get_recorder()
        if rec is None:
            self.ckpt.save(self.step_count, self._state())
        else:
            with rec.span("trainer.checkpoint", step=self.step_count):
                self.ckpt.save(self.step_count, self._state())
        self._last_saved_step = self.step_count

    def maybe_restore(self) -> bool:
        """Resume from the latest checkpoint if one exists. A checkpoint
        whose pytree structure does not match the current schema (e.g.
        written by an older version) is reported and skipped — training
        starts fresh rather than crashing."""
        if self.ckpt is None or self.ckpt.latest_step() is None:
            return False
        try:
            state = self.ckpt.restore(self._state())
        except Exception as e:
            import sys

            print(
                f"checkpoint restore failed (incompatible schema?): {e}; "
                "starting fresh",
                file=sys.stderr,
            )
            return False
        # device placement of restored leaves is load_state_dict's job
        # (MPI_PS._decommit_restored keeps correctly-sharded restores,
        # rehosts the rest)
        self.step_count = int(np.asarray(state.pop("trainer_step")))
        state.setdefault("aux_state", None)
        self.opt.load_state_dict(state)
        return True

    # -- evaluation ---------------------------------------------------------
    def evaluate(
        self,
        batches: Iterator[PyTree],
        num_batches: int,
        eval_fn: Optional[Callable] = None,
    ) -> float:
        """Mean of ``eval_fn(params, batch)`` (default: the training
        ``loss_fn``) over ``num_batches`` batches, without touching
        optimizer state."""
        fn = eval_fn if eval_fn is not None else self.loss_fn
        # key by behavior, not object identity: a bound method or fresh
        # lambda per call must not recompile every evaluate() (same
        # machinery MPI_PS.step uses for loss_fn)
        from pytorch_ps_mpi_tpu.ps import _fn_cache_key

        key = ("eval", _fn_cache_key(fn))
        if key not in self._eval_compiled:
            self._eval_compiled[key] = jax.jit(fn)
        compiled = self._eval_compiled[key]
        total = 0.0
        for _ in range(num_batches):
            total += float(compiled(self.opt.params, next(batches)))
        return total / max(1, num_batches)

    # -- training -----------------------------------------------------------
    def fit(
        self,
        batches: Iterator[PyTree],
        num_steps: int,
        log_every: int = 0,
    ) -> Dict[str, float]:
        """Train for ``num_steps`` batches; returns mean metrics (the
        reference's returned-timings contract, aggregated)."""
        t0 = time.perf_counter()
        last_loss = None
        done = 0
        while done < num_steps:
            rec = get_recorder()  # one attr read/step when disabled
            if self.scan_chunk > 1 and num_steps - done >= self.scan_chunk:
                span_t0 = time.monotonic()
                chunk = [next(batches) for _ in range(self.scan_chunk)]
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *chunk)
                losses, data = self.opt.run_steps(self.loss_fn, stacked)
                last_loss = float(losses[-1])
                self.metrics.add(data)
                done += self.scan_chunk
                self.step_count += self.scan_chunk
                if rec is not None:
                    rec.event("trainer.step_chunk", kind="span", ts=span_t0,
                              dur=time.monotonic() - span_t0,
                              step=self.step_count, loss=last_loss,
                              n_steps=self.scan_chunk)
            else:
                span_t0 = time.monotonic()
                loss, data = self.opt.step(loss_fn=self.loss_fn, batch=next(batches))
                last_loss = float(loss)
                self.metrics.add(data)
                done += 1
                self.step_count += 1
                if rec is not None:
                    rec.event("trainer.step", kind="span", ts=span_t0,
                              dur=time.monotonic() - span_t0,
                              step=self.step_count, loss=last_loss)
            if log_every and done % log_every == 0:
                rate = done / (time.perf_counter() - t0)
                print(f"step {self.step_count}: loss={last_loss:.4f} "
                      f"({rate:.1f} steps/s)")
            # interval crossing, not modulo: scan_chunk may not divide
            # checkpoint_every
            if (self.ckpt is not None
                    and self.step_count - self._last_saved_step >= self.checkpoint_every):
                self.save()
        if self.ckpt is not None and self.step_count != self._last_saved_step:
            self.save()
        out = self.metrics.mean()
        out["final_loss"] = last_loss
        out["wall_time"] = time.perf_counter() - t0
        out["steps_per_sec_overall"] = num_steps / out["wall_time"]
        return out
