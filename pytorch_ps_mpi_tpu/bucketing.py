"""Flat-bucket gradient aggregation: fuse per-leaf collectives into
dtype-grouped flat buffers.

The reference received gradients one parameter at a time in a reverse-order
loop (``ps.py:155-176``); our tree-mapped rebuild kept that granularity —
one ``psum``/``all_gather``/``psum_scatter`` launch per leaf, hundreds for a
BERT-size tree, each paying the fixed collective dispatch latency the ICI
cannot amortize (the per-message-overhead effect of "On the Utility of
Gradient Compression in Distributed Training Systems"; SparCML applies the
same fix at the MPI layer by streaming many small contributions through few
large buffers).

This module is the compile-time answer: a :class:`BucketPlan` groups a
pytree's leaves **by dtype** (a bucket is a single flat array, so its dtype
must be uniform — grouping also preserves each leaf's precision end to end)
into contiguous ~``bucket_mb``-MB buffers, with exact offset bookkeeping for
every leaf including 0-d scalars. The transforms are pure and cheap inside
jit — ``pack`` is one concatenate per bucket, ``unpack`` one slice per leaf
— so XLA fuses them into the surrounding program; what changes is the
*collective launch count*: one per bucket instead of one per leaf.

Consumers:

- ``ps.MPI_PS(bucket_mb=...)`` — psums / psum_scatters buckets instead of
  leaves in both topologies (``mode='allgather'`` and the ZeRO-1
  ``mode='leader'``, where each worker owns a contiguous bucket shard);
- ``parallel.dp.make_sync_train_step(bucket_mb=...)`` — the functional API;
- ``parallel.dcn.CodecWire(bucket_mb=...)`` — the host wire ships one
  contiguous per-bucket payload per push instead of per-leaf fragments.

Shape-agnostic stateless codecs (``Codec.bucketable``) encode per bucket;
per-tensor codecs (PowerSGD, top-k) keep the per-leaf path. ``bucket_mb=0``
everywhere preserves today's per-leaf behavior exactly.
"""

from __future__ import annotations

import re
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


class LeafSlot(NamedTuple):
    """Where one pytree leaf lives inside the bucket set: exact offset
    bookkeeping (0-d leaves occupy one element; ``shape=()`` restores
    them on unpack)."""

    bucket: int            # index into BucketPlan.buckets
    offset: int            # element offset inside that bucket
    size: int              # element count (1 for 0-d scalars)
    shape: Tuple[int, ...]
    dtype: Any             # canonical jnp dtype


class BucketSpec(NamedTuple):
    """One flat bucket: uniform dtype, ``size`` total elements."""

    dtype: Any
    size: int

    @property
    def nbytes(self) -> int:
        return self.size * jnp.dtype(self.dtype).itemsize


class BucketPlan:
    """Compile-time bucketing plan for one pytree structure.

    Built once per (tree structure, ``bucket_mb``) from shapes/dtypes only
    (array leaves and ``ShapeDtypeStruct`` templates both work); the
    ``pack``/``unpack`` transforms are pure functions of the plan, safe to
    trace inside jit/shard_map and bit-exact inverses of each other
    (``unpack(pack(t)) == t`` element-for-element — buckets are a
    permutation-into-concatenation, no arithmetic).
    """

    def __init__(self, treedef, leaf_slots: List[LeafSlot],
                 buckets: List[BucketSpec], bucket_mb: float):
        self.treedef = treedef
        self.leaf_slots = leaf_slots
        self.buckets = buckets
        self.bucket_mb = float(bucket_mb)

    # -- accounting -------------------------------------------------------
    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    @property
    def num_leaves(self) -> int:
        return len(self.leaf_slots)

    @property
    def bucket_bytes(self) -> List[int]:
        return [b.nbytes for b in self.buckets]

    @property
    def total_bytes(self) -> int:
        return sum(self.bucket_bytes)

    def __repr__(self) -> str:
        return (
            f"BucketPlan(leaves={self.num_leaves}, "
            f"buckets={self.num_buckets}, "
            f"bytes={[b.nbytes for b in self.buckets]})"
        )

    # -- transforms -------------------------------------------------------
    def pack_leaves(self, leaves: Sequence[jax.Array]) -> List[jax.Array]:
        """Flat-leaf form of :func:`flatten_into_buckets` (wire code that
        already holds the flat list skips the treedef round-trip)."""
        if len(leaves) != self.num_leaves:
            raise ValueError(
                f"plan built for {self.num_leaves} leaves, got {len(leaves)}"
            )
        per_bucket: List[List[jax.Array]] = [[] for _ in self.buckets]
        for slot, leaf in zip(self.leaf_slots, leaves):
            flat = jnp.reshape(leaf, (-1,))
            if flat.dtype != jnp.dtype(slot.dtype):
                raise TypeError(
                    f"leaf dtype {flat.dtype} != planned {slot.dtype} "
                    f"(tree changed since the plan was built?)"
                )
            per_bucket[slot.bucket].append(flat)
        # slots were assigned in leaf order, so in-order concatenation
        # reproduces exactly the planned offsets
        return [
            jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            for parts in per_bucket
        ]

    def unpack_leaves(self, buckets: Sequence[jax.Array]) -> List[jax.Array]:
        if len(buckets) != self.num_buckets:
            raise ValueError(
                f"plan has {self.num_buckets} buckets, got {len(buckets)}"
            )
        out = []
        for slot in self.leaf_slots:
            flat = buckets[slot.bucket][slot.offset: slot.offset + slot.size]
            out.append(jnp.reshape(flat, slot.shape))
        return out

    def pack(self, tree: PyTree) -> List[jax.Array]:
        return self.pack_leaves(jax.tree.leaves(tree))

    def unpack(self, buckets: Sequence[jax.Array]) -> PyTree:
        return jax.tree.unflatten(self.treedef, self.unpack_leaves(buckets))

    def bucket_templates(self) -> List[jax.ShapeDtypeStruct]:
        """Abstract per-bucket templates (shape/dtype only) — e.g. the
        ZeRO-1 bucket-shard update needs target sizes without
        materializing a second copy of the parameters."""
        return [
            jax.ShapeDtypeStruct((b.size,), jnp.dtype(b.dtype))
            for b in self.buckets
        ]


def plan_buckets(tree: PyTree, bucket_mb: float) -> Optional[BucketPlan]:
    """Group ``tree``'s leaves by dtype into ~``bucket_mb``-MB flat buckets.

    Leaves keep their flatten order within each dtype group (locality: a
    transformer block's weights land in the same or adjacent buckets). A
    single leaf larger than the cap gets a bucket of its own — it is
    already one large transfer, splitting it would only add launches.
    ``bucket_mb <= 0`` returns ``None``: the per-leaf path, exactly
    today's behavior.
    """
    if bucket_mb is None or bucket_mb <= 0:
        return None
    cap_bytes = float(bucket_mb) * (1 << 20)
    leaves, treedef = jax.tree.flatten(tree)

    # dtype groups in first-appearance order, leaf order preserved within
    groups: dict = {}
    for i, leaf in enumerate(leaves):
        dt = jnp.dtype(getattr(leaf, "dtype", jnp.result_type(leaf)))
        groups.setdefault(dt.name, []).append(i)

    buckets: List[BucketSpec] = []
    slots: List[Optional[LeafSlot]] = [None] * len(leaves)
    for dt_name, idxs in groups.items():
        dt = jnp.dtype(dt_name)
        cur_size = 0  # elements in the open bucket
        cur_bucket = -1
        for i in idxs:
            leaf = leaves[i]
            shape = tuple(np.shape(leaf))
            size = int(np.prod(shape)) if shape else 1
            nbytes = size * dt.itemsize
            if cur_bucket < 0 or (
                cur_size > 0 and (cur_size * dt.itemsize + nbytes) > cap_bytes
            ):
                buckets.append(BucketSpec(dt, 0))
                cur_bucket = len(buckets) - 1
                cur_size = 0
            slots[i] = LeafSlot(cur_bucket, cur_size, size, shape, dt)
            cur_size += size
            buckets[cur_bucket] = BucketSpec(dt, cur_size)
    return BucketPlan(treedef, [s for s in slots], buckets, bucket_mb)


def flatten_into_buckets(plan: BucketPlan, tree: PyTree) -> List[jax.Array]:
    """Pure transform: pytree -> list of flat dtype-uniform buckets
    (inverse: :func:`unflatten_from_buckets`; bit-exact round trip)."""
    return plan.pack(tree)


def unflatten_from_buckets(
    plan: BucketPlan, buckets: Sequence[jax.Array]
) -> PyTree:
    """Pure transform: bucket list -> the original pytree structure."""
    return plan.unpack(buckets)


# ---------------------------------------------------------------------------
# Launch counting: make the win checkable (tests) and visible (bench).
# ---------------------------------------------------------------------------

_COLLECTIVE_OPS = (
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "collective_permute",
)


def count_collectives(lowered_text: str) -> dict:
    """Count collective ops in a lowered (StableHLO/HLO) program text.

    This counts *launches at the program level* — what the per-leaf tree-map
    emits one-per-leaf and bucketing emits one-per-bucket. (XLA's own
    all-reduce combiner may later merge some launches; counting the
    pre-optimization program keeps the number deterministic across
    backends, and the host DCN wire never gets XLA's help at all.)
    """
    out = {}
    for op in _COLLECTIVE_OPS:
        # stablehlo spells them "stablehlo.all_reduce"; HLO text spells
        # "all-reduce" — normalize both
        pat = re.compile(
            r"\b(?:stablehlo\.)?" + op.replace("_", "[-_]") + r"\b"
        )
        out[op] = len(pat.findall(lowered_text))
    out["total"] = sum(out[op] for op in _COLLECTIVE_OPS)
    return out


def lowered_collective_counts(jit_fn, *args, **kwargs) -> dict:
    """Lower a jitted function (ShapeDtypeStruct args welcome — nothing is
    executed or materialized) and count its collective launches."""
    return count_collectives(jit_fn.lower(*args, **kwargs).as_text())
