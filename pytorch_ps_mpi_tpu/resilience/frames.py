"""Self-verifying wire frames for the async PS gradient push path.

The PR 2 flat-bucket wire documented a hole: the one-time wire agreement
is enforced only through a total-byte-count check, so a codec/bucket
config mismatch that happens to preserve the byte count (identity codec
over a mixed-dtype tree, same-size codec-kw drift) silently mis-decodes,
and a size mismatch killed the PS with a ``RuntimeError`` from
``poll_grad``. This module closes both holes — and, since the v2 format,
carries the **push trace ID** the lineage layer
(:mod:`pytorch_ps_mpi_tpu.telemetry.lineage`) consumes — with a 36-byte
header prepended to every gradient push when frame checking is enabled
(``frame=True`` on the servers/workers, ``cfg["frame_check"]`` on the
async fleet):

``magic u32 | payload_len u32 | crc32 u32 | fingerprint u64 |``
``step u32 | seq u32 | send_wall f64``

- **magic** rejects garbage and framing drift (a peer without frames);
  the magic doubles as the format VERSION — a v1 (``PSF1``, 20-byte
  header, PR 3) frame against a v2 server is rejected with the explicit
  reason ``"version"``, counted but never fatal;
- **payload_len** rejects truncation inside an otherwise valid slot;
- **crc32** (of the payload bytes) rejects corruption — the chaos
  injector's ``corrupt`` fault and any real bit-rot on the path;
- **fingerprint** is an 8-byte BLAKE2b digest of the *wire
  configuration*: codec class name + constructor-visible kwargs, the
  per-unit wire layout (bucket shapes/dtypes — so ``bucket_mb`` drift is
  caught even at equal byte counts), the flat payload specs, and the
  template treedef. Worker and server compute it independently from
  their own config; any drift — even byte-count-preserving — fails the
  compare.
- **step / seq / send_wall** are the lineage extension (v2): the
  worker's training step, its monotonic push sequence number, and the
  wall-clock instant the frame was sealed at the encode site. Together
  with the transport-carried worker id they form the causal trace ID
  ``(worker, step, seq)`` every published version's lineage is built
  from, and the (send_wall, recv_wall) pair per frame is what the
  cross-process clock-skew fit consumes.

**Hop-composed lineage (the aggregation-tree extension).** A frame
pushed by a tree LEADER composes many worker pushes into one payload
(``parallel.tree``): the constituent trace IDs ride a fixed-size
**lineage trailer** appended after the codec payload, INSIDE the
CRC'd/length-checked frame payload region — the frame format itself
stays PSF2 and the native validators (size, fingerprint, CRC) cover the
trailer for free. A server constructed with ``tree_slots=K`` expects
every push's payload to be ``wire_bytes + trailer_bytes(K)`` long
(``K`` = the largest group's size; the slot count joins the wire
fingerprint, so slot drift is a ``"config"`` rejection, not a silent
mis-split); a leaf worker pushing DIRECTLY to such a server (leader-
crash fallback) appends a trailer composing only itself. The trailer is
``magic u32 | count u32`` followed by ``K`` fixed slots of
``worker u32 | step u32 | seq u32 | send_wall f64`` (unused slots
zeroed), so the expected payload size never varies with the round's
degraded/fallback shape. A validated frame whose trailer magic or
count is wrong is rejected with the explicit reason ``"trailer"``.

A failed check is a **counted, per-worker rejection**
(``PSServerTelemetry._reject_frame`` → ``ps_frames_rejected_total``),
never a server crash: one misconfigured worker cannot take down the PS
serving everyone else.

The params path (server → worker snapshot reads) is not framed: a
corrupted snapshot produces a bad gradient whose *push* the server then
judges; config drift is symmetric so the push-side fingerprint already
catches it.
"""

from __future__ import annotations

import hashlib
import json
import struct
import time
import zlib
from typing import Any, Callable, Optional, Tuple

import numpy as np

from pytorch_ps_mpi_tpu.telemetry.recorder import record_event

PyTree = Any

#: Header magic ("PSF2" little-endian) — the magic IS the format version.
#: Distinct from the TCP transport's outer 'TPS1' op-frame magic — this
#: header travels INSIDE the payload of a transport frame / shm mailbox
#: slot.
FRAME_MAGIC = 0x32465350
#: The PR 3 v1 magic ("PSF1", 20-byte header without the lineage
#: extension). Recognized only to reject it with reason ``"version"``.
FRAME_MAGIC_V1 = 0x31465350

# magic, payload_len, crc32, fingerprint, step, seq, send_wall
_HEADER = struct.Struct("<IIIQIId")
HEADER_BYTES = _HEADER.size
assert HEADER_BYTES == 36
HEADER_BYTES_V1 = 20
#: offset of the lineage extension inside the header (step u32 onward)
_LINEAGE = struct.Struct("<IId")
_LINEAGE_OFF = 20

#: lineage-trailer magic ("PSTL" little-endian) — marks the hop-composed
#: trace-ID block appended after the codec payload on tree wires
TRAILER_MAGIC = 0x4C545350
_TRAILER_HEAD = struct.Struct("<II")          # magic, count
_TRAILER_ENTRY = struct.Struct("<IIId")       # worker, step, seq, send_wall
TRAILER_ENTRY_BYTES = _TRAILER_ENTRY.size
assert TRAILER_ENTRY_BYTES == 20


def trailer_bytes(slots: int) -> int:
    """On-wire size of a ``slots``-capacity lineage trailer (0 → 0)."""
    slots = int(slots)
    return 0 if slots <= 0 else _TRAILER_HEAD.size + slots * TRAILER_ENTRY_BYTES


def pack_trailer(out: np.ndarray, off: int, entries, slots: int) -> int:
    """Write a lineage trailer into ``out`` at ``off`` and return the
    bytes written. ``entries`` is a sequence of ``(worker, step, seq,
    send_wall)`` tuples or dicts with those keys; at most ``slots`` are
    kept (oldest first — a degraded fold can never overflow its declared
    capacity, the excess is truncated loudly by the caller's own
    accounting). Unused slots are zeroed so the frame bytes are
    deterministic."""
    slots = int(slots)
    norm = []
    for e in entries or ():
        if isinstance(e, dict):
            norm.append((int(e["worker"]), int(e.get("step", 0)),
                         int(e.get("seq", 0)),
                         float(e.get("send_wall", 0.0))))
        else:
            w, s, q, t = e
            norm.append((int(w), int(s), int(q), float(t)))
    norm = norm[:slots]
    _TRAILER_HEAD.pack_into(out, off, TRAILER_MAGIC, len(norm))
    pos = off + _TRAILER_HEAD.size
    for w, s, q, t in norm:
        _TRAILER_ENTRY.pack_into(out, pos, w & 0xFFFFFFFF, s & 0xFFFFFFFF,
                                 q & 0xFFFFFFFF, t)
        pos += TRAILER_ENTRY_BYTES
    end = off + trailer_bytes(slots)
    out[pos:end] = 0
    return end - off


def read_composed(payload: np.ndarray, wire_bytes: int,
                  slots: int) -> Optional[list]:
    """Parse the lineage trailer of a VALIDATED tree-wire frame payload
    (``payload`` = codec payload + trailer). Returns the composed
    ``[{worker, step, seq, send_wall}, ...]`` list, or None when the
    trailer is malformed (wrong magic, impossible count) — callers
    reject the frame with reason ``"trailer"``."""
    slots = int(slots)
    need = wire_bytes + trailer_bytes(slots)
    if payload.nbytes != need:
        return None
    magic, count = _TRAILER_HEAD.unpack_from(payload, wire_bytes)
    # count == 0 is rejected too: a composed frame that composes
    # NOTHING would drive the root round's weighting denominator to
    # zero — it is malformed, not merely empty
    if magic != TRAILER_MAGIC or count > slots or count == 0:
        return None
    out = []
    pos = wire_bytes + _TRAILER_HEAD.size
    for _ in range(count):
        w, s, q, t = _TRAILER_ENTRY.unpack_from(payload, pos)
        out.append({"worker": int(w), "step": int(s), "seq": int(q),
                    "send_wall": float(t)})
        pos += TRAILER_ENTRY_BYTES
    return out


def _codec_desc(code) -> dict:
    """Canonical JSON-able description of a codec's configuration: class
    name + every public primitive-valued attribute (the constructor
    kwargs land there). Jitted closures / arrays / PRNG state are
    excluded — they are derived, not configuration."""
    kw = {}
    for k, v in vars(code).items():
        if k.startswith("_"):
            continue
        if isinstance(v, (bool, int, float, str, type(None))):
            kw[k] = v
        elif isinstance(v, (tuple, list)) and all(
                isinstance(x, (bool, int, float, str)) for x in v):
            kw[k] = list(v)
    return {"cls": type(code).__name__, "kw": kw}


def wire_fingerprint(wire, template: PyTree, tree_slots: int = 0) -> int:
    """64-bit fingerprint of the wire agreement. ``wire`` is a
    ``CodecWire`` (or None for the raw-f32 wire); ``template`` the
    parameter pytree. Both ends compute this from their OWN config — a
    matching fingerprint means codec name/kw, bucket layout, payload
    specs, and tree structure all agree. Per-worker codec seeds do not
    enter (they legitimately differ across the fleet). ``tree_slots``
    (the lineage-trailer capacity of a tree wire) joins the agreement
    when nonzero — slot drift is then a ``"config"`` rejection, never a
    mis-split — and is omitted at 0 so pre-tree fingerprints are
    unchanged."""
    import jax

    if wire is None:
        leaves, treedef = jax.tree.flatten(template)
        desc = {
            "codec": None,
            "units": [[list(np.shape(l)), "float32"] for l in leaves],
            "treedef": str(treedef),
        }
    else:
        desc = {
            "codec": _codec_desc(wire.code),
            # unit layout: bucket sizes/dtypes when bucketing, per-leaf
            # shapes otherwise — catches bucket_mb drift at equal bytes
            "units": [[list(s), str(np.dtype(d))]
                      for s, d in zip(wire.shapes, wire.dtypes)],
            "specs": [[list(s), str(np.dtype(d))]
                      for s, d in wire._flat_specs],
            "treedef": str(wire.treedef),
        }
    if int(tree_slots) > 0:
        desc["tree_slots"] = int(tree_slots)
    blob = json.dumps(desc, sort_keys=True).encode()
    return int.from_bytes(
        hashlib.blake2b(blob, digest_size=8).digest(), "little"
    )


def seal_frame(out: np.ndarray, payload: np.ndarray, fingerprint: int,
               step: int = 0, seq: int = 0,
               send_wall: Optional[float] = None,
               composed=None, tree_slots: int = 0) -> np.ndarray:
    """Write header + payload into the preallocated uint8 buffer ``out``
    (sized ``HEADER_BYTES + payload.nbytes`` — plus
    ``trailer_bytes(tree_slots)`` on a tree wire — by the caller) and
    return the exact-length view. ``step``/``seq`` are the push's
    trace-ID fields (the transport carries the worker id); ``send_wall``
    defaults to now — THE encode-site timestamp lineage e2e latency and
    clock-skew estimation are measured from. With ``tree_slots > 0`` a
    hop-composed lineage trailer (``composed`` entries — defaulting to
    nothing, which a leaf caller should never want; transports default
    it to the pushing worker itself) is appended after the payload, and
    the header's length + CRC cover payload AND trailer, so the native
    validators check the trailer for free. One extra memcpy per push
    versus the unframed wire — the price of the end-to-end check."""
    if payload.dtype != np.uint8:
        payload = payload.view(np.uint8)
    payload = payload.reshape(-1)
    n = payload.nbytes
    out[HEADER_BYTES:HEADER_BYTES + n] = payload
    total = n
    if int(tree_slots) > 0:
        total += pack_trailer(out, HEADER_BYTES + n, composed or (),
                              tree_slots)
    body = out[HEADER_BYTES:HEADER_BYTES + total]
    _HEADER.pack_into(out, 0, FRAME_MAGIC, total,
                      zlib.crc32(body) & 0xFFFFFFFF, fingerprint,
                      int(step) & 0xFFFFFFFF, int(seq) & 0xFFFFFFFF,
                      time.time() if send_wall is None else float(send_wall))
    return out[:HEADER_BYTES + total]


def open_frame(
    buf: np.ndarray,
    fingerprint: int,
    expected_payload: Optional[int] = None,
) -> Tuple[Optional[np.ndarray], Optional[str]]:
    """Validate a received frame. Returns ``(payload_view, None)`` on
    success or ``(None, reason)`` where reason is one of ``"short"``
    (no room for a header), ``"version"`` (a v1 frame from a peer
    running the pre-lineage format — old frames are rejected, never
    mis-parsed), ``"magic"``, ``"size"`` (declared/expected length
    mismatch — the misconfigured-worker case), ``"config"``
    (fingerprint drift), ``"corrupt"`` (CRC failure). The payload is a
    zero-copy view into ``buf``. Lineage fields are NOT returned here —
    callers read them from a validated frame via :func:`read_lineage`."""
    if buf.nbytes < 4:
        return None, "short"
    (magic,) = struct.unpack_from("<I", buf)
    if magic == FRAME_MAGIC_V1:
        return None, "version"
    if magic != FRAME_MAGIC:
        return None, "magic"
    if buf.nbytes < HEADER_BYTES:
        return None, "short"
    _, plen, crc, fp, _, _, _ = _HEADER.unpack_from(buf)
    if plen != buf.nbytes - HEADER_BYTES or (
            expected_payload is not None and plen != expected_payload):
        return None, "size"
    if fp != fingerprint:
        return None, "config"
    payload = buf[HEADER_BYTES:]
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        return None, "corrupt"
    return payload, None


def read_lineage(buf: np.ndarray) -> Tuple[int, int, float]:
    """``(step, seq, send_wall)`` from a VALIDATED v2 frame — the trace
    ID the worker's encode site stamped (plus the worker id the
    transport itself carries)."""
    step, seq, send_wall = _LINEAGE.unpack_from(buf, _LINEAGE_OFF)
    return int(step), int(seq), float(send_wall)


#: C++ FrameStatus codes (native/tcpps.cpp) → open_frame reason strings.
BATCH_REASONS = {1: "short", 2: "version", 3: "magic", 4: "size",
                 5: "config", 6: "corrupt"}


def _open_with_epochs(server, buf: np.ndarray):
    """Open a frame against the server's CURRENT wire agreement, falling
    back to any still-accepted older epoch (the controller's codec
    renegotiation installs the new wire beside the old one in
    ``server._epoch_table``; in-flight old-epoch frames are consumed —
    decoded with THEIR epoch's wire — never rejected). Returns
    ``(payload, err, wire, epoch)``: ``wire`` is None for a
    current-epoch frame (callers use the server's own decode path)."""
    payload, err = open_frame(buf, server._fingerprint,
                              server._expected_payload)
    if err is None:
        # note the worker's epoch: the controller retires the old epoch
        # once every live worker has been seen on the current one
        return payload, None, None, getattr(server, "_epoch", 0)
    table = getattr(server, "_epoch_table", None)
    if err in ("config", "size") and table:
        for fp_old, ep in table.items():
            payload, err2 = open_frame(buf, fp_old, ep["expected"])
            if err2 is None:
                server.epoch_old_frames = getattr(
                    server, "epoch_old_frames", 0) + 1
                return payload, None, ep["wire"], ep["epoch"]
    return None, err, None, None


def _split_composed(server, wid: int, payload: np.ndarray):
    """Tree-wire post-validation step shared by both consume paths:
    split a validated frame payload into (codec payload, composed
    lineage entries). On a non-tree server this is the identity.
    Returns ``(wire_payload, composed, ok)``; a malformed trailer is a
    counted ``"trailer"`` rejection (``ok=False``). Every valid frame's
    composed count — stale-dropped ones included — advances
    ``server.tree_composed``, the canonical exact-accounting counter
    tree drivers stop on."""
    slots = int(getattr(server, "tree_slots", 0) or 0)
    if not slots:
        return payload, None, True
    entries = read_composed(payload, server._wire_payload_bytes, slots)
    if entries is None:
        server._reject_frame(wid, "trailer")
        return None, None, False
    server.tree_composed += len(entries)
    return payload[:server._wire_payload_bytes], entries, True


def framed_batch_consume(server, frames_iter, raw: bool = False) -> list:
    """The batched twin of :func:`framed_poll` for transports whose
    native side already validated the frames (``tps_server_pop_grad_batch``
    runs the magic/version/size/fingerprint/CRC checks in C++ and hands
    back reason-coded metas + validated payload views). Applies the SAME
    accounting — per-worker rejection counting, bounded staleness,
    lineage feed, ``serve.consume`` spans, ``last_push_meta`` — so the
    two ingest paths are indistinguishable to everything downstream.

    ``frames_iter`` yields ``(worker, version, status, payload_view,
    step, seq, send_wall)``; ``status`` 0 means validated. Returns the
    consumed ``(worker, version, grad_or_payload)`` list (stale drops
    and rejections are counted, not returned); the consumed items'
    metas land on ``server.last_batch_metas`` in the same order (a
    batch overwrites ``last_push_meta`` per item, so consumers that
    need EVERY item's trace ID — the tree leader — read the aligned
    list instead). Payload views alias the transport's batch buffer —
    valid until the next batched pop."""
    lt = getattr(server, "lineage_tracker", None)
    out = []
    metas = []
    for wid, version, status, payload, lstep, lseq, send_wall in frames_iter:
        # any frame — valid or not — proves the worker is alive
        server.last_seen[wid] = time.time()
        if status:
            server._reject_frame(wid, BATCH_REASONS.get(status, "magic"))
            continue
        recv_wall = time.time()
        full_bytes = payload.nbytes
        payload, composed, ok = _split_composed(server, wid, payload)
        if not ok:
            continue
        staleness = max(0, server.version - version)
        server.staleness_seen[staleness] = (
            server.staleness_seen.get(staleness, 0) + 1
        )
        server.grads_received += 1
        server.bytes_received += full_bytes
        meta = {
            "worker": int(wid), "step": lstep, "seq": lseq,
            "version_read": int(version), "staleness": int(staleness),
            "bytes": int(full_bytes),
            "send_wall": send_wall, "recv_wall": recv_wall,
        }
        if composed is not None:
            meta["composed"] = composed
        if staleness <= server.max_staleness:
            t_dec = time.monotonic()
            if raw:
                grad = payload
                meta["decode_s"] = 0.0  # deferred to the round's ONE decode
            else:
                grad = server._decode_payload(payload)
                meta["decode_s"] = round(time.monotonic() - t_dec, 6)
            server.last_push_meta = meta
            record_event("serve.consume", kind="span", ts=t_dec,
                         dur=meta["decode_s"], step=lstep,
                         src_worker=int(wid), seq=lseq,
                         staleness=int(staleness))
            if lt is not None:
                lt.observe_consume(meta)
            if composed is not None:
                # the serve loop pops one count per consumed item — the
                # composed-weighted averaging denominator (tree mode)
                server._composed_queue.append(len(composed))
            out.append((int(wid), int(version), grad))
            metas.append(meta)
        else:
            server.stale_drops += 1
            if lt is not None:
                meta["stale_drop"] = True
                lt.observe_consume(meta)
    server.last_batch_metas = metas
    return out


def framed_poll(
    server, pop_once: Callable[[], Tuple[int, int, int]],
    raw: bool = False,
) -> Optional[Tuple[int, int, PyTree]]:
    """The ONE frame-checking poll loop both PS transports share (the
    transports differ only in how a frame is popped — ``pop_once``
    returns ``(nbytes, worker, version)`` with ``nbytes <= 0`` meaning
    nothing pending, the frame bytes landing in ``server._grad_buf``).

    Every popped frame is validated (magic/version, size, fingerprint,
    CRC) BEFORE any gradient bookkeeping; a bad frame is a counted
    per-worker rejection (``server._reject_frame``) and polling
    continues — one corrupting or misconfigured worker can never kill
    the PS serving everyone else. Valid frames then get the standard
    bounded-staleness treatment (count, drop-if-over, decode via
    ``server._decode_payload``) — and their lineage fields (step, seq,
    send_wall from the header; recv time, staleness, decode wall
    measured here) feed ``server.lineage_tracker`` when one is attached
    and land on ``server.last_push_meta`` either way, so the serve loop
    can read the consumed push's trace ID without re-parsing anything.

    ``raw=True`` is the homomorphic-aggregation mode: a consumed push is
    returned as ``(worker, version, payload_view)`` — validated, counted
    and lineage-fed exactly as above, but NOT decoded (the serve loop
    folds the bytes into a compressed accumulator and the one decode per
    published version happens there). The view aliases the server's
    receive buffer: copy or fold before the next poll."""
    lt = getattr(server, "lineage_tracker", None)
    while True:
        n, wid, version = pop_once()
        if n <= 0:
            return None
        # any frame — valid or not — proves the worker is alive
        server.last_seen[wid] = time.time()
        payload, err, old_wire, epoch = _open_with_epochs(
            server, server._grad_buf[:n])
        if err is not None:
            server._reject_frame(wid, err)
            continue
        if getattr(server, "_epoch_table", None) is not None:
            server.__dict__.setdefault("_epoch_seen", {})[wid] = epoch
        recv_wall = time.time()
        lstep, lseq, send_wall = read_lineage(server._grad_buf)
        full_bytes = payload.nbytes
        payload, composed, ok = _split_composed(server, wid, payload)
        if not ok:
            continue
        staleness = max(0, server.version - version)
        server.staleness_seen[staleness] = (
            server.staleness_seen.get(staleness, 0) + 1
        )
        server.grads_received += 1
        server.bytes_received += full_bytes
        meta = {
            "worker": int(wid), "step": lstep, "seq": lseq,
            "version_read": int(version), "staleness": int(staleness),
            "bytes": int(full_bytes),
            "send_wall": send_wall, "recv_wall": recv_wall,
        }
        if composed is not None:
            meta["composed"] = composed
        if staleness <= server.max_staleness:
            t_dec = time.monotonic()
            if raw and old_wire is None:
                grad = payload
                meta["decode_s"] = 0.0  # deferred to the round's ONE decode
            else:
                # an old-epoch frame is decoded with ITS epoch's wire —
                # even in raw mode, where it cannot enter the current
                # wire's compressed accumulator (the controller suspends
                # aggregation around a renegotiation, so this is the
                # defensive path, not the expected one)
                grad = server._decode_payload(payload, wire=old_wire)
                meta["decode_s"] = round(time.monotonic() - t_dec, 6)
            server.last_push_meta = meta
            # the server-side anchor of the cross-process flow arrow:
            # a span carrying the same (worker, step, seq) trace ID the
            # worker's push span carries
            record_event("serve.consume", kind="span", ts=t_dec,
                         dur=meta["decode_s"], step=lstep,
                         src_worker=int(wid), seq=lseq,
                         staleness=int(staleness))
            if lt is not None:
                lt.observe_consume(meta)
            if composed is not None:
                server._composed_queue.append(len(composed))
            return wid, version, grad
        server.stale_drops += 1
        if lt is not None:
            meta["stale_drop"] = True
            lt.observe_consume(meta)
