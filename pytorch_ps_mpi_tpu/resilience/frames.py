"""Self-verifying wire frames for the async PS gradient push path.

The PR 2 flat-bucket wire documented a hole: the one-time wire agreement
is enforced only through a total-byte-count check, so a codec/bucket
config mismatch that happens to preserve the byte count (identity codec
over a mixed-dtype tree, same-size codec-kw drift) silently mis-decodes,
and a size mismatch killed the PS with a ``RuntimeError`` from
``poll_grad``. This module closes both holes — and, since the v2 format,
carries the **push trace ID** the lineage layer
(:mod:`pytorch_ps_mpi_tpu.telemetry.lineage`) consumes — with a 36-byte
header prepended to every gradient push when frame checking is enabled
(``frame=True`` on the servers/workers, ``cfg["frame_check"]`` on the
async fleet):

``magic u32 | payload_len u32 | crc32 u32 | fingerprint u64 |``
``step u32 | seq u32 | send_wall f64``

- **magic** rejects garbage and framing drift (a peer without frames);
  the magic doubles as the format VERSION — a v1 (``PSF1``, 20-byte
  header, PR 3) frame against a v2 server is rejected with the explicit
  reason ``"version"``, counted but never fatal;
- **payload_len** rejects truncation inside an otherwise valid slot;
- **crc32** (of the payload bytes) rejects corruption — the chaos
  injector's ``corrupt`` fault and any real bit-rot on the path;
- **fingerprint** is an 8-byte BLAKE2b digest of the *wire
  configuration*: codec class name + constructor-visible kwargs, the
  per-unit wire layout (bucket shapes/dtypes — so ``bucket_mb`` drift is
  caught even at equal byte counts), the flat payload specs, and the
  template treedef. Worker and server compute it independently from
  their own config; any drift — even byte-count-preserving — fails the
  compare.
- **step / seq / send_wall** are the lineage extension (v2): the
  worker's training step, its monotonic push sequence number, and the
  wall-clock instant the frame was sealed at the encode site. Together
  with the transport-carried worker id they form the causal trace ID
  ``(worker, step, seq)`` every published version's lineage is built
  from, and the (send_wall, recv_wall) pair per frame is what the
  cross-process clock-skew fit consumes.

A failed check is a **counted, per-worker rejection**
(``PSServerTelemetry._reject_frame`` → ``ps_frames_rejected_total``),
never a server crash: one misconfigured worker cannot take down the PS
serving everyone else.

The params path (server → worker snapshot reads) is not framed: a
corrupted snapshot produces a bad gradient whose *push* the server then
judges; config drift is symmetric so the push-side fingerprint already
catches it.
"""

from __future__ import annotations

import hashlib
import json
import struct
import time
import zlib
from typing import Any, Callable, Optional, Tuple

import numpy as np

from pytorch_ps_mpi_tpu.telemetry.recorder import record_event

PyTree = Any

#: Header magic ("PSF2" little-endian) — the magic IS the format version.
#: Distinct from the TCP transport's outer 'TPS1' op-frame magic — this
#: header travels INSIDE the payload of a transport frame / shm mailbox
#: slot.
FRAME_MAGIC = 0x32465350
#: The PR 3 v1 magic ("PSF1", 20-byte header without the lineage
#: extension). Recognized only to reject it with reason ``"version"``.
FRAME_MAGIC_V1 = 0x31465350

# magic, payload_len, crc32, fingerprint, step, seq, send_wall
_HEADER = struct.Struct("<IIIQIId")
HEADER_BYTES = _HEADER.size
assert HEADER_BYTES == 36
HEADER_BYTES_V1 = 20
#: offset of the lineage extension inside the header (step u32 onward)
_LINEAGE = struct.Struct("<IId")
_LINEAGE_OFF = 20


def _codec_desc(code) -> dict:
    """Canonical JSON-able description of a codec's configuration: class
    name + every public primitive-valued attribute (the constructor
    kwargs land there). Jitted closures / arrays / PRNG state are
    excluded — they are derived, not configuration."""
    kw = {}
    for k, v in vars(code).items():
        if k.startswith("_"):
            continue
        if isinstance(v, (bool, int, float, str, type(None))):
            kw[k] = v
        elif isinstance(v, (tuple, list)) and all(
                isinstance(x, (bool, int, float, str)) for x in v):
            kw[k] = list(v)
    return {"cls": type(code).__name__, "kw": kw}


def wire_fingerprint(wire, template: PyTree) -> int:
    """64-bit fingerprint of the wire agreement. ``wire`` is a
    ``CodecWire`` (or None for the raw-f32 wire); ``template`` the
    parameter pytree. Both ends compute this from their OWN config — a
    matching fingerprint means codec name/kw, bucket layout, payload
    specs, and tree structure all agree. Per-worker codec seeds do not
    enter (they legitimately differ across the fleet)."""
    import jax

    if wire is None:
        leaves, treedef = jax.tree.flatten(template)
        desc = {
            "codec": None,
            "units": [[list(np.shape(l)), "float32"] for l in leaves],
            "treedef": str(treedef),
        }
    else:
        desc = {
            "codec": _codec_desc(wire.code),
            # unit layout: bucket sizes/dtypes when bucketing, per-leaf
            # shapes otherwise — catches bucket_mb drift at equal bytes
            "units": [[list(s), str(np.dtype(d))]
                      for s, d in zip(wire.shapes, wire.dtypes)],
            "specs": [[list(s), str(np.dtype(d))]
                      for s, d in wire._flat_specs],
            "treedef": str(wire.treedef),
        }
    blob = json.dumps(desc, sort_keys=True).encode()
    return int.from_bytes(
        hashlib.blake2b(blob, digest_size=8).digest(), "little"
    )


def seal_frame(out: np.ndarray, payload: np.ndarray, fingerprint: int,
               step: int = 0, seq: int = 0,
               send_wall: Optional[float] = None) -> np.ndarray:
    """Write header + payload into the preallocated uint8 buffer ``out``
    (sized ``HEADER_BYTES + payload.nbytes`` by the caller) and return
    the exact-length view. ``step``/``seq`` are the push's trace-ID
    fields (the transport carries the worker id); ``send_wall`` defaults
    to now — THE encode-site timestamp lineage e2e latency and clock-
    skew estimation are measured from. One extra memcpy per push versus
    the unframed wire — the price of the end-to-end check."""
    if payload.dtype != np.uint8:
        payload = payload.view(np.uint8)
    payload = payload.reshape(-1)
    n = payload.nbytes
    _HEADER.pack_into(out, 0, FRAME_MAGIC, n,
                      zlib.crc32(payload) & 0xFFFFFFFF, fingerprint,
                      int(step) & 0xFFFFFFFF, int(seq) & 0xFFFFFFFF,
                      time.time() if send_wall is None else float(send_wall))
    out[HEADER_BYTES:HEADER_BYTES + n] = payload
    return out[:HEADER_BYTES + n]


def open_frame(
    buf: np.ndarray,
    fingerprint: int,
    expected_payload: Optional[int] = None,
) -> Tuple[Optional[np.ndarray], Optional[str]]:
    """Validate a received frame. Returns ``(payload_view, None)`` on
    success or ``(None, reason)`` where reason is one of ``"short"``
    (no room for a header), ``"version"`` (a v1 frame from a peer
    running the pre-lineage format — old frames are rejected, never
    mis-parsed), ``"magic"``, ``"size"`` (declared/expected length
    mismatch — the misconfigured-worker case), ``"config"``
    (fingerprint drift), ``"corrupt"`` (CRC failure). The payload is a
    zero-copy view into ``buf``. Lineage fields are NOT returned here —
    callers read them from a validated frame via :func:`read_lineage`."""
    if buf.nbytes < 4:
        return None, "short"
    (magic,) = struct.unpack_from("<I", buf)
    if magic == FRAME_MAGIC_V1:
        return None, "version"
    if magic != FRAME_MAGIC:
        return None, "magic"
    if buf.nbytes < HEADER_BYTES:
        return None, "short"
    _, plen, crc, fp, _, _, _ = _HEADER.unpack_from(buf)
    if plen != buf.nbytes - HEADER_BYTES or (
            expected_payload is not None and plen != expected_payload):
        return None, "size"
    if fp != fingerprint:
        return None, "config"
    payload = buf[HEADER_BYTES:]
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        return None, "corrupt"
    return payload, None


def read_lineage(buf: np.ndarray) -> Tuple[int, int, float]:
    """``(step, seq, send_wall)`` from a VALIDATED v2 frame — the trace
    ID the worker's encode site stamped (plus the worker id the
    transport itself carries)."""
    step, seq, send_wall = _LINEAGE.unpack_from(buf, _LINEAGE_OFF)
    return int(step), int(seq), float(send_wall)


#: C++ FrameStatus codes (native/tcpps.cpp) → open_frame reason strings.
BATCH_REASONS = {1: "short", 2: "version", 3: "magic", 4: "size",
                 5: "config", 6: "corrupt"}


def framed_batch_consume(server, frames_iter, raw: bool = False) -> list:
    """The batched twin of :func:`framed_poll` for transports whose
    native side already validated the frames (``tps_server_pop_grad_batch``
    runs the magic/version/size/fingerprint/CRC checks in C++ and hands
    back reason-coded metas + validated payload views). Applies the SAME
    accounting — per-worker rejection counting, bounded staleness,
    lineage feed, ``serve.consume`` spans, ``last_push_meta`` — so the
    two ingest paths are indistinguishable to everything downstream.

    ``frames_iter`` yields ``(worker, version, status, payload_view,
    step, seq, send_wall)``; ``status`` 0 means validated. Returns the
    consumed ``(worker, version, grad_or_payload)`` list (stale drops
    and rejections are counted, not returned). Payload views alias the
    transport's batch buffer — valid until the next batched pop."""
    lt = getattr(server, "lineage_tracker", None)
    out = []
    for wid, version, status, payload, lstep, lseq, send_wall in frames_iter:
        # any frame — valid or not — proves the worker is alive
        server.last_seen[wid] = time.time()
        if status:
            server._reject_frame(wid, BATCH_REASONS.get(status, "magic"))
            continue
        recv_wall = time.time()
        staleness = max(0, server.version - version)
        server.staleness_seen[staleness] = (
            server.staleness_seen.get(staleness, 0) + 1
        )
        server.grads_received += 1
        server.bytes_received += payload.nbytes
        meta = {
            "worker": int(wid), "step": lstep, "seq": lseq,
            "version_read": int(version), "staleness": int(staleness),
            "bytes": int(payload.nbytes),
            "send_wall": send_wall, "recv_wall": recv_wall,
        }
        if staleness <= server.max_staleness:
            t_dec = time.monotonic()
            if raw:
                grad = payload
                meta["decode_s"] = 0.0  # deferred to the round's ONE decode
            else:
                grad = server._decode_payload(payload)
                meta["decode_s"] = round(time.monotonic() - t_dec, 6)
            server.last_push_meta = meta
            record_event("serve.consume", kind="span", ts=t_dec,
                         dur=meta["decode_s"], step=lstep,
                         src_worker=int(wid), seq=lseq,
                         staleness=int(staleness))
            if lt is not None:
                lt.observe_consume(meta)
            out.append((int(wid), int(version), grad))
        else:
            server.stale_drops += 1
            if lt is not None:
                meta["stale_drop"] = True
                lt.observe_consume(meta)
    return out


def framed_poll(
    server, pop_once: Callable[[], Tuple[int, int, int]],
    raw: bool = False,
) -> Optional[Tuple[int, int, PyTree]]:
    """The ONE frame-checking poll loop both PS transports share (the
    transports differ only in how a frame is popped — ``pop_once``
    returns ``(nbytes, worker, version)`` with ``nbytes <= 0`` meaning
    nothing pending, the frame bytes landing in ``server._grad_buf``).

    Every popped frame is validated (magic/version, size, fingerprint,
    CRC) BEFORE any gradient bookkeeping; a bad frame is a counted
    per-worker rejection (``server._reject_frame``) and polling
    continues — one corrupting or misconfigured worker can never kill
    the PS serving everyone else. Valid frames then get the standard
    bounded-staleness treatment (count, drop-if-over, decode via
    ``server._decode_payload``) — and their lineage fields (step, seq,
    send_wall from the header; recv time, staleness, decode wall
    measured here) feed ``server.lineage_tracker`` when one is attached
    and land on ``server.last_push_meta`` either way, so the serve loop
    can read the consumed push's trace ID without re-parsing anything.

    ``raw=True`` is the homomorphic-aggregation mode: a consumed push is
    returned as ``(worker, version, payload_view)`` — validated, counted
    and lineage-fed exactly as above, but NOT decoded (the serve loop
    folds the bytes into a compressed accumulator and the one decode per
    published version happens there). The view aliases the server's
    receive buffer: copy or fold before the next poll."""
    lt = getattr(server, "lineage_tracker", None)
    while True:
        n, wid, version = pop_once()
        if n <= 0:
            return None
        # any frame — valid or not — proves the worker is alive
        server.last_seen[wid] = time.time()
        payload, err = open_frame(
            server._grad_buf[:n], server._fingerprint,
            server._expected_payload,
        )
        if err is not None:
            server._reject_frame(wid, err)
            continue
        recv_wall = time.time()
        lstep, lseq, send_wall = read_lineage(server._grad_buf)
        staleness = max(0, server.version - version)
        server.staleness_seen[staleness] = (
            server.staleness_seen.get(staleness, 0) + 1
        )
        server.grads_received += 1
        server.bytes_received += payload.nbytes
        meta = {
            "worker": int(wid), "step": lstep, "seq": lseq,
            "version_read": int(version), "staleness": int(staleness),
            "bytes": int(payload.nbytes),
            "send_wall": send_wall, "recv_wall": recv_wall,
        }
        if staleness <= server.max_staleness:
            t_dec = time.monotonic()
            if raw:
                grad = payload
                meta["decode_s"] = 0.0  # deferred to the round's ONE decode
            else:
                grad = server._decode_payload(payload)
                meta["decode_s"] = round(time.monotonic() - t_dec, 6)
            server.last_push_meta = meta
            # the server-side anchor of the cross-process flow arrow:
            # a span carrying the same (worker, step, seq) trace ID the
            # worker's push span carries
            record_event("serve.consume", kind="span", ts=t_dec,
                         dur=meta["decode_s"], step=lstep,
                         src_worker=int(wid), seq=lseq,
                         staleness=int(staleness))
            if lt is not None:
                lt.observe_consume(meta)
            return wid, version, grad
        server.stale_drops += 1
        if lt is not None:
            meta["stale_drop"] = True
            lt.observe_consume(meta)
