"""Resilience layer: make every failure scenario injectable and survivable.

The async PS protocol (AsySG-InCon, Lian et al. 2015) tolerates *stale*
workers by design, but the stack — like the reference MPI job it
reproduces — used to die on *failed* ones: a worker hitting a socket EOF
or a timed-out ack raised and exited, a server restart stranded every
worker, and a dead worker wedged ``sync_barrier`` rounds forever. This
package closes that gap with four cooperating pieces:

- :mod:`.faults` — a seeded, deterministic :class:`FaultInjector`. A
  fault plan is a JSON-able list of ``{at_step, worker, kind}`` entries
  (kinds: drop / delay / duplicate / corrupt / crash_worker /
  crash_server) consulted by the worker loop and the serve loop, so every
  chaos scenario is a reproducible test, not a flake: the same plan and
  seed produce the same injected-event log, byte-for-byte.
- :mod:`.frames` — self-verifying wire frames: a 36-byte v2 header
  (magic/version, payload length, CRC32, config fingerprint hashing
  codec name/kw + bucket layout + template treedef, plus the lineage
  trace-ID fields step/seq/send_wall) on every gradient push, so
  payload corruption, codec/bucket config drift — documented as
  "undetectable" by the flat-bucket wire — and stale-format peers all
  fail loudly as a counted, per-worker rejection instead of a silent
  mis-decode or a PS crash.
- :mod:`.worker` — :class:`ResilientWorker`, wrapping ``ShmPSWorker`` /
  ``TcpPSWorker`` with exponential backoff + deterministic jitter on
  timeouts and a full reconnect on EOF/transport errors, so a server
  restart-from-checkpoint is survived transparently.
- :mod:`.supervisor` — :class:`Supervisor`, the process that watches
  ``server.stragglers()``/``connected()``, respawns dead workers via
  ``spawn_worker``, and restarts a crashed server with ``resume=True``
  from its checkpoint cadence, keeping the publish version monotonic.

Every recovery event (retry, reconnect, respawn, rejected frame,
degraded round, server restart) flows into the telemetry layer: flight-
recorder events in the per-process JSONLs and counters on the PS
``/metrics`` registry (``ps_frames_rejected_total``,
``ps_worker_respawns_total``, ``ps_server_restarts_total``,
``ps_worker_reconnects_total``, ``ps_degraded_rounds_total``).
"""

from pytorch_ps_mpi_tpu.resilience.faults import (
    CRASH_EXIT_CODE,
    FAULT_KINDS,
    FaultInjector,
    InjectedServerCrash,
    load_fault_log,
    normalize_plan,
)
from pytorch_ps_mpi_tpu.resilience.frames import (
    FRAME_MAGIC,
    FRAME_MAGIC_V1,
    HEADER_BYTES,
    HEADER_BYTES_V1,
    open_frame,
    read_lineage,
    seal_frame,
    wire_fingerprint,
)
from pytorch_ps_mpi_tpu.resilience.supervisor import Supervisor
from pytorch_ps_mpi_tpu.resilience.worker import ResilientWorker

__all__ = [
    "CRASH_EXIT_CODE",
    "FAULT_KINDS",
    "FaultInjector",
    "InjectedServerCrash",
    "load_fault_log",
    "normalize_plan",
    "FRAME_MAGIC",
    "FRAME_MAGIC_V1",
    "HEADER_BYTES",
    "HEADER_BYTES_V1",
    "open_frame",
    "read_lineage",
    "seal_frame",
    "wire_fingerprint",
    "Supervisor",
    "ResilientWorker",
]
