"""Supervisor: keep an async PS job alive through worker AND server death.

The elastic-recovery pieces already existed as manual moves documented in
the ops runbook — watch ``stragglers()``/``connected()``, respawn a dead
worker with the same id (``reset_worker_slot`` first on shm), restart a
dead server with ``resume=True`` — but nothing *performed* them. The
:class:`Supervisor` is that missing process-level loop:

- it owns the server lifecycle: builds the server from the job ``cfg``
  (shm or TCP — the TCP port is pinned after the first bind so workers
  can always re-reach the same address), runs :func:`serve`, and on a
  server crash (:class:`InjectedServerCrash` from the fault injector, or
  any crash of the serve loop itself) restarts it with ``resume=True``
  from the checkpoint cadence — the publish version stays monotonic by
  the existing crash-window jump;
- it watches the worker fleet from *inside* the serve loop (the
  ``on_tick`` hook — no second thread ever touches the native transport
  handles): a worker process that exited nonzero is respawned via
  ``spawn_worker`` with the same id (after ``reset_worker_slot`` on shm
  and after marking its crash fault fired so a deterministic fault plan
  cannot crash-loop the replacement);
- it stops when every worker has exited cleanly and the gradient queue
  has drained (``stop_when``), so drop/duplicate/corrupt faults — which
  make exact push counts unknowable — can never hang the job the way a
  fixed ``total_received`` would.

Fleet-level recovery counters are mirrored into the server's scrape
registry (they survive into ``/metrics`` text):
``ps_worker_respawns_total``, ``ps_server_restarts_total``,
``ps_worker_reconnects_total`` (workers seen pushing again after a
server restart — the client-side backoff/reconnect story observed from
the server side).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from pytorch_ps_mpi_tpu import telemetry
from pytorch_ps_mpi_tpu.resilience.faults import (
    FaultInjector,
    InjectedServerCrash,
)

PyTree = Any


class _WorkerRec:
    __slots__ = ("wid", "proc", "spawned_at", "respawns", "done",
                 "abandoned")

    def __init__(self, wid: int, proc, now: float):
        self.wid = wid
        self.proc = proc
        self.spawned_at = now
        self.respawns = 0
        self.done = False
        self.abandoned = False


class Supervisor:
    """Run one supervised async-PS job to completion.

    ``cfg`` is the shared job config (`make_problem` keys + transport /
    codec / resilience / fault keys). The supervisor copies it and
    maintains the ``fault_fired`` list across respawns/restarts.
    """

    def __init__(self, cfg: Dict[str, Any], n_workers: int, *,
                 shm_name: Optional[str] = None, port: int = 0,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 10,
                 sync_barrier: bool = False,
                 timeout: float = 300.0,
                 max_worker_respawns: int = 3,
                 max_server_restarts: int = 3,
                 straggler_timeout: float = 5.0):
        import os

        self.cfg = dict(cfg)
        self.cfg.setdefault("fault_fired", [])
        if self.cfg.get("resilient"):
            # resilient workers need SHORT op timeouts: a failover is
            # only detected when a push times out, and the retry loop —
            # not one long blocking call — supplies the patience
            self.cfg.setdefault("push_timeout", 10.0)
        self.n_workers = int(n_workers)
        self.transport = self.cfg.get("transport", "shm")
        self.shm_name = shm_name or f"/psq_sup_{os.getpid()}"
        self._port = int(port)  # pinned to the first bind once serving
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        self.sync_barrier = bool(sync_barrier)
        self.timeout = float(timeout)
        self.max_worker_respawns = int(max_worker_respawns)
        self.max_server_restarts = int(max_server_restarts)
        self.straggler_timeout = float(straggler_timeout)

        self.worker_respawns = 0
        self.server_restarts = 0
        self.worker_reconnects = 0
        self.phase_versions: List[int] = []
        self.final_prometheus_text: Optional[str] = None
        self._recs: Dict[int, _WorkerRec] = {}
        # after a server restart, each worker owes one observed reconnect
        self._reconnect_credit: set = set()
        # counters accumulated across server generations (a replacement
        # server starts at zero; the run's totals must not)
        self._frames_rejected_accum: Dict[int, int] = {}
        self._frames_rejected_accum_total = 0
        self._grads_received_accum = 0
        # recovery-time measurement (tick-granularity, ~0.2 s):
        # respawn = worker death handled → replacement's first consumed
        # frame; restart = server crash → replacement's first consumed
        # frame. The numbers RESULTS.md quotes from the chaos smoke.
        self.recovery_times: Dict[str, List[float]] = {
            "worker_respawn_s": [], "server_restart_s": [],
        }
        self._respawn_watch: Dict[int, float] = {}
        self._restart_watch: Optional[float] = None

    # -- server lifecycle -------------------------------------------------
    def _make_codec(self):
        if not self.cfg.get("codec"):
            return None
        from pytorch_ps_mpi_tpu.codecs import get_codec

        return get_codec(self.cfg["codec"], **self.cfg.get("codec_kw", {}))

    def _make_server(self, template: PyTree):
        kw = dict(
            num_workers=self.n_workers, template=template,
            max_staleness=int(self.cfg.get("max_staleness", 4)),
            code=self._make_codec(),
            bucket_mb=float(self.cfg.get("bucket_mb", 0.0)),
            frame=bool(self.cfg.get("frame_check")),
        )
        if self.transport == "tcp":
            from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSServer

            server = TcpPSServer(self._port, **kw)
            self._port = server.port  # pin: replacements bind the same port
        else:
            from pytorch_ps_mpi_tpu.parallel.dcn import ShmPSServer

            server = ShmPSServer(self.shm_name, **kw)
        reg = server.scrape_registry()
        reg.add_collector(
            lambda r, s=server: self._collect_recovery_metrics(r, s))
        return server

    def _collect_recovery_metrics(self, reg, server) -> None:
        reg.counter("ps_worker_respawns_total",
                    "dead worker processes respawned by the supervisor"
                    ).set(float(self.worker_respawns))
        reg.counter("ps_server_restarts_total",
                    "server crashes recovered by restart-from-checkpoint"
                    ).set(float(self.server_restarts))
        reg.counter("ps_worker_reconnects_total",
                    "workers observed pushing again after a server restart"
                    ).set(float(self.worker_reconnects))
        # registered AFTER the server's own collector, so these run
        # totals (prior server generations + the live one) win the
        # scrape. Per-worker labeled series only — an unlabeled sibling
        # under the same name would double PromQL sum() aggregations.
        rej_help = ("self-verifying frames rejected "
                    "(corruption / config drift / size mismatch)")
        live = getattr(server, "frames_rejected", {})
        for w in range(self.n_workers):
            total = (self._frames_rejected_accum.get(w, 0)
                     + int(live.get(w, 0)))
            reg.counter("ps_frames_rejected_total", rej_help,
                        labels={"worker": str(w)}).set(float(total))

    def _absorb_server_counts(self, server) -> None:
        """Fold a retiring server generation's counters into the run
        totals (called just before every ``server.close()``)."""
        for w, n in getattr(server, "frames_rejected", {}).items():
            self._frames_rejected_accum[w] = (
                self._frames_rejected_accum.get(w, 0) + int(n))
        self._frames_rejected_accum_total += int(
            getattr(server, "frames_rejected_total", 0))
        self._grads_received_accum += int(server.grads_received)

    def addr(self) -> str:
        """The address workers connect to — stable across restarts."""
        if self.transport == "tcp":
            return f"127.0.0.1:{self._port}"
        return self.shm_name

    # -- worker lifecycle -------------------------------------------------
    def _worker_cfg(self) -> Dict[str, Any]:
        cfg = dict(self.cfg)
        cfg["fault_fired"] = sorted(self.cfg["fault_fired"])
        return cfg

    def _spawn(self, wid: int) -> None:
        from pytorch_ps_mpi_tpu.parallel.async_train import spawn_worker

        proc = spawn_worker(self.addr(), wid, self._worker_cfg())
        now = time.time()
        if wid in self._recs:
            rec = self._recs[wid]
            rec.proc = proc
            rec.spawned_at = now
        else:
            self._recs[wid] = _WorkerRec(wid, proc, now)

    def _mark_crash_fault_fired(self, wid: int) -> None:
        """A respawned worker restarts at step 0: mark its earliest
        unfired crash fault fired so the deterministic plan cannot
        crash-loop the replacement."""
        fired = set(self.cfg["fault_fired"])
        crashes = sorted(
            (f for f in self.cfg.get("fault_plan", ())
             if f.get("kind") == "crash_worker"
             and int(f.get("worker", -1)) == wid),
            key=lambda f: int(f["at_step"]),
        )
        for i, f in enumerate(crashes):
            fid = int(f.get("id", self.cfg["fault_plan"].index(f)))
            if fid not in fired:
                fired.add(fid)
                break
        self.cfg["fault_fired"] = sorted(fired)

    def _tick(self, server) -> None:
        """Called from inside the serve loop (same thread as the native
        transport — never racing a pump): respawn dead workers, observe
        post-restart reconnects."""
        # per-worker respawn counts, stashed on the server for the
        # control plane: a respawn-looping worker is churn the
        # controller's evict rule should see even when the worker's own
        # beacon counters died with it
        server._supervisor_respawns = {
            r.wid: r.respawns for r in self._recs.values() if r.respawns
        }
        for rec in self._recs.values():
            if rec.done or rec.abandoned:
                continue
            rc = rec.proc.poll()
            if rc is None:
                continue
            if rc == 0:
                rec.done = True
                self._reconnect_credit.discard(rec.wid)
                continue
            if rec.respawns >= self.max_worker_respawns:
                rec.abandoned = True
                telemetry.record_event("supervisor.worker_abandoned",
                                       worker=rec.wid, exit_code=rc)
                continue
            self._mark_crash_fault_fired(rec.wid)
            if hasattr(server, "reset_worker_slot"):
                # shm: a worker killed inside its mailbox-write window
                # leaves the slot wedged; clear it for the replacement
                try:
                    server.reset_worker_slot(rec.wid)
                except Exception:
                    pass  # slot already clean / segment replaced
            rec.respawns += 1
            self.worker_respawns += 1
            self._reconnect_credit.discard(rec.wid)
            telemetry.record_event("supervisor.worker_respawn",
                                   worker=rec.wid, exit_code=rc,
                                   respawns=rec.respawns)
            self._spawn(rec.wid)
            self._respawn_watch[rec.wid] = time.time()
        for wid, t0 in list(self._respawn_watch.items()):
            seen = server.last_seen.get(wid, 0.0)
            if seen > t0:  # the replacement's first frame landed
                self.recovery_times["worker_respawn_s"].append(seen - t0)
                del self._respawn_watch[wid]
        if self._restart_watch is not None and server.grads_received > 0:
            self.recovery_times["server_restart_s"].append(
                time.time() - self._restart_watch)
            self._restart_watch = None
        if self._reconnect_credit:
            # a worker is "reconnected" once the restarted server has
            # consumed something from it (transport-agnostic signal)
            for wid in sorted(self._reconnect_credit):
                if wid in server.last_seen:
                    self._reconnect_credit.discard(wid)
                    self.worker_reconnects += 1
                    telemetry.record_event("supervisor.worker_reconnected",
                                           worker=wid)

    def _workers_done(self) -> bool:
        return all(r.done or r.abandoned for r in self._recs.values())

    # -- the supervised run ----------------------------------------------
    def run(self) -> Tuple[PyTree, Dict[str, Any]]:
        """Serve (and re-serve, across server crashes) until every worker
        finished; returns ``(params, metrics)`` where metrics is the last
        serve phase's dict plus the fleet-recovery totals."""
        import jax

        from pytorch_ps_mpi_tpu.parallel.async_train import (
            join_workers,
            make_problem,
            serve,
        )

        _, template, batch_fn, loss_fn = make_problem(self.cfg)
        deadline = time.time() + self.timeout
        resume = bool(self.cfg.get("resume"))
        # the RUN's initial loss: a server crash destroys phase 1's
        # metrics dict, so the end-to-end "training improved" claim needs
        # its own anchor (same held-out eval batch as serve's)
        run_loss_initial = None
        if not (resume and self._ckpt_exists()):
            run_loss_initial = float(
                jax.jit(loss_fn)(template, batch_fn(10**6, 10**6)))
        params, metrics = None, {}
        phases = 0
        try:
            while True:
                server = self._make_server(template)
                if not self._recs:  # first phase: launch the fleet
                    for wid in range(self.n_workers):
                        self._spawn(wid)
                try:
                    do_resume = resume and self._ckpt_exists()
                    params, metrics = serve(
                        server, self.cfg, total_grads=10**18,
                        sync_barrier=self.sync_barrier,
                        timeout=max(1.0, deadline - time.time()),
                        checkpoint_dir=self.checkpoint_dir,
                        checkpoint_every=self.checkpoint_every,
                        resume=do_resume,
                        on_tick=lambda: self._tick(server),
                        stop_when=self._workers_done,
                    )
                    phases += 1
                    self.phase_versions.append(int(server.version))
                    self.final_prometheus_text = server.prometheus_text()
                    break
                except (InjectedServerCrash, RuntimeError, OSError) as e:
                    # a server crash — injected (the fault kind) or real
                    # (native transport failure, checkpoint I/O error).
                    # Same recovery either way: restart from the cadence
                    # snapshot. Only injected crashes are fired-marked.
                    phases += 1
                    self.phase_versions.append(int(server.version))
                    fault_id = None
                    if isinstance(e, InjectedServerCrash):
                        fault_id = e.fault["id"]
                        fired = set(self.cfg["fault_fired"])
                        fired.add(fault_id)
                        self.cfg["fault_fired"] = sorted(fired)
                    self.server_restarts += 1
                    self._reconnect_credit = {
                        r.wid for r in self._recs.values()
                        if not (r.done or r.abandoned)
                    }
                    self._restart_watch = time.time()
                    resume = True
                    telemetry.record_event("supervisor.server_restart",
                                           fault_id=fault_id,
                                           error=str(e),
                                           restarts=self.server_restarts)
                    if self.server_restarts > self.max_server_restarts:
                        raise
                    if not self.checkpoint_dir:
                        raise RuntimeError(
                            "server crashed but no checkpoint_dir was "
                            "configured — cannot restart-from-checkpoint"
                        ) from e
                finally:
                    self._absorb_server_counts(server)
                    server.close()
                if time.time() > deadline:
                    raise TimeoutError(
                        "supervised run exceeded its deadline")
        except BaseException:
            # never leak the fleet on a failed run: terminate and reap
            # every worker before propagating (the success path joins
            # with the full remaining budget below)
            join_workers([r.proc for r in self._recs.values()],
                         timeout=5.0)
            raise

        exit_codes = join_workers(
            [r.proc for r in self._recs.values()],
            timeout=max(1.0, deadline - time.time()),
        )
        metrics = dict(metrics)
        metrics.update(
            worker_respawns=float(self.worker_respawns),
            server_restarts=float(self.server_restarts),
            worker_reconnects=float(self.worker_reconnects),
            workers_abandoned=float(
                sum(1 for r in self._recs.values() if r.abandoned)),
            supervised_phases=float(phases),
            worker_exit_codes=exit_codes,
            versions_monotonic=all(
                b > a for a, b in zip(self.phase_versions,
                                      self.phase_versions[1:])
            ),
            # run totals across every server generation (a replacement
            # server's own counters start at zero)
            frames_rejected=float(self._frames_rejected_accum_total),
            frames_rejected_by_worker=dict(self._frames_rejected_accum),
            grads_received_all_phases=float(self._grads_received_accum),
            recovery_times={k: [round(v, 3) for v in vs]
                            for k, vs in self.recovery_times.items()},
        )
        if run_loss_initial is not None:
            metrics["run_loss_initial"] = run_loss_initial
        return params, metrics

    def _ckpt_exists(self) -> bool:
        if not self.checkpoint_dir:
            return False
        from pytorch_ps_mpi_tpu.utils.checkpoint import CheckpointManager

        try:
            return CheckpointManager(
                self.checkpoint_dir).latest_step() is not None
        except Exception:
            return False
