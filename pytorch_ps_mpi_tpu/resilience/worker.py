"""ResilientWorker: retry/backoff + auto-reconnect around a PS worker.

Today's failure mode: ``worker_main`` raises on the first
``TimeoutError`` from ``read_params``/``push_grad`` and on any transport
``RuntimeError`` (socket EOF, wedged shm mailbox), so a server restart
kills every worker even though the replacement serves the same snapshot
seconds later. This wrapper keeps the worker's surface
(``read_params`` / ``push_grad`` / ``close``) while absorbing those
failures:

- **timeouts** → exponential backoff with deterministic jitter
  (seeded per worker — two workers never thundering-herd in lockstep,
  and a test replay sleeps the same schedule), then a reconnect after
  ``reconnect_after`` consecutive timeouts. The shm orphan case needs
  this: a restarted shm server *recreates* the segment, so a surviving
  worker's pushes land in an orphaned mailbox and time out — the
  reconnect re-opens the name and finds the live segment.
- **transport errors** (``RuntimeError``/``OSError``/``ConnectionError``
  — TCP EOF, protocol desync) → immediate reconnect via the factory,
  which itself retries with backoff while the replacement server comes
  up.

At most one in-flight gradient is lost per failover (the push the old
server acknowledged but never applied, or the one written into an
orphaned mailbox) — exactly the loss the async protocol already
tolerates from a stale drop.

Counters (``retries``, ``reconnects``) are exposed for tests and pushed
into the flight recorder as ``resilience.retry`` / ``resilience.reconnect``
events, so worker JSONLs tell the recovery story per process; the
supervisor mirrors fleet-level reconnects into ``/metrics``.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional

from pytorch_ps_mpi_tpu import telemetry

PyTree = Any


class ResilientWorker:
    """Wrap a transport worker factory with retry, backoff and reconnect.

    ``factory`` builds a fresh ``ShmPSWorker``/``TcpPSWorker`` (or
    anything with the same surface); it may raise ``TimeoutError`` while
    the server is down — construction itself is retried with backoff.
    """

    def __init__(self, factory: Callable[[], Any], worker_id: int = 0, *,
                 max_retries: int = 12, backoff_base: float = 0.05,
                 backoff_max: float = 2.0, jitter: float = 0.5,
                 reconnect_after: int = 1, seed: int = 0):
        if max_retries < 1:
            raise ValueError(f"max_retries must be >= 1, got {max_retries}")
        self._factory = factory
        self.worker_id = worker_id
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.reconnect_after = int(reconnect_after)
        # deterministic jitter stream: (seed, worker) → same backoff
        # schedule on every replay of a chaos scenario
        self._rng = random.Random((int(seed) << 16) ^ (worker_id + 1))
        self.retries = 0
        self.reconnects = 0
        # fallback push seq for the lineage trace ID, owned HERE so it
        # survives reconnects (a factory-built replacement transport
        # restarts its own counter at 0, which would reuse trace IDs
        # the server already consumed)
        self._auto_seq = 0
        self._tamper = None
        # the last applied wire renegotiation (controller epoch bump) —
        # re-applied after every reconnect, because the factory builds
        # the BOOT wire and a replacement pushing the boot fingerprint
        # would be config-rejected once the old epoch retires
        self._renegotiated: Optional[tuple] = None
        self._w: Optional[Any] = None
        self._w = self._build(initial=True)

    # -- plumbing ---------------------------------------------------------
    @property
    def inner(self):
        """The current transport worker (changes across reconnects)."""
        return self._w

    @property
    def wire(self):
        return getattr(self._w, "wire", None)

    def set_tamper(self, fn) -> None:
        """One-shot outgoing-frame hook (fault injection); survives a
        reconnect so a corrupt fault is never silently skipped by a
        concurrent failover."""
        self._tamper = fn
        if self._w is not None:
            self._w._tamper = fn

    def set_wire_delay(self, delay_s: float) -> None:
        """One-shot post-seal push delay (fault kind ``wire_delay``):
        forwarded to the current transport — the sleep runs between the
        frame's ``send_wall`` stamp and the bytes traveling, so the
        lineage wire stage measures it."""
        if self._w is not None:
            self._w._wire_delay_s = float(delay_s)

    def _backoff(self, attempt: int) -> None:
        d = min(self.backoff_max, self.backoff_base * (2.0 ** attempt))
        d *= 1.0 + self.jitter * self._rng.random()
        time.sleep(d)

    def _build(self, initial: bool = False):
        """Construct a transport worker, retrying while the server is
        unreachable. Counts a reconnect (and emits the event) for every
        non-initial rebuild."""
        last: Optional[BaseException] = None
        for attempt in range(self.max_retries):
            try:
                w = self._factory()
                if not initial:
                    self.reconnects += 1
                    telemetry.record_event(
                        "resilience.reconnect", worker=self.worker_id,
                        attempt=attempt, reconnects=self.reconnects,
                    )
                w._tamper = self._tamper
                if self._renegotiated is not None:
                    code, bucket_mb = self._renegotiated
                    reneg = getattr(w, "renegotiate", None)
                    if reneg is not None:
                        reneg(code, bucket_mb=bucket_mb)
                return w
            except (TimeoutError, RuntimeError, OSError) as e:
                last = e
                self.retries += 1
                telemetry.record_event(
                    "resilience.retry", worker=self.worker_id,
                    op="connect", attempt=attempt, error=str(e),
                )
                self._backoff(attempt)
        raise TimeoutError(
            f"worker {self.worker_id}: could not (re)connect after "
            f"{self.max_retries} attempts"
        ) from last

    def _reconnect(self) -> None:
        if self._w is not None:
            try:
                self._w.close()
            except Exception:
                pass  # a dead transport may fail its own teardown
            self._w = None
        self._w = self._build()

    def _call(self, op: str, *args, **kw):
        timeouts_in_a_row = 0
        last: Optional[BaseException] = None
        for attempt in range(self.max_retries):
            try:
                return getattr(self._w, op)(*args, **kw)
            except TimeoutError as e:
                last = e
                self.retries += 1
                timeouts_in_a_row += 1
                telemetry.record_event(
                    "resilience.retry", worker=self.worker_id, op=op,
                    attempt=attempt, error=str(e),
                )
                if timeouts_in_a_row >= self.reconnect_after:
                    # repeated timeouts on a live handle smell like an
                    # orphaned segment / dead peer: re-resolve the server
                    self._reconnect()
                    timeouts_in_a_row = 0
                else:
                    self._backoff(attempt)
            except (RuntimeError, OSError, ConnectionError) as e:
                # transport-level failure (EOF, reset, wedged slot):
                # the handle is unusable, rebuild it
                last = e
                self.retries += 1
                telemetry.record_event(
                    "resilience.retry", worker=self.worker_id, op=op,
                    attempt=attempt, error=str(e),
                )
                self._reconnect()
        raise TimeoutError(
            f"worker {self.worker_id}: {op} failed after "
            f"{self.max_retries} attempts: {last}"
        ) from last

    # -- worker surface ---------------------------------------------------
    def read_params(self, timeout: float = 30.0):
        return self._call("read_params", timeout=timeout)

    def push_grad(self, grad: PyTree, version: int,
                  timeout: float = 30.0, lineage=None) -> None:
        # the trace ID is pinned BEFORE the retry loop: a retransmitted
        # frame is the SAME push, so every retry (and any reconnect in
        # between) re-seals with the same (step, seq) — without this,
        # the inner transport's per-connection auto-seq would mint a
        # fresh id per retry and restart at 0 after a reconnect
        if lineage is None:
            lineage = (0, self._auto_seq)
            self._auto_seq += 1
        out = self._call("push_grad", grad, version, timeout=timeout,
                         lineage=lineage)
        # the transport consumed any one-shot tamper with the push
        self._tamper = getattr(self._w, "_tamper", None)
        return out

    def renegotiate(self, code, bucket_mb: float = 0.0) -> bool:
        """Forward a wire renegotiation to the inner transport and
        remember it, so every later reconnect rebuilds onto the CURRENT
        epoch instead of the factory's boot wire."""
        reneg = getattr(self._w, "renegotiate", None)
        if reneg is None:
            return False
        ok = bool(reneg(code, bucket_mb=bucket_mb))
        if ok:
            self._renegotiated = (code, float(bucket_mb))
        return ok

    def close(self) -> None:
        if self._w is not None:
            self._w.close()
            self._w = None
