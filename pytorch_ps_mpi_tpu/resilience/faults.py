"""Seeded, deterministic fault injection for the async PS stack.

A **fault plan** is a JSON-able list of entries::

    {"at_step": 5, "worker": 1, "kind": "crash_worker"}
    {"at_step": 3, "worker": 0, "kind": "corrupt"}
    {"at_step": 20, "worker": "server", "kind": "crash_server"}

- ``worker`` is a worker id (matched against the worker loop's local
  step counter) or ``"server"`` (matched against the serve loop's
  global applied-gradient count, so a resumed server never re-fires
  faults behind its restored ``applied_total``).
- ``kind`` is one of :data:`FAULT_KINDS`:

  ===============  ========================================================
  ``drop``         the worker computes but skips the push (one lost grad)
  ``delay``        sleep ``delay_ms`` (default 100) before the push
  ``wire_delay``   sleep ``delay_ms`` (default 100) INSIDE the push,
                   after the frame is sealed (its ``send_wall`` stamp is
                   taken) and before the bytes travel — emulated wire
                   latency the lineage/anatomy layers must attribute to
                   the WIRE stage, where ``delay`` lands in produce
                   (``tools/whatif_smoke.py``'s injected bottleneck)
  ``duplicate``    push the same gradient twice with the same version tag
  ``corrupt``      XOR-flip ``corrupt_bytes`` (default 8) payload bytes —
                   deterministic positions from (seed, fault id); detected
                   and rejected when frame checking is on
  ``nan``          poison the step's gradient with NaNs BEFORE encode —
                   frames stay wire-valid (CRC passes); detection is the
                   numerics layer's job (``telemetry.numerics``
                   quarantine), which this fault exists to exercise
  ``crash_worker`` ``os._exit`` mid-step (skips every ``finally:`` — the
                   closest a test can get to SIGKILL from inside)
  ``crash_server`` raise :class:`InjectedServerCrash` out of the serve
                   loop after the matching applied update
  ``slow_leader``  per-push fold delay at ONE tree leader (worker
                   ``"leader<g>"``, matched against the leader's round
                   counter): every payload folded from ``at_step`` on
                   costs an extra ``slow_ms`` (default 20) inside the
                   fold window, so the slowdown lands in the hop row's
                   ``fold_s`` and the anatomy advisor attributes it to
                   the ``leader_fold`` stage — the injection vector the
                   structural controller's group split heals (half the
                   members → half the per-push fold work)
  ``reader_storm`` burst open-loop read load at one serving endpoint
                   (worker ``"reader<j>"``, matched against the storm
                   driver's burst counter): the driver issues
                   ``storm_reads`` (default 200) extra reads in a burst
                   — the shed-pressure vector the elastic read tier
                   absorbs by scaling replicas out. Client-side by
                   construction: the injector only *decides*; the
                   driver (``tools/topo_smoke.py``) issues the reads.
  ===============  ========================================================

  Role-addressed kinds (``slow_leader``/``reader_storm``) target string
  workers — ``"leader<g>"`` / ``"reader<j>"`` — which
  :func:`normalize_plan` keeps verbatim (like ``"server"``) instead of
  coercing to a worker id.

Determinism is the contract: the plan is explicit (no sampled fault
times), the only randomness — corrupt byte positions — derives from
``(seed, fault id)``, and every fired fault appends one stable event row
``{id, kind, worker, at_step}`` to :attr:`FaultInjector.events` (plus a
JSONL fault log when ``cfg["fault_log_dir"]`` is set, written *before*
a crash kind takes the process down). Two runs with the same plan and
seed therefore produce identical injected-event logs — the property
``tests/test_resilience.py`` and ``tools/chaos_smoke.py`` assert. The
log files APPEND (a respawned worker must extend its generation-0 rows,
not clobber them), so one RUN is delimited by a fresh ``fault_log_dir``
— use a new directory per run, or clear ``faults-*.jsonl`` at run start
the way ``examples/train_async.py`` does.

Crash faults and respawns: a respawned worker restarts its step counter
at 0 and would re-fire its own crash fault forever. The supervisor marks
fired crash faults in ``cfg["fault_fired"]`` (a list of fault ids) when
it respawns/restarts, and :meth:`FaultInjector.from_cfg` excludes them —
non-crash faults intentionally re-fire on replay so both runs of a
deterministic pair see the same sequence.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

FAULT_KINDS = ("drop", "delay", "wire_delay", "duplicate", "corrupt",
               "nan", "crash_worker", "crash_server",
               "slow_leader", "reader_storm")

#: Exit code of an injected worker crash (``os._exit``) — distinguishable
#: from a clean exit (0) and from real crashes in logs, treated like any
#: other death by the supervisor.
CRASH_EXIT_CODE = 97


class InjectedServerCrash(RuntimeError):
    """Raised out of the serve loop by a ``crash_server`` fault; carries
    the fault entry so a supervisor can mark it fired before restarting
    the server from its checkpoint."""

    def __init__(self, fault: Dict[str, Any]):
        super().__init__(
            f"injected server crash (fault id={fault['id']} "
            f"at applied={fault['at_step']})"
        )
        self.fault = fault


def normalize_plan(plan: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Validate and normalize a fault plan: assigns each entry a stable
    ``id`` (its index) used for fired-marking and corrupt-RNG seeding."""
    out = []
    for i, f in enumerate(plan):
        kind = f.get("kind")
        if kind not in FAULT_KINDS:
            raise ValueError(f"fault {i}: unknown kind {kind!r} "
                             f"(one of {FAULT_KINDS})")
        worker = f.get("worker", "server" if kind == "crash_server" else None)
        if worker is None:
            raise ValueError(f"fault {i}: missing worker")
        if kind == "crash_server" and worker != "server":
            raise ValueError(f"fault {i}: crash_server must target 'server'")
        if kind == "slow_leader" and not (
                isinstance(worker, str) and worker.startswith("leader")):
            raise ValueError(f"fault {i}: slow_leader must target a "
                             f"'leader<g>' role, got {worker!r}")
        if kind == "reader_storm" and not (
                isinstance(worker, str) and worker.startswith("reader")):
            raise ValueError(f"fault {i}: reader_storm must target a "
                             f"'reader<j>' role, got {worker!r}")
        entry = dict(f)
        entry["id"] = int(f.get("id", i))
        entry["at_step"] = int(f["at_step"])
        # role-addressed workers ("server", "leader<g>", "reader<j>")
        # stay verbatim strings; everything else is a worker id
        if isinstance(worker, str) and not worker.lstrip("-").isdigit():
            entry["worker"] = worker
        else:
            entry["worker"] = int(worker)
        entry["kind"] = kind
        out.append(entry)
    if len({f["id"] for f in out}) != len(out):
        raise ValueError("fault plan ids must be unique")
    return out


class FaultInjector:
    """Consults a normalized fault plan for one role (a worker id or
    ``"server"``), fires matching faults, and logs every injection."""

    def __init__(self, plan: Sequence[Dict[str, Any]], seed: int = 0,
                 role: Union[int, str] = "server",
                 fired: Iterable[int] = (),
                 log_path: Optional[str] = None):
        self.plan = normalize_plan(plan)
        self.seed = int(seed)
        self.role = role
        self.fired = set(int(i) for i in fired)
        self.log_path = log_path
        self.events: List[Dict[str, Any]] = []
        self._mine = [f for f in self.plan if f["worker"] == role]

    @classmethod
    def from_cfg(cls, cfg: Dict[str, Any],
                 role: Union[int, str] = "server") -> Optional["FaultInjector"]:
        """Build from the shared job config (``fault_plan``,
        ``fault_seed``, ``fault_fired``, ``fault_log_dir`` keys) — the
        same dict that rides every worker spawn's argv, so one plan arms
        the whole fleet. Returns None when no plan is configured."""
        plan = cfg.get("fault_plan")
        if not plan:
            return None
        log_path = None
        if cfg.get("fault_log_dir"):
            os.makedirs(cfg["fault_log_dir"], exist_ok=True)
            log_path = os.path.join(cfg["fault_log_dir"],
                                    f"faults-{role}.jsonl")
        return cls(plan, seed=int(cfg.get("fault_seed", 0)), role=role,
                   fired=cfg.get("fault_fired") or (), log_path=log_path)

    def faults_at(self, step: int) -> List[Dict[str, Any]]:
        """Unfired faults for this role scheduled exactly at ``step``."""
        return [f for f in self._mine
                if f["at_step"] == step and f["id"] not in self.fired]

    def faults_between(self, lo: int, hi: int) -> List[Dict[str, Any]]:
        """Unfired faults with ``lo < at_step <= hi`` — the serve loop's
        form, where a sync-barrier round advances the applied count by
        several at once."""
        return [f for f in self._mine
                if lo < f["at_step"] <= hi and f["id"] not in self.fired]

    def fire(self, fault: Dict[str, Any]) -> Dict[str, Any]:
        """Mark ``fault`` fired and log it. The event row carries only
        deterministic fields (id/kind/worker/at_step) so event logs can
        be compared across runs; it is appended to the in-memory list,
        the fault log file (flushed immediately — crash kinds never get
        a second chance), and the flight recorder when armed."""
        self.fired.add(fault["id"])
        event = {"id": fault["id"], "kind": fault["kind"],
                 "worker": fault["worker"], "at_step": fault["at_step"]}
        self.events.append(event)
        if self.log_path:
            with open(self.log_path, "a") as f:
                f.write(json.dumps(event, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
        from pytorch_ps_mpi_tpu import telemetry

        telemetry.record_event("fault.injected", **event)
        return event

    def corrupt(self, fault: Dict[str, Any], buf: np.ndarray) -> None:
        """XOR-flip ``corrupt_bytes`` positions of ``buf`` in place.
        Positions derive from (seed, fault id) only — the same fault
        corrupts the same offsets in every run."""
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + 7919 * (fault["id"] + 1)) % 2**32
        )
        n = max(1, int(fault.get("corrupt_bytes", 8)))
        idx = rng.randint(0, buf.nbytes, size=n)
        buf[idx] ^= 0xFF

    def make_tamper(self, fault: Dict[str, Any]):
        """One-shot outgoing-frame tamper hook for the transport workers'
        ``_tamper`` slot: fires the fault and corrupts the wire bytes of
        the next push."""

        def tamper(buf: np.ndarray) -> None:
            self.fire(fault)
            self.corrupt(fault, buf)

        return tamper


def load_fault_log(path: str) -> List[Dict[str, Any]]:
    """Read one fault-log JSONL back as a list of event rows (missing
    file = no faults fired by that role = empty list)."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
