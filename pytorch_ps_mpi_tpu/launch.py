"""Multi-host launcher: ``python -m pytorch_ps_mpi_tpu.launch script.py``.

The SPMD bootstrap the reference got from ``mpirun -n 2`` (reference
``Makefile:2-3``): every host runs the same script; this module wires
``jax.distributed.initialize`` from flags/env before handing control to
the user script, so rank topology is explicit instead of ambient
(reference ``mpi_comms.py:11-13``).

On TPU pods the runtime usually autodetects everything and a bare
``python script.py`` per host suffices; flags are for CPU/GPU clusters or
explicit control:

  python -m pytorch_ps_mpi_tpu.launch \
      --coordinator host0:1234 --num-processes 2 --process-id 0 train.py
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coordinator", default=os.environ.get("PS_COORDINATOR"),
                    help="host:port of process 0")
    ap.add_argument("--num-processes", type=int,
                    default=int(os.environ.get("PS_NUM_PROCESSES", "0")) or None)
    ap.add_argument("--process-id", type=int,
                    default=int(os.environ.get("PS_PROCESS_ID", "-1")))
    ap.add_argument("--platform", default=os.environ.get("PS_PLATFORM"),
                    help="pin the JAX platform (e.g. 'cpu') before "
                         "distributed init — needed on hosts whose "
                         "accelerator plugin ignores JAX_PLATFORMS")
    ap.add_argument("script", help="user training script (runs as __main__)")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    if args.coordinator is None and (
        args.num_processes is not None or args.process_id >= 0
    ):
        ap.error(
            "--num-processes/--process-id given without --coordinator "
            "(or PS_COORDINATOR): the job would silently run single-process"
        )

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from pytorch_ps_mpi_tpu.mesh import initialize_distributed

    initialize_distributed(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id if args.process_id >= 0 else None,
    )
    sys.argv = [args.script] + args.script_args
    # match `python script.py` semantics: the script's directory is
    # importable (runpy.run_path does not add it itself)
    sys.path.insert(0, os.path.dirname(os.path.abspath(args.script)))
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
