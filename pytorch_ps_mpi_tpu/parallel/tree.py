"""Hierarchical multi-hop aggregation: the PS is a tree, not a star.

The star topology (every worker pushes to one PS) was the last
flat-scaling bottleneck: root ingest bytes/sec grow linearly with worker
count even though per-push fold cost is flat in model size. This module
builds the DynamiQ-shaped fix (PAPERS.md): workers are partitioned into
**groups**, each with a **leader** process that

1. runs a :class:`~pytorch_ps_mpi_tpu.parallel.dcn.WireAggregator` over
   its group's compressed payloads — folded straight from the framed
   wire's validated payload bytes, so a per-push decode NEVER happens
   mid-tree (the leader's ``decodes_done`` stays 0);
2. finalizes ONCE per group round and **re-encodes** the aggregate for
   the upstream hop behind per-hop error feedback
   (:class:`~pytorch_ps_mpi_tpu.codecs.error_feedback.HopErrorFeedback`)
   so fidelity is bounded per hop and composes additively across hops;
3. pushes ONE frame upstream to the root PS, carrying the constituent
   worker trace IDs in the frame's composed-lineage trailer
   (``resilience.frames``) so every worker push is accounted at the
   root's published-version composition.

Topology emulation maps onto the transports: the leaf hop (worker →
leader) is the cheap intra-pod link — shm, or TCP with
``TPS_WAN_RTT_MS`` unset — and defaults to the **identity** group codec,
i.e. an exact local reduce (the multi-process stand-in for an ICI-level
``psum``); the leader → root hop is the compressed DCN link, paying the
WAN emulation's RTT where configured so the DCN tax is real in CI.

Weighting is exact by construction: leaders push group **sums** and the
root divides each round by the TOTAL composed worker-push count read
from the trailers, so degraded groups, ragged group sizes and
direct-to-root fallback pushes (leader crash) all weight correctly
without any coordination.

Resilience: a leader crash makes its group's
:class:`TreeWorkerConn` fall back to pushing **directly to the root**
(compressed, composing themselves in the trailer); the
:func:`run_tree` supervisor respawns the leader on its pinned port and
the group rejoins on its next probe. Root-side, the membership-dynamic
barrier in ``async_train.serve`` (``cfg["tree"]``) absorbs both
transitions.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

PyTree = Any

#: leader-loop tuning knobs and their defaults (``cfg["leader_kw"]``)
LEADER_KNOBS: Dict[str, Any] = {
    "group_transport": "tcp",  # leaf-hop wire: "tcp" | "shm"
    "group_codec": "identity",  # leaf-hop codec (exact local reduce)
    "group_codec_kw": {},       # its constructor kwargs
    "read_poll_s": 0.02,        # upstream snapshot poll cadence
    "degrade_after": 3.0,       # round wait before excluding dead members
    "flush_after": 6.0,         # round wait before a partial fold
    "startup_grace": 120.0,     # member startup window before idle-exit
    "idle_exit_s": 3.0,         # quiet time (members gone) before exit
    "timeout": 600.0,           # absolute leader lifetime bound
    "rejoin_every": 8,          # fallback pushes between leader probes
    "probe_timeout": 1.0,       # leader-probe connect timeout (fallback)
    "crash_at_round": None,     # TEST hook: os._exit before this round
    "max_respawns": 3,          # run_tree: leader respawn budget
}


def group_plan(n_workers: int, group_size: int) -> List[List[int]]:
    """Partition worker ids 0..n-1 into contiguous groups of
    ``group_size`` (the last group takes the remainder; a remainder of
    one still forms a group — its leader is a relay, which keeps the
    root's expected-pusher set uniform)."""
    if group_size < 1:
        raise ValueError(f"group_size must be >= 1, got {group_size}")
    return [list(range(i, min(i + group_size, n_workers)))
            for i in range(0, n_workers, group_size)]


def leader_wid(n_workers: int, group_id: int) -> int:
    """The worker id a group's leader pushes upstream under: leaders
    occupy ids ``n_workers .. n_workers+n_groups-1`` at the root, so
    leaf ids stay free for direct-to-root fallback pushes."""
    return int(n_workers) + int(group_id)


def tree_slot_capacity(n_workers: int, group_size: int) -> int:
    """The composed-lineage trailer capacity every push to the root
    carries: the largest group's size (one trace entry per composed
    worker push; a direct fallback push uses one slot)."""
    return min(int(group_size), int(n_workers))


class _HopLog:
    """Buffered JSONL writer for ``lineage-leader<g>.jsonl`` — the
    leader's half of cross-hop lineage: one ``leader_consume`` row per
    group push it ingests, one ``hop`` row per upstream push (with the
    composed trace IDs and the per-stage hop latency breakdown
    ``tools/telemetry_report.py`` tabulates)."""

    def __init__(self, dir: Optional[str], group_id: int,
                 flush_every: int = 32):
        self._f = None
        self._pending = 0
        self.flush_every = int(flush_every)
        if dir:
            os.makedirs(dir, exist_ok=True)
            self._f = open(
                os.path.join(dir, f"lineage-leader{group_id}.jsonl"), "a")

    def row(self, doc: Dict[str, Any]) -> None:
        if self._f is None:
            return
        self._f.write(json.dumps(doc) + "\n")
        self._pending += 1
        if self._pending >= self.flush_every:
            self.flush()

    def flush(self) -> None:
        if self._f is not None and self._pending:
            self._f.flush()
            self._pending = 0

    def close(self) -> None:
        if self._f is not None:
            self.flush()
            self._f.close()
            self._f = None


def _leader_knobs(cfg: Dict[str, Any]) -> Dict[str, Any]:
    kw = dict(LEADER_KNOBS)
    kw.update(cfg.get("leader_kw") or {})
    return kw


def _upstream_codec(cfg: Dict[str, Any]):
    if not cfg.get("codec"):
        return None
    from pytorch_ps_mpi_tpu.codecs import get_codec

    return get_codec(cfg["codec"], **(cfg.get("codec_kw") or {}))


def _group_codec(kw: Dict[str, Any]):
    from pytorch_ps_mpi_tpu.codecs import get_codec

    return get_codec(kw["group_codec"], **(kw.get("group_codec_kw") or {}))


# ---------------------------------------------------------------------------
# the leader process
# ---------------------------------------------------------------------------

def leader_main(upstream: Sequence[str], group_id: int,
                group: Sequence[int], cfg: Dict[str, Any],
                port: int = 0) -> int:
    """One leader process body: group-facing PS server (compressed
    ingest, zero per-push decodes), upstream-facing worker connection(s)
    (one per root shard — path-sharding composes with key-sharding),
    and the fold → EF re-encode → one-frame-upstream hop between them.
    Returns the number of upstream pushes. ``port`` pins the group
    server's port so a supervisor respawn is rejoinable."""
    from pytorch_ps_mpi_tpu.codecs.error_feedback import HopErrorFeedback
    from pytorch_ps_mpi_tpu.parallel.async_train import make_problem
    from pytorch_ps_mpi_tpu.parallel.dcn import (
        ShmPSServer,
        _flat_size,
        _flatten,
        _unflatten,
    )
    from pytorch_ps_mpi_tpu.parallel.sharded import (
        _slice_template,
        shard_plan,
    )
    from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSServer, TcpPSWorker

    kw = _leader_knobs(cfg)
    group = [int(w) for w in group]
    n_workers = int(cfg["n_workers"])
    slots = int(cfg.get("tree_slots")
                or tree_slot_capacity(n_workers, len(group)))
    _, params0, _, _ = make_problem(cfg)
    lid = leader_wid(n_workers, group_id)

    # -- group-facing server: the leaf hop's compressed ingest ------------
    gcode = _group_codec(kw)
    shm_name = f"/psq_tree_{os.getppid()}_{group_id}"
    if kw["group_transport"] == "shm":
        server = ShmPSServer(shm_name, num_workers=n_workers,
                             template=params0,
                             max_staleness=int(cfg.get("max_staleness", 4)),
                             code=gcode, frame=True)
        addr = f"shm:{shm_name}"
    else:
        server = TcpPSServer(int(port), num_workers=n_workers,
                             template=params0,
                             max_staleness=int(cfg.get("max_staleness", 4)),
                             code=gcode, frame=True)
        addr = f"127.0.0.1:{server.port}"
    gwire = server.wire
    if not gwire.agg_supported:
        raise ValueError(
            f"group codec {kw['group_codec']!r} has no compressed-domain "
            "aggregation algebra — a leader would have to decode per "
            "push, which the tree forbids")

    # -- upstream-facing connections: the DCN hop --------------------------
    ucode = _upstream_codec(cfg)
    sharded = len(upstream) > 1
    flat_n = _flat_size(params0)
    plan = shard_plan(flat_n, len(upstream)) if sharded else [(0, flat_n)]
    conns: List[Any] = []
    hops: List[HopErrorFeedback] = []
    for (start, stop), a in zip(plan, upstream):
        host, p = a.rsplit(":", 1)
        tmpl = _slice_template(stop - start) if sharded else params0
        c = TcpPSWorker(host, int(p), lid, tmpl,
                        code=(_upstream_codec(cfg) if sharded else ucode),
                        timeout=float(cfg.get("open_timeout", 60.0)),
                        bucket_mb=(0.0 if sharded
                                   else float(cfg.get("bucket_mb", 0.0))),
                        frame=True, tree_slots=slots)
        conns.append(c)
        if c.wire is None:
            raise ValueError("the tree's upstream hop needs a codec wire "
                             "(cfg['codec']) — set codec='identity' for "
                             "an uncompressed DCN hop")
        hops.append(HopErrorFeedback(c.wire,
                                     enabled=bool(cfg.get("hop_ef", True))))

    # -- observability: /metrics + /fleet card (role "leader") ------------
    ocfg = dict(cfg)
    ocfg["fleet_role"] = "leader"
    ocfg.pop("fleet_name", None)
    ocfg["fleet_meta"] = {"group": int(group_id), "members": group}
    if ((ocfg.get("fleet_dir") or ocfg.get("metrics_port") is not None
         or ocfg.get("health_port") is not None)
            and getattr(server, "_metrics_http", None) is None):
        http_port = server.start_metrics_http(0)
    else:
        http_port = None
    server.arm_observability(ocfg, name=f"leader{group_id}")
    reg = server.scrape_registry()
    # hop anatomy (cfg["hop_anatomy"]): arm_observability attached the
    # profiler; arm the bounded native interval rings behind its
    # timeline — per-frame validate stamps (tcpps) and per-fold-call
    # spans (wirecodec). Both are drop-and-count on overflow and both
    # arms are no-ops under PS_NO_NATIVE or the shm transport: the
    # timeline then falls back to the Python stage walls alone
    # (validate time stays inside ingest_wait).
    from pytorch_ps_mpi_tpu.utils import native as wc_native

    hop_an = getattr(server, "hop_anatomy", None)
    hop_stamps_on = hop_spans_on = False
    if hop_an is not None:
        ring_cap = int(hop_an.knobs["ring_capacity"])
        stamp_arm = getattr(server, "hop_stamps_arm", None)
        hop_stamps_on = (bool(stamp_arm(ring_cap))
                         if stamp_arm is not None else False)
        hop_spans_on = bool(wc_native.fold_spans_arm(ring_cap))
    state = {"upstream_pushes": 0, "partial_rounds": 0, "composed": 0}

    def _collect(r):
        r.counter("ps_tree_upstream_pushes_total",
                  "aggregate frames this leader pushed upstream").set(
                      float(state["upstream_pushes"]))
        r.counter("ps_tree_partial_rounds_total",
                  "group rounds folded over a partial membership").set(
                      float(state["partial_rounds"]))
        r.gauge("ps_tree_hop_rel_error",
                "last upstream re-encode's relative L2 error "
                "(before EF correction)").set(
                    max(h.last_rel_error for h in hops))
        r.gauge("ps_tree_ef_residual_norm",
                "per-hop error-feedback residual norm").set(
                    sum(h.residual_norm for h in hops))
        r.gauge("ps_tree_leader_decodes",
                "per-push ingest decodes at this leader — the tree's "
                "zero-decodes-mid-tree invariant says this stays 0 "
                "(the EF decode-back is not an ingest decode)").set(
                    float(server.decodes_done))

    reg.add_collector(_collect)

    log = _HopLog(cfg.get("lineage_dir") or cfg.get("telemetry_dir"),
                  group_id)
    # seeded fault injection, role-addressed: a "slow_leader" fault for
    # "leader<g>" arms a per-folded-payload delay from its at_step
    # round on — the structural controller's injected hot hop
    from pytorch_ps_mpi_tpu.resilience.faults import FaultInjector

    inj = FaultInjector.from_cfg(cfg, role=f"leader{group_id}")
    slow_fold_s = 0.0
    hello = {"leader": int(group_id), "addr": addr, "wid": lid}
    if http_port is not None:
        hello["health_port"] = http_port
    print(json.dumps(hello), flush=True)

    # -- the loop ----------------------------------------------------------
    import collections

    pending: Dict[int, Any] = collections.defaultdict(collections.deque)
    v_map: Dict[int, List[int]] = {}
    dead: set = set()
    #: members the topology document reassigned AWAY from this leader
    #: (structural split): they stop gating rounds IMMEDIATELY — no
    #: degrade_after stall — but anything they already pushed here
    #: stays queued and folds exactly (acked pushes are never dropped)
    departed: set = set()
    topo_state = {"seq": 0, "mtime": 0}
    topo_dir = (cfg.get("control_dir") or cfg.get("telemetry_dir")) \
        if cfg.get("topo_actions") else None
    crash_at = kw.get("crash_at_round")
    if isinstance(crash_at, dict):
        crash_at = crash_at.get(str(group_id), crash_at.get(int(group_id)))
    rounds = 0
    up_seq = 0
    t_start = time.monotonic()
    deadline = t_start + float(kw["timeout"])
    round_t0 = time.monotonic()
    last_activity = time.monotonic()
    next_read = 0.0
    next_tick = 0.0
    can_connect = hasattr(server, "connected")
    batch_poll = getattr(server, "poll_grad_batch", None)

    upstream_down = False

    def _read_upstream(timeout: float) -> Optional[Tuple[PyTree, List[int]]]:
        """Latest root snapshot (+ per-shard versions). Cached reads make
        an unchanged poll a header-sized round trip."""
        if not sharded:
            params, v = conns[0].read_params(timeout=timeout)
            return params, [int(v)]
        flat = np.empty(flat_n, np.float32)
        vs = []
        for (start, stop), c in zip(plan, conns):
            sl, v = c.read_params(timeout=timeout)
            flat[start:stop] = sl["flat"]
            vs.append(int(v))
        return _unflatten(flat, params0), vs

    def _republish(timeout: float = 2.0) -> None:
        nonlocal upstream_down
        try:
            got = _read_upstream(timeout)
        except TimeoutError:
            # upstream slow/stalled, not provably dead: skip this poll
            # (a blocked read here must never wedge the idle-exit path)
            return
        except (RuntimeError, OSError):
            # the upstream PS closed (job done, server gone): not this
            # leader's crash — drain out and exit cleanly below
            upstream_down = True
            return
        if got is None:
            return
        params, vs = got
        if v_map and v_map[max(v_map)] == vs:
            return  # upstream unchanged — nothing to republish
        server.publish(params)
        v_map[server.version] = vs
        while len(v_map) > 64:
            v_map.pop(min(v_map))

    def _map_versions(v_local: int) -> List[int]:
        if v_local in v_map:
            return v_map[v_local]
        return v_map[max(v_map)] if v_map else [0] * len(conns)

    def _consume(item, meta) -> None:
        nonlocal last_activity
        wid, v_local, payload = item
        if not gwire.payload_finite(payload):
            server._reject_frame(wid, "nonfinite")
            return
        pending[wid].append((np.copy(payload), dict(meta or {}),
                             _map_versions(int(v_local))))
        dead.discard(wid)
        last_activity = time.monotonic()

    def _pump_ingest() -> int:
        """Drain queued group pushes (batched when the native fast path
        is armed); returns the number of frames consumed. Each item's
        trace-ID meta is taken from the ALIGNED batch-meta list — the
        per-item ``last_push_meta`` would be overwritten inside one
        batch and silently drop trace IDs from the hop's composition."""
        if batch_poll is not None:
            batch = batch_poll(raw=True)
            if batch is not None:
                metas = getattr(server, "last_batch_metas", None) or []
                for it, meta in zip(batch, metas):
                    # raw views alias the batch buffer — copied (in
                    # _consume) before the next batched pop
                    _consume(it, meta)
                return len(batch)
        item = server.poll_grad(raw=True)
        if item is None:
            return 0
        _consume(item, server.last_push_meta)
        return 1

    def _mark_dead() -> None:
        silent = (None if can_connect
                  else server.stragglers(float(kw["degrade_after"])))
        for w in group:
            if w in dead or pending[w] or w not in server.last_seen:
                continue
            alive = (server.connected(w) if can_connect
                     else (w not in silent))
            if not alive:
                dead.add(w)

    def _hop_push(active: List[int]) -> None:
        """Fold one queued payload per listed worker, EF re-encode, push
        ONE frame upstream (per shard path), log the hop row."""
        nonlocal rounds, up_seq, round_t0, slow_fold_s
        if inj is not None and slow_fold_s == 0.0:
            # fires once (one deterministic event row); the delay then
            # persists — a sustained hotspot, not a one-round blip
            for f in inj.faults_between(-1, rounds):
                if f["kind"] == "slow_leader":
                    inj.fire(f)
                    slow_fold_s = float(f.get("slow_ms", 20.0)) / 1e3
        t_fold0 = time.monotonic()
        agg = gwire.agg_begin()
        entries: List[Dict[str, Any]] = []
        root_vs: List[List[int]] = []
        for w in active:
            payload, meta, vs = pending[w].popleft()
            agg.fold(payload)
            if slow_fold_s:
                # inside the fold window by design: the slowdown lands
                # in fold_s -> the anatomy advisor's leader_fold stage
                time.sleep(slow_fold_s)
            entries.append({"worker": int(meta.get("worker", w)),
                            "step": int(meta.get("step", 0)),
                            "seq": int(meta.get("seq", 0)),
                            "send_wall": float(meta.get("send_wall", 0.0))})
            root_vs.append(vs)
        t_fin0 = time.monotonic()
        summed = agg.finalize()
        t_fin1 = time.monotonic()
        fin_s = t_fin1 - t_fin0
        # fold_s keeps its historical meaning (fold loop + finalize) —
        # the hop row below and the offline round anatomy join on it;
        # the hop-anatomy row splits finalize into its own sub-stage
        fold_s = t_fin1 - t_fold0
        # conservative per-shard version tag: the OLDEST snapshot any
        # folded gradient was computed against — staleness is never
        # under-reported upstream
        v_up = [min(vs[i] for vs in root_vs) for i in range(len(conns))]
        t_enc0 = time.monotonic()
        if sharded:
            flat = _flatten(summed)
            payloads = [
                hop.encode({"flat": flat[start:stop]})
                for hop, (start, stop) in zip(hops, plan)
            ]
        else:
            payloads = [hops[0].encode(summed)]
        enc_s = time.monotonic() - t_enc0
        t_push0 = time.monotonic()
        nonlocal upstream_down
        pushed_shards = 0
        try:
            for c, p, v in zip(conns, payloads, v_up):
                c.push_payload(p, v,
                               timeout=float(cfg.get("push_timeout", 60.0)),
                               lineage=(rounds, up_seq), composed=entries)
                pushed_shards += 1
        except (TimeoutError, RuntimeError, OSError):
            upstream_down = True
            if pushed_shards == 0:
                # nothing reached any shard: the round's pushes are
                # positively lost — log them and drain out
                for e in entries:
                    log.row({"kind": "leader_consume", "lost": True,
                             "reason": "upstream_lost", **e})
            else:
                # PARTIAL shard coverage: earlier shards already
                # composed these entries, so a "lost" row here would
                # double-count them — record the partial round as its
                # own kind instead
                log.row({"kind": "hop_partial", "leader": int(group_id),
                         "round": rounds, "up_seq": up_seq,
                         "pushed_shards": pushed_shards,
                         "n_shards": len(conns), "composed": entries,
                         "t": time.time()})
            log.flush()
            return
        push_s = time.monotonic() - t_push0
        state["upstream_pushes"] += len(conns)
        state["composed"] += len(entries)
        if len(active) < len([w for w in group if w not in dead]) or dead:
            state["partial_rounds"] += 1
        log.row({
            "kind": "hop", "leader": int(group_id), "round": rounds,
            # the upstream-facing worker id this hop pushes as — the
            # root's composed push meta carries it, so offline round
            # anatomy can join hop rows to root rounds by EITHER the
            # wid or the composed trace IDs
            "leader_wid": int(lid),
            "up_seq": up_seq, "t": time.time(),
            "composed": entries, "versions": v_up,
            "fold_s": round(fold_s, 6), "encode_s": round(enc_s, 6),
            "push_s": round(push_s, 6),
            **hops[0].probe(),
        })
        log.flush()
        if hop_an is not None:
            # the hop-anatomy round: drain the native rings (owned by
            # THIS thread — the same one that pumps the transport and
            # runs the folds), attribute the round window to sub-stages
            # and feed the streaming-headroom scoreboard. The window
            # opens at the previous round's push end (round_t0).
            t_done = time.monotonic()
            validate_s = 0.0
            ring_drops = 0
            if hop_stamps_on:
                got = server.drain_hop_stamps()
                if got is not None:
                    stamps, lost = got
                    validate_s = sum(s[1] for s in stamps) / 1e9
                    ring_drops += int(lost)
            fold_calls = 0
            fold_busy_s = 0.0
            if hop_spans_on:
                got = wc_native.fold_spans_drain()
                if got is not None:
                    spans, lost = got
                    fold_calls = len(spans)
                    fold_busy_s = sum(e - s for s, e, _ in spans) / 1e9
                    ring_drops += int(lost)
            hop_an.observe_round(
                leader=int(group_id), round=rounds,
                frames=len(entries),
                stages={
                    "ingest_wait": max(
                        t_fold0 - round_t0 - validate_s, 0.0),
                    "validate": validate_s,
                    "fold": max(fold_s - fin_s, 0.0),
                    "finalize": fin_s,
                    "encode": enc_s,
                    "upstream_push": push_s,
                },
                round_s=max(t_done - round_t0, 0.0),
                drops=ring_drops,
                native=bool(hop_stamps_on or hop_spans_on),
                fold_calls=fold_calls, fold_busy_s=fold_busy_s)
            hop_an.flush()  # the root's tailer reads rows live
        rounds += 1
        up_seq += 1
        round_t0 = time.monotonic()

    try:
        # the first read blocks until the root's first publish (workers
        # wait on this leader's first downstream snapshot)
        _republish(timeout=float(cfg.get("open_timeout", 60.0)))
        while time.monotonic() < deadline:
            now = time.monotonic()
            if now >= next_tick:
                next_tick = now + float(cfg.get("tick_interval", 0.2))
                if server.timeseries_db is not None:
                    server.observability_tick()
                if topo_dir is not None:
                    # structural control: the SAME document the moved
                    # leaves repoint from tells this leader they left —
                    # without it every post-split round would stall a
                    # full degrade_after window waiting on a member
                    # that now pushes elsewhere
                    from pytorch_ps_mpi_tpu.control.topo import poll_topo

                    tdoc = poll_topo(topo_dir, topo_state)
                    if tdoc is not None:
                        for w_s, a in (tdoc.get("assign") or {}).items():
                            try:
                                wi = int(w_s)
                            except (TypeError, ValueError):
                                continue
                            if wi not in group:
                                continue
                            if a == addr:
                                departed.discard(wi)  # merged back
                            else:
                                departed.add(wi)
            if now >= next_read:
                next_read = now + float(kw["read_poll_s"])
                _republish()
            if upstream_down:
                # upstream gone: anything still queued is positively
                # lost (logged), then exit cleanly — the supervisor
                # owns the decision to restart the tree
                for w in group:
                    for _, meta, _ in pending[w]:
                        log.row({"kind": "leader_consume", "lost": True,
                                 "reason": "upstream_lost",
                                 "worker": int(meta.get("worker", w)),
                                 "step": int(meta.get("step", 0)),
                                 "seq": int(meta.get("seq", 0))})
                log.row({"kind": "upstream_lost", "t": time.time()})
                break
            if _pump_ingest():
                continue
            # round bookkeeping: deterministic crash hook first — it
            # fires "mid-fold": pushes are consumed (acked, queued) but
            # the round has not gone upstream, so they are positively
            # LOST and logged as such (the accounting smoke's case)
            if (crash_at is not None and rounds >= int(crash_at)
                    and any(pending[w] for w in group)):
                for w in group:
                    for payload, meta, _ in pending[w]:
                        log.row({"kind": "leader_consume", "lost": True,
                                 "worker": int(meta.get("worker", w)),
                                 "step": int(meta.get("step", 0)),
                                 "seq": int(meta.get("seq", 0))})
                log.close()
                os._exit(77)  # resilience.faults.CRASH_EXIT_CODE
            # a departed (reassigned-away) member stops gating rounds
            # the moment the topo document says so, but anything it
            # already pushed here still folds — one payload per round,
            # exactly like a live member, until its queue drains
            active = [w for w in group if w not in dead
                      and (w not in departed or pending[w])]
            if active and all(pending[w] for w in active):
                _hop_push(active)
                continue
            waited = time.monotonic() - round_t0
            queued = [w for w in group if pending[w]]
            if queued and waited > float(kw["degrade_after"]):
                _mark_dead()
                active = [w for w in group if w not in dead
                          and (w not in departed or pending[w])]
                if active and all(pending[w] for w in active):
                    _hop_push(active)
                    continue
                if waited > float(kw["flush_after"]):
                    # partial fold: liveness beats completeness — the
                    # composed trailer keeps the weighting exact anyway
                    _hop_push(queued)
                    continue
            if not queued:
                round_t0 = time.monotonic()  # no round in progress
                # idle-exit: every member that ever connected is gone
                # again. Members NEVER seen don't count as gone — they
                # may still be paying the minutes-long jax-import
                # startup skew, and a clean (rc 0) exit here would
                # never be respawned, stranding them at connect — so a
                # partially-seen group holds the leader open until the
                # startup grace expires.
                up = time.monotonic() - t_start
                seen = [w for w in group if w in server.last_seen]
                if can_connect:
                    gone = bool(seen) and all(
                        not server.connected(w) for w in seen)
                else:
                    # shm has no death signal: silence is the only one
                    silent = server.stragglers(float(kw["idle_exit_s"]))
                    gone = bool(seen) and all(w in silent for w in seen)
                all_arrived = len(seen) == len(group)
                if (seen and gone
                        and (all_arrived
                             or up > float(kw["startup_grace"]))
                        and (time.monotonic() - last_activity
                             > float(kw["idle_exit_s"]))):
                    break
                if not seen and up > float(kw["startup_grace"]):
                    break
            time.sleep(0.0005)
    finally:
        log.close()
        for c in conns:
            c.close()
        server.close()
    return int(state["upstream_pushes"])


# ---------------------------------------------------------------------------
# the worker-side tree connection (leader primary, root fallback)
# ---------------------------------------------------------------------------

class TreeWorkerConn:
    """A worker's transport in a tree job: push to the group leader;
    when the leader dies, fall back to pushing DIRECTLY to the root
    (compressed with the upstream codec, composing itself in the
    lineage trailer) and periodically probe the leader's pinned address
    to rejoin. Presents the worker surface ``worker_main`` expects
    (``read_params`` / ``push_grad`` / ``wire`` / ``close`` plus
    ``retries``/``reconnects`` counters)."""

    _TRANSPORT_ERRORS = (TimeoutError, RuntimeError, OSError)

    def __init__(self, worker_id: int, template: PyTree,
                 cfg: Dict[str, Any]):
        self.worker_id = int(worker_id)
        self.template = template
        self.cfg = cfg
        self.kw = _leader_knobs(cfg)
        self.leader_addr = cfg["tree_leader"]
        # fallback is single-root only: a sharded tree's recovery path
        # is the leader respawn (a leaf cannot slice its own pushes)
        self.root_addr = cfg.get("tree_fallback")
        self.slots = int(cfg.get("tree_slots", 1) or 1)
        self.retries = 0
        self.reconnects = 0
        self.fallback_pushes = 0
        self._mode = "leader"
        self._leader = None
        self._root = None
        self._pushes_since_fallback = 0
        self._tamper = None
        self._connect_leader(
            timeout=float(cfg.get("open_timeout", 60.0)), initial=True)

    # -- plumbing ---------------------------------------------------------
    @property
    def wire(self):
        w = self._leader if self._mode == "leader" else self._root
        return getattr(w, "wire", None)

    def set_tamper(self, fn) -> None:
        self._tamper = fn
        w = self._leader if self._mode == "leader" else self._root
        if w is not None:
            w._tamper = fn

    def renegotiate(self, code, bucket_mb: float = 0.0) -> bool:
        """Decline controller wire renegotiation: a tree leaf's group
        codec (and the root's trailer-bearing upstream wire) is the
        tree topology's own agreement — the leader re-encodes the hop,
        so swapping the leaf wire unilaterally would split the group's
        fold. The leaf keeps its epoch; the root consumes it until the
        old epoch retires (the controller disables the codec rule in
        tree mode for exactly this reason)."""
        return False

    def repoint(self, addr: str) -> bool:
        """Structural re-parent (controller group split/merge): switch
        this leaf's leader to ``addr`` — the control-topo.json poll's
        actuation.  Idempotent when already attached there.  On connect
        failure it takes the STANDARD failover path (root fallback /
        pinned-address retry) instead of returning with a half-open
        state: ``AttributeError`` on a ``None`` leader is not in
        ``_TRANSPORT_ERRORS``, so leaving ``_mode == "leader"`` with no
        connection would crash the next read.  The rejoin probe — now
        aimed at the NEW pinned address — retries from fallback."""
        addr = str(addr)
        if addr == self.leader_addr and self._mode == "leader" \
                and self._leader is not None:
            return True
        self.leader_addr = addr
        old, self._leader = self._leader, None
        if old is not None:
            try:
                old.close()
            except Exception:
                pass
        if self._connect_leader(timeout=float(self.kw["probe_timeout"])):
            self.reconnects += 1
            return True
        self._failover()
        return False

    def _connect_leader(self, timeout: float, initial: bool = False) -> bool:
        from pytorch_ps_mpi_tpu.parallel.dcn import ShmPSWorker
        from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSWorker

        try:
            if self.leader_addr.startswith("shm:"):
                w = ShmPSWorker(self.leader_addr[4:], self.worker_id,
                                self.template, timeout=timeout,
                                code=_group_codec(self.kw),
                                seed=int(self.cfg.get("seed", 0)),
                                frame=True)
            else:
                host, port = self.leader_addr.rsplit(":", 1)
                w = TcpPSWorker(host, int(port), self.worker_id,
                                self.template, timeout=timeout,
                                code=_group_codec(self.kw),
                                seed=int(self.cfg.get("seed", 0)),
                                frame=True)
        except self._TRANSPORT_ERRORS:
            if initial:
                if self.root_addr is None:
                    raise
                # leader not up (crashed before this worker started):
                # begin life on the fallback path; the periodic probe
                # rejoins the leader once the supervisor respawns it
                self.reconnects += 1
                self._mode = "root"
                self._connect_root()
            return False
        if self._leader is not None:
            try:
                self._leader.close()
            except Exception:
                pass
        self._leader = w
        self._leader._tamper = self._tamper
        self._mode = "leader"
        self._pushes_since_fallback = 0
        if self._root is not None:
            # drop the fallback socket on rejoin: an open root
            # connection would keep this worker in the root barrier's
            # membership forever (TCP liveness is positive there)
            try:
                self._root.close()
            except Exception:
                pass
            self._root = None
        return True

    def _connect_root(self):
        from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSWorker

        if self.root_addr is None:
            raise RuntimeError(
                "group leader unreachable and no tree_fallback root is "
                "configured (sharded tree) — waiting on leader respawn")
        if self._root is None:
            host, port = self.root_addr.rsplit(":", 1)
            self._root = TcpPSWorker(
                host, int(port), self.worker_id, self.template,
                code=_upstream_codec(self.cfg),
                timeout=float(self.cfg.get("open_timeout", 60.0)),
                bucket_mb=float(self.cfg.get("bucket_mb", 0.0)),
                frame=True, tree_slots=self.slots,
                seed=int(self.cfg.get("seed", 0)))
            self._root._tamper = self._tamper
        return self._root

    def _failover(self) -> None:
        """Leader unreachable: route around it (single root) or block-
        retry the pinned leader address until its respawn (sharded tree
        — a leaf cannot slice its own pushes across shards)."""
        self.reconnects += 1
        self._pushes_since_fallback = 0
        if self._leader is not None:
            try:
                self._leader.close()
            except Exception:
                pass
            self._leader = None
        if self.root_addr is None:
            deadline = time.time() + float(self.cfg.get("open_timeout",
                                                        60.0))
            while time.time() < deadline:
                if self._connect_leader(
                        timeout=float(self.kw["probe_timeout"])):
                    return
                time.sleep(0.5)
            raise TimeoutError(
                "group leader unreachable, no tree_fallback configured, "
                "and the leader never came back within open_timeout")
        self._mode = "root"
        self._connect_root()

    # -- worker surface ---------------------------------------------------
    def read_params(self, timeout: float = 30.0) -> Tuple[PyTree, int]:
        if self._mode == "leader":
            try:
                return self._leader.read_params(timeout=timeout)
            except self._TRANSPORT_ERRORS:
                self.retries += 1
                self._failover()
            if self._mode == "leader":  # reconnected (leader respawn)
                return self._leader.read_params(timeout=timeout)
        return self._connect_root().read_params(timeout=timeout)

    def push_grad(self, grad: PyTree, version: int, timeout: float = 30.0,
                  lineage: Optional[Tuple[int, int]] = None) -> None:
        if self._mode == "root":
            self._pushes_since_fallback += 1
            if self._pushes_since_fallback >= int(self.kw["rejoin_every"]):
                # probe the (possibly respawned) leader on its pinned
                # address; on success the group rejoins the tree
                if self._connect_leader(
                        timeout=float(self.kw["probe_timeout"])):
                    # version domains differ (leader-local counter):
                    # re-read so this push is tagged in the new domain
                    try:
                        _, version = self._leader.read_params(
                            timeout=timeout)
                    except self._TRANSPORT_ERRORS:
                        self._failover()
                else:
                    self._pushes_since_fallback = 0
        if self._mode == "leader":
            try:
                self._leader.push_grad(grad, version, timeout=timeout,
                                       lineage=lineage)
                return
            except self._TRANSPORT_ERRORS:
                self.retries += 1
                self._failover()
            if self._mode == "leader":  # reconnected (leader respawn)
                self._leader.push_grad(grad, version, timeout=timeout,
                                       lineage=lineage)
                return
        # direct-to-root: re-read for a root-domain version tag (the
        # leader-local tag would be nonsense staleness), then push with
        # the worker's own trace ID composing itself in the trailer
        root = self._connect_root()
        try:
            _, v_root = root.read_params(timeout=timeout)
        except self._TRANSPORT_ERRORS:
            self.retries += 1
            v_root = int(version)
        root.push_grad(grad, v_root, timeout=timeout, lineage=lineage)
        self.fallback_pushes += 1
        self._pushes_since_fallback += 1

    def close(self) -> None:
        for w in (self._leader, self._root):
            if w is not None:
                try:
                    w.close()
                except Exception:
                    pass
        self._leader = self._root = None


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def spawn_leader(upstream: Sequence[str], group_id: int,
                 group: Sequence[int], cfg: Dict[str, Any], port: int = 0,
                 env: Optional[Dict[str, str]] = None):
    """Launch ``leader_main`` in a fresh OS process (host backend pinned
    like every other fleet process); the child prints a one-line hello
    with its group-facing address."""
    src = (
        "import json,sys\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from pytorch_ps_mpi_tpu.parallel.tree import leader_main\n"
        "up, gid, grp, cfg, port = (json.loads(sys.argv[1]),\n"
        "    int(sys.argv[2]), json.loads(sys.argv[3]),\n"
        "    json.loads(sys.argv[4]), int(sys.argv[5]))\n"
        "sys.exit(0 if leader_main(up, gid, grp, cfg, port) >= 0 else 1)\n"
    )
    e = dict(os.environ)
    e.update({"JAX_PLATFORMS": "cpu"})
    e.update(env or {})
    return subprocess.Popen(
        [sys.executable, "-c", src, json.dumps(list(upstream)),
         str(group_id), json.dumps([int(w) for w in group]),
         json.dumps(cfg), str(port)],
        env=e, stdout=subprocess.PIPE, text=True,
    )


def read_leader_hello(proc, timeout: float = 120.0) -> Dict[str, Any]:
    """Block until a spawned leader prints its hello line."""
    import select

    deadline = time.time() + timeout
    while time.time() < deadline:
        r, _, _ = select.select([proc.stdout], [], [], 0.5)
        if r:
            line = proc.stdout.readline()
            if line:
                return json.loads(line)
        if proc.poll() is not None:
            raise RuntimeError(f"leader exited early: {proc.returncode}")
    raise TimeoutError("leader never reported its address")


def run_tree(cfg: Dict[str, Any], *, total_pushes: Optional[int] = None,
             timeout: float = 300.0,
             worker_env: Optional[Dict[str, str]] = None,
             leader_env: Optional[Dict[str, str]] = None
             ) -> Tuple[PyTree, Dict[str, Any]]:
    """Spawn and drive a full aggregation tree: root PS (in-process
    ``serve()``), one leader per group, one worker process per worker.
    Returns the root's ``(params, metrics)`` with tree bookkeeping
    (leader respawns, per-leader exit codes, worker codes) merged in.

    The root's stop condition is composed-accounting based: with
    ``total_pushes`` (default: the fleet's total step count) the serve
    loop drains until every worker push is accounted — composed at the
    root or positively lost with a crashed leader — or the fleet exits.
    """
    from pytorch_ps_mpi_tpu.parallel.async_train import (
        join_workers,
        make_problem,
        serve,
        spawn_worker,
    )
    from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSServer

    cfg = dict(cfg)
    n_workers = int(cfg["n_workers"])
    group_size = int(cfg.get("group_size", 4))
    kw = _leader_knobs(cfg)
    groups = group_plan(n_workers, group_size)
    slots = tree_slot_capacity(n_workers, group_size)
    lids = [leader_wid(n_workers, g) for g in range(len(groups))]
    cfg.update(tree=True, tree_slots=slots, tree_members=lids)

    code = _upstream_codec(cfg)
    if code is None:
        raise ValueError("run_tree needs cfg['codec'] (the compressed "
                         "DCN hop); use 'identity' to ship raw bytes")
    _, params0, _, _ = make_problem(cfg)
    # structural control needs spare wid headroom: each group replan
    # promotes one NEW leader wid, up to replan_max concurrent splits
    spare_wids = (int((cfg.get("control_kw") or {}).get("replan_max", 1))
                  if cfg.get("topo_actions") else 0)
    root = TcpPSServer(0, num_workers=n_workers + len(groups) + spare_wids,
                       template=params0,
                       max_staleness=int(cfg.get("max_staleness", 4)),
                       code=code, bucket_mb=float(cfg.get("bucket_mb", 0.0)),
                       frame=True, tree_slots=slots)
    root_addr = f"127.0.0.1:{root.port}"
    cfg["tree_fallback"] = root_addr

    leaders: List[Any] = []
    leader_ports: List[int] = []
    leader_addrs: List[str] = []
    respawns = [0] * len(groups)
    workers: List[Any] = []
    try:
        for g, grp in enumerate(groups):
            p = spawn_leader([root_addr], g, grp, cfg, env=leader_env)
            hello = read_leader_hello(p)
            leaders.append(p)
            leader_addrs.append(hello["addr"])
            leader_ports.append(
                0 if hello["addr"].startswith("shm:")
                else int(hello["addr"].rsplit(":", 1)[1]))
        for g, grp in enumerate(groups):
            for w in grp:
                wcfg = dict(cfg)
                wcfg["tree_leader"] = leader_addrs[g]
                workers.append(spawn_worker(root_addr, w, wcfg,
                                            env=worker_env))

        # structural control (cfg["topo_actions"]): the actuator owns
        # group split/merge through THESE supervision lists, so a
        # promoted leader is pinned-port respawned like a boot one;
        # the hop tailer feeds the leaders' lineage rows to the live
        # anatomy advisor (the engine's hot_group input)
        actuator = None
        tailer = None
        hop_tailer = None
        # hop anatomy at the root: the leaders WRITE hop-leaderN.jsonl;
        # this tailer replays their rows into the root's own HopAnatomy
        # (armed by serve()'s arm_observability) — the fleet scoreboard
        # the /health hop section, ps_top and the topo controller read
        if cfg.get("hop_anatomy"):
            from pytorch_ps_mpi_tpu.control.topo import HopTailer

            hop_dir = cfg.get("lineage_dir") or cfg.get("telemetry_dir")
            if hop_dir:
                hop_tailer = HopTailer(
                    hop_dir,
                    lambda row: (root.hop_anatomy.observe_row(row)
                                 if getattr(root, "hop_anatomy", None)
                                 is not None else None),
                    pattern="hop-*.jsonl")
        if cfg.get("topo_actions"):
            from pytorch_ps_mpi_tpu.control.topo import (
                HopTailer,
                TreeTopoActuator,
            )

            actuator = TreeTopoActuator(
                cfg=cfg, groups=groups, leaders=leaders,
                leader_ports=leader_ports, leader_addrs=leader_addrs,
                respawns=respawns, root_addr=root_addr,
                leader_env=leader_env)
            root.topo_actuator = actuator
            hop_dir = cfg.get("lineage_dir") or cfg.get("telemetry_dir")
            if hop_dir:
                tailer = HopTailer(
                    hop_dir,
                    lambda row: (root.anatomy.observe_hop(row)
                                 if getattr(root, "anatomy", None)
                                 is not None else None))
            root.topo_state = {
                "groups": len(groups), "leader_respawns": 0,
                "hot_churn_group": -1,
            }

        def on_tick():
            # leader supervision: a crashed leader is respawned on its
            # PINNED port so fallen-back workers can rejoin it. The
            # hello is NOT awaited — this runs on the serve thread, and
            # the pinned port makes the address already known.
            for g, p in enumerate(leaders):
                rc = p.poll()
                if rc is not None and rc != 0 and (
                        respawns[g] < int(kw["max_respawns"])):
                    respawns[g] += 1
                    # injected crash hooks are one-shot: the respawned
                    # generation must come back healthy (same rule as
                    # the chaos supervisor's crash-fault marking)
                    rcfg = dict(cfg)
                    lkw = dict(rcfg.get("leader_kw") or {})
                    lkw.pop("crash_at_round", None)
                    rcfg["leader_kw"] = lkw
                    leaders[g] = spawn_leader(
                        [root_addr], g, groups[g], rcfg,
                        port=leader_ports[g], env=leader_env)
            if actuator is not None:
                actuator.pump()  # non-blocking: reap split-leader hello
                root.topo_state = {
                    "groups": actuator.active_groups,
                    "leader_respawns": max(respawns) if respawns else 0,
                    "hot_churn_group": (
                        max(range(len(respawns)), key=respawns.__getitem__)
                        if respawns and max(respawns) > 0 else -1),
                }
            if tailer is not None:
                tailer.poll()
            if hop_tailer is not None:
                hop_tailer.poll()

        def stop_when():
            if total_pushes is not None and root.tree_composed >= total_pushes:
                return True
            return (all(p.poll() is not None for p in workers)
                    and all(p.poll() is not None for p in leaders))

        params, m = serve(
            root, cfg, total_grads=10 ** 9, timeout=timeout,
            sync_barrier=not cfg.get("tree_async", False),
            on_tick=on_tick, stop_when=stop_when,
        )
        worker_codes = join_workers(workers, timeout=60.0)
        leader_codes = join_workers(leaders, timeout=60.0)
        m["tree"] = {
            "groups": [list(g) for g in groups],
            "leader_wids": [leader_wid(n_workers, g)
                            for g in range(len(groups))],
            "tree_slots": slots,
            "leader_respawns": sum(respawns),
            "leader_codes": leader_codes,
            "worker_codes": worker_codes,
        }
        if actuator is not None:
            m["tree"]["topo_events"] = list(actuator.events)
        return params, m
    finally:
        for p in workers + leaders:
            if p.poll() is None:
                p.terminate()
        root.close()
