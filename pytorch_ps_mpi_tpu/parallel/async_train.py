"""Async PS training with REAL jitted compute in every process.

The full AsySG-InCon stack the reference ran — every rank doing actual
backprop, gradients shipped through the wire, a PS applying them in
arrival order (reference ``README.md:61-81`` pseudo-code; hook/pool
overlap ``ps.py:65-66,98-101``) — realized end-to-end across OS
processes:

  worker process:  read latest params (inconsistent read, seqlock)
                   → jitted ``value_and_grad`` of a flax model on device
                   → codec ``encode`` (jitted, CodecWire)
                   → payload BYTES into the shm mailbox
  server process:  poll mailboxes in arrival order
                   → codec ``decode`` (jitted)
                   → jitted fused ``sgd_update``/``adam_update``
                   → publish new snapshot (version += 1)

No gradient anywhere is computed outside ``jax.jit``. Staleness is
measured against publish versions and bounded by the server
(``max_staleness`` drops, ``stale_drops`` counter); a deliberately slow
worker exercises both the nontrivial staleness histogram and the drops.

Two serve disciplines, for the async-vs-sync wall-clock comparison the
algorithm exists for (Lian et al. 2015, arXiv:1506.08272):

- ``serve(..., sync_barrier=False)`` — AsySG: apply each gradient the
  moment it arrives. Throughput tracks the FAST workers.
- ``serve(..., sync_barrier=True)``  — synchronous PS oracle: collect one
  gradient from EVERY worker per round, apply the batch, publish once.
  Throughput collapses to the slowest worker (the straggler effect the
  reference's two-phase protocol fought, ``mpi_comms.py:190-191``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from pytorch_ps_mpi_tpu import telemetry

PyTree = Any

# update/wait latency buckets (seconds): sub-ms jitted updates through
# multi-second straggler waits
_LATENCY_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
                    5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _telemetry_from_cfg(cfg: Dict[str, Any], worker: Any):
    """The zero-cost-when-disabled switch: ``cfg["telemetry_dir"]``
    enables the process-global FlightRecorder (server process AND every
    spawned worker — cfg rides the spawn's JSON argv, so one flag arms
    the whole fleet). Returns the active recorder or None."""
    rec = telemetry.get_recorder()
    if rec is None and cfg.get("telemetry_dir"):
        rec = telemetry.configure(
            capacity=int(cfg.get("telemetry_capacity", 65536)), worker=worker
        )
    return rec


def _dump_recorder(cfg: Dict[str, Any], rec, filename: str) -> Optional[str]:
    tdir = cfg.get("telemetry_dir")
    if rec is None or not tdir:
        return None
    os.makedirs(tdir, exist_ok=True)
    return rec.dump_jsonl(os.path.join(tdir, filename))


def _model_by_name(name: str, **kw):
    if name == "mlp":
        from pytorch_ps_mpi_tpu.models import MLP

        return MLP(features=tuple(kw.get("features", (32, 8))))
    if name == "resnet18":
        from pytorch_ps_mpi_tpu.models import ResNet18

        return ResNet18(num_classes=kw.get("num_classes", 10),
                        small_inputs=True)
    if name == "resnet50":
        from pytorch_ps_mpi_tpu.models import ResNet50

        return ResNet50(num_classes=kw.get("num_classes", 10),
                        small_inputs=True)
    if name == "gpt":
        from pytorch_ps_mpi_tpu.models import GPTLM, gpt_tiny

        # forward EVERY config knob (remat, attention, dtype, ...);
        # only the sizing defaults are overridden for fleet-test scale
        return GPTLM(gpt_tiny(**{
            "vocab_size": 256, "hidden_size": 64, "num_layers": 2,
            "num_heads": 4, "intermediate_size": 128, "max_position": 64,
            **kw,
        }))
    raise ValueError(f"unknown model {name!r}")


def make_problem(cfg: Dict[str, Any]):
    """(model, params0, batch_fn, loss_fn) deterministically from ``cfg``
    — every process (server and workers) rebuilds the same problem from
    the same dict, the rank-parameterized-oracle pattern of the
    reference's tests (SURVEY §4) applied to a train job."""
    import jax
    import jax.numpy as jnp

    model = _model_by_name(cfg["model"], **cfg.get("model_kw", {}))
    in_shape = tuple(cfg.get("in_shape", (8,)))
    batch = int(cfg.get("batch", 32))
    k = jax.random.key(int(cfg.get("seed", 0)))
    kp, kx, kw = jax.random.split(k, 3)
    if cfg["model"] != "gpt":  # token models init on int inputs below
        x0 = jnp.zeros((1,) + in_shape, jnp.float32)
        params0 = model.init(kp, x0)

    n_out = int(cfg.get("model_kw", {}).get("num_classes", 0)) or (
        tuple(cfg.get("model_kw", {}).get("features", (32, 8)))[-1]
        if cfg["model"] == "mlp" else 10
    )

    if cfg["model"] == "gpt":
        # causal LM on a fixed bigram Markov chain: the TABLE is built
        # once from cfg['seed'] (every process sees the same language);
        # sampling streams derive per (worker, step) through a
        # SeedSequence, which cannot collide the way linear seed
        # arithmetic (1000*worker + step) did at step >= 1000
        from pytorch_ps_mpi_tpu.data import markov_table, sample_markov
        from pytorch_ps_mpi_tpu.models import causal_lm_loss

        vocab = model.cfg.vocab_size
        seq = int(cfg.get("seq_len", 32))
        if seq > model.cfg.max_position:
            raise ValueError(
                f"seq_len={seq} exceeds the model's max_position="
                f"{model.cfg.max_position}: positions past it would be "
                "silently clamped to one embedding"
            )
        base_seed = int(cfg.get("seed", 0))
        cum = markov_table(vocab, base_seed)
        params0 = model.init(kp, jnp.zeros((1, seq), jnp.int32))

        def batch_fn(step: int, worker: int):
            ss = np.random.SeedSequence([base_seed, worker, step])
            rng = np.random.RandomState(ss.generate_state(1)[0])
            return jnp.asarray(sample_markov(cum, batch, seq, rng))

        def loss_fn(params, tokens):
            return causal_lm_loss(model.apply(params, tokens), tokens)

        return model, params0, batch_fn, loss_fn

    if cfg["model"] == "mlp":
        # regression against a fixed random linear teacher: smooth convex-
        # ish loss whose value cleanly separates trained from untrained
        d_in = int(np.prod(in_shape))
        w_true = jax.random.normal(kw, (d_in, n_out)) / d_in ** 0.5

        def batch_fn(step: int, worker: int):
            kk = jax.random.fold_in(jax.random.fold_in(kx, worker), step)
            x = jax.random.normal(kk, (batch,) + in_shape)
            y = x.reshape(batch, -1) @ w_true
            return x, y

        def loss_fn(params, b):
            x, y = b
            pred = model.apply(params, x)
            return jnp.mean((pred - y) ** 2)
    else:
        def batch_fn(step: int, worker: int):
            kk = jax.random.fold_in(jax.random.fold_in(kx, worker), step)
            x = jax.random.normal(kk, (batch,) + in_shape)
            y = jax.random.randint(jax.random.fold_in(kk, 1), (batch,), 0, n_out)
            return x, y

        def loss_fn(params, b):
            x, y = b
            logits = model.apply(params, x)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    return model, params0, batch_fn, loss_fn


def worker_cfg(cfg: Dict[str, Any], worker_id: int) -> Tuple[float, int]:
    """Per-worker (slow_ms, steps) from the shared job config — one
    parser for every worker body (shm, tcp, sharded)."""
    slow_ms = float(cfg.get("slow_ms", {}).get(str(worker_id), 0.0)) if isinstance(
        cfg.get("slow_ms"), dict) else 0.0
    steps = int(cfg.get("worker_steps", {}).get(str(worker_id),
                cfg.get("steps", 10))) if isinstance(
        cfg.get("worker_steps"), dict) else int(cfg.get("steps", 10))
    return slow_ms, steps


def worker_main(name: str, worker_id: int, cfg: Dict[str, Any]) -> int:
    """Worker process body: jitted fwd/bwd → encode → push bytes.
    Returns the number of gradients pushed.

    ``cfg["transport"]`` selects the wire: ``"shm"`` (default, co-hosted
    processes, ``dcn.py``) or ``"tcp"`` (cross-host DCN role, ``tcp.py``
    — ``name`` then carries ``"host:port"``). The compute path is
    identical either way: no gradient is ever produced outside jit."""
    import jax

    code = None
    if cfg.get("codec"):
        from pytorch_ps_mpi_tpu.codecs import get_codec

        code = get_codec(cfg["codec"], **cfg.get("codec_kw", {}))

    _, params0, batch_fn, loss_fn = make_problem(cfg)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))  # ONLY grad source

    slow_ms, steps = worker_cfg(cfg, worker_id)

    if cfg.get("transport", "shm") == "tcp":
        from pytorch_ps_mpi_tpu.parallel.tcp import TcpPSWorker

        host, port = name.rsplit(":", 1)
        w = TcpPSWorker(host, int(port), worker_id, params0, code=code,
                        timeout=float(cfg.get("open_timeout", 60.0)),
                        bucket_mb=float(cfg.get("bucket_mb", 0.0)))
    else:
        from pytorch_ps_mpi_tpu.parallel.dcn import ShmPSWorker

        w = ShmPSWorker(name, worker_id, params0, code=code,
                        timeout=float(cfg.get("open_timeout", 60.0)),
                        bucket_mb=float(cfg.get("bucket_mb", 0.0)))
    rec = _telemetry_from_cfg(cfg, worker=worker_id)
    pushed = 0
    try:
        for step in range(steps):
            if rec is None:
                params, version = w.read_params()
                loss, grads = grad_fn(params, batch_fn(step, worker_id))
                jax.block_until_ready(grads)
                if slow_ms:
                    time.sleep(slow_ms / 1e3)  # deliberate straggler
                w.push_grad(grads, version,
                            timeout=float(cfg.get("push_timeout", 60.0)))
            else:
                with rec.span("worker.read_params", step=step):
                    params, version = w.read_params()
                with rec.span("worker.grad", step=step, version=version):
                    loss, grads = grad_fn(params, batch_fn(step, worker_id))
                    jax.block_until_ready(grads)
                if slow_ms:
                    with rec.span("worker.straggle", step=step):
                        time.sleep(slow_ms / 1e3)  # deliberate straggler
                with rec.span("worker.push_grad", step=step, version=version):
                    w.push_grad(grads, version,
                                timeout=float(cfg.get("push_timeout", 60.0)))
            pushed += 1
    finally:
        w.close()
        _dump_recorder(cfg, rec, f"worker-{worker_id}.jsonl")
    return pushed


def _restore_ps_checkpoint(ckpt, params, state, checkpoint_every: int):
    """Restore the latest PS snapshot; returns (params, opt_state,
    applied_total, resumed_version). The resumed version is jumped past
    anything a surviving worker could have read in the crash window (the
    SAVED run's cadence bounds it — see serve's docstring); the restored
    step is marked already-saved so it is never re-saved (Orbax raises
    StepAlreadyExistsError). Shared by the single-server serve loop and
    the sharded shard-server loop."""
    template = {"params": params, "opt_state": state,
                "version": 0, "applied_total": 0, "checkpoint_every": 0}
    restored = ckpt.restore(template)
    applied_before = int(restored["applied_total"])
    ckpt._last_ps_step = applied_before
    jump = max(int(restored["checkpoint_every"]), int(checkpoint_every), 0)
    version = int(restored["version"]) + jump + 1
    return restored["params"], restored["opt_state"], applied_before, version


class _PSCheckpointCadence:
    """The save half of PS checkpointing, shared by the single-server
    serve loop and the sharded shard-server loop so the crash-window
    guarantees can never diverge between them: save when the APPLIED
    COUNT has advanced by ``checkpoint_every`` since the last save (not
    on divisibility — sync_barrier mode advances ``applied`` by
    n_workers per round and would hit an exact multiple only every lcm),
    plus one unconditional final save at loop exit."""

    def __init__(self, ckpt, checkpoint_every: int, applied_before: int):
        self.ckpt = ckpt
        self.every = int(checkpoint_every)
        self.last_saved = int(applied_before)

    def _save(self, params, state, server, applied_total: int) -> None:
        if getattr(self.ckpt, "_last_ps_step", None) == applied_total:
            return  # final save coinciding with a periodic one
        import jax

        self.ckpt.save(applied_total, {
            "params": jax.tree.map(np.asarray, params),
            "opt_state": jax.tree.map(np.asarray, state),
            "version": server.version,
            "applied_total": applied_total,
            # the SAVING run's cadence bounds how far past this snapshot
            # the server can have published before a crash — the resume
            # jump must use it, not the restarting run's (possibly
            # smaller) one
            "checkpoint_every": self.every,
        })
        self.ckpt._last_ps_step = applied_total

    def maybe_save(self, params, state, server, applied_total: int) -> None:
        if self.every and applied_total - self.last_saved >= self.every:
            self._save(params, state, server, applied_total)
            self.last_saved = applied_total

    def final_save(self, params, state, server, applied_total: int) -> None:
        self._save(params, state, server, applied_total)


def serve(
    server,
    cfg: Dict[str, Any],
    total_grads: int,
    *,
    sync_barrier: bool = False,
    total_received: Optional[int] = None,
    timeout: float = 300.0,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    resume: bool = False,
) -> Tuple[PyTree, Dict[str, float]]:
    """Server body: poll → (decode) → jitted optimizer update → publish.

    ``total_grads`` counts APPLIED gradients (stale drops don't count).
    When ``total_received`` is given, the loop instead runs until that
    many gradients were CONSUMED (applied + stale-dropped) — the right
    stop condition when workers push a fixed count and some pushes are
    expected to be dropped (otherwise their final blocked pushes would
    time out). Returns (final params, metrics incl. steps/sec and final
    loss on a held-out evaluation batch).

    Checkpointing closes the SERVER side of the failure story (workers
    are already elastic): with ``checkpoint_dir`` set, the full PS state
    (params, optimizer state, publish version, applied count) is saved
    every ``checkpoint_every`` applied gradients; a replacement server
    started with ``resume=True`` restores the latest snapshot and keeps
    the version counter monotonic, so training continues where the dead
    server left off — workers just reconnect and read the next snapshot
    (the reference's MPI job had no analog: a rank-0 death ended the
    job, SURVEY §5.4/§5.3). ``applied``/counters restart per serve call;
    the restored ``applied_total`` rides in the metrics.

    Telemetry (``cfg`` keys, so one dict arms server and workers):

    - ``telemetry_dir``: enables the FlightRecorder here AND in every
      spawned worker (cfg rides the spawn argv); each process dumps its
      JSONL into the directory at exit (``server.jsonl``,
      ``worker-N.jsonl``) and the path rides the returned metrics as
      ``telemetry_jsonl``. Disabled (the default), the loop pays one
      None-check per gradient.
    - ``metrics_port``: start the Prometheus ``/metrics`` HTTP endpoint
      on a server that can serve one (TCP transport; 0 = auto-assign).
      The bound port is returned as ``metrics_port`` in the metrics and
      the endpoint stays up until ``server.close()``. Either way the
      serve loop feeds step-latency and straggler-wait histograms into
      ``server.scrape_registry()`` — the shm transport scrapes the same
      registry via ``server.prometheus_text()``.
    """
    import jax

    from pytorch_ps_mpi_tpu.optim import OPTIMIZERS

    _, params, batch_fn, loss_fn = make_problem(cfg)
    hyper_cls, init_state, update_fn = OPTIMIZERS[cfg.get("optim", "sgd")]
    h = hyper_cls(**cfg.get("hyper", {"lr": 0.05}))
    state = init_state(params)
    update = jax.jit(lambda p, g, s: update_fn(p, g, s, h))
    eval_loss = jax.jit(loss_fn)
    eval_batch = batch_fn(10**6, 10**6)  # never used by any worker

    ckpt = None
    applied_before = 0
    if resume and not checkpoint_dir:
        raise ValueError("resume=True requires checkpoint_dir")
    if checkpoint_dir:
        from pytorch_ps_mpi_tpu.utils.checkpoint import CheckpointManager

        ckpt = CheckpointManager(checkpoint_dir)
        if resume:
            params, state, applied_before, server.version = (
                _restore_ps_checkpoint(ckpt, params, state, checkpoint_every)
            )

    rec = _telemetry_from_cfg(cfg, worker="server")
    reg = server.scrape_registry()
    h_update = reg.histogram(
        "ps_update_seconds", _LATENCY_BUCKETS,
        "optimizer update + publish wall per applied round",
    )
    h_wait = reg.histogram(
        "ps_poll_wait_seconds", _LATENCY_BUCKETS,
        "idle poll time preceding each consumed gradient (straggler wait)",
    )
    g_applied = reg.gauge(
        "ps_applied_total", "gradients applied this serve call"
    )
    metrics_http_port = None
    if cfg.get("metrics_port") is not None and hasattr(
            server, "start_metrics_http"):
        metrics_http_port = server.start_metrics_http(
            int(cfg["metrics_port"])
        )
        print(f"prometheus /metrics on port {metrics_http_port}",
              flush=True)

    loss0 = float(eval_loss(params, eval_batch))
    server.publish(params)
    applied = 0
    cadence = (_PSCheckpointCadence(ckpt, checkpoint_every, applied_before)
               if ckpt else None)
    n_workers = server.num_workers
    # sync_barrier holds a FIFO per worker: the server pops mailboxes
    # eagerly (the single-slot mailbox never back-pressures a fast
    # worker), so a worker may deliver several gradients before a
    # straggler's first — queueing them, not overwriting, keeps the
    # oracle a true synchronous PS in which EVERY gradient enters exactly
    # one averaged round.
    import collections

    pending: Dict[int, Any] = collections.defaultdict(collections.deque)
    t0 = time.perf_counter()
    deadline = t0 + timeout

    def keep_going():
        if total_received is not None:
            return server.grads_received < total_received
        return applied < total_grads

    wait_t0 = time.perf_counter()
    while keep_going() and time.perf_counter() < deadline:
        item = server.poll_grad()
        if item is None:
            time.sleep(0.0005)
            continue
        wid, grad_version, grad = item
        h_wait.observe(time.perf_counter() - wait_t0)
        if rec is not None:
            rec.event("serve.grad", worker=wid,
                      staleness=max(0, server.version - grad_version),
                      step=applied, version=grad_version)
        if sync_barrier:
            # synchronous oracle: a round completes when every worker has
            # at least one queued gradient; one per worker is consumed
            pending[wid].append(grad)
            if sum(1 for q in pending.values() if q) < n_workers:
                wait_t0 = time.perf_counter()
                continue
            up_t0 = time.perf_counter()
            batch_grads = [pending[w].popleft() for w in range(n_workers)]
            summed = jax.tree.map(lambda *gs: sum(gs) / len(gs), *batch_grads)
            params, state = update(params, summed, state)
            applied += n_workers
        else:
            up_t0 = time.perf_counter()
            params, state = update(params, grad, state)
            applied += 1
        server.publish(jax.tree.map(np.asarray, params))
        up_dur = time.perf_counter() - up_t0
        h_update.observe(up_dur)
        g_applied.set(float(applied))
        if rec is not None:
            rec.event("serve.update", kind="span", ts=up_t0, dur=up_dur,
                      step=applied, version=server.version)
        if cadence:
            cadence.maybe_save(params, state, server, applied_before + applied)
        wait_t0 = time.perf_counter()
    wall = time.perf_counter() - t0
    if cadence:  # final state always captured, whatever the stop reason
        cadence.final_save(params, state, server, applied_before + applied)
    m = dict(server.metrics())
    m.update(
        applied=float(applied),
        applied_total=float(applied_before + applied),
        wall_s=wall,
        updates_per_sec=applied / wall if wall > 0 else 0.0,
        loss_initial=loss0,
        loss_final=float(eval_loss(params, eval_batch)),
        staleness_hist={int(k): int(v) for k, v in server.staleness_seen.items()},
    )
    if metrics_http_port is not None:
        m["metrics_port"] = metrics_http_port
    jsonl = _dump_recorder(cfg, rec, "server.jsonl")
    if jsonl is not None:
        m["telemetry_jsonl"] = jsonl
    return params, m


def spawn_worker(name: str, worker_id: int, cfg: Dict[str, Any],
                 env: Optional[Dict[str, str]] = None):
    """Launch ``worker_main`` in a fresh OS process (its own JAX runtime,
    pinned to the host backend so tests/benches never contend for the one
    tunneled TPU chip)."""
    import json
    import os
    import subprocess
    import sys

    src = (
        "import json,sys\n"
        # the axon TPU plugin ignores the JAX_PLATFORMS env var; the
        # config flag is the pin it respects (workers must never contend
        # for the one tunneled chip)
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from pytorch_ps_mpi_tpu.parallel.async_train import worker_main\n"
        "name, wid, cfg = sys.argv[1], int(sys.argv[2]), json.loads(sys.argv[3])\n"
        "sys.exit(0 if worker_main(name, wid, cfg) >= 0 else 1)\n"
    )
    e = dict(os.environ)
    e.update({"JAX_PLATFORMS": "cpu"})
    e.update(env or {})
    return subprocess.Popen(
        [sys.executable, "-c", src, name, str(worker_id), json.dumps(cfg)],
        env=e,
    )
